#!/usr/bin/env python
"""GPT decoder-LM composition smoke (tools/run_checks.sh, ISSUE 14).

The LM is the one workload that composes every expensive subsystem —
causal flash/blockwise attention, ring-attention sequence parallelism,
GPipe pipelining, ZeRO-1/2 weight-update sharding, the bf16
PrecisionPolicy, gradient accumulation — and this smoke gates the
composed configs on the repo's parity spine, all on a 4-device CPU mesh:

1. dp=4 x zero2 x accum=2        == dp=4 replicated x accum=2   BITWISE
2. dp=2 x sp=2(ring) x zero1     == dp=2 x sp=2 replicated      BITWISE
   (+ shardcheck statically proves the ring: SC008 collective-permute,
    and the sp-mesh zero contract adaptations hold)
3. dp=2 x sp=2 x zero2 x bf16    == dp=2 x sp=2 x bf16          BITWISE
   losses, fp32 master weights, finite trajectory
4. pp=2 GPipe (graph pipeline, M=1) == the SINGLE-REPLICA program
   BITWISE losses
5. every composed fp32 trajectory matches the single-replica program
   within tolerance (cross-mesh loss reductions reassociate — see
   PARITY.md "composition parity map" for what is bitwise vs carved)

Exit 0 = the full composition surface (dp x tp-or-sp x pp x zero2 x
bf16) trains and every gate above holds.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4").strip()

DEVICES = 4
STEPS = 3
SEQ = 8
BATCH = 8
TOL = 1e-4  # cross-mesh fp32 loss agreement (reassociation only)


def main() -> int:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", DEVICES)
    except AttributeError:
        pass
    if len(jax.devices()) < DEVICES:
        print(f"lm_smoke: FAIL need {DEVICES} cpu devices, "
              f"have {jax.devices()}")
        return 1

    from jax.sharding import Mesh

    from deeplearning4j_tpu.models.gpt import (
        char_lm_batches, char_vocab, gpt_tiny, synthetic_char_text,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel.mesh import MeshContext
    from deeplearning4j_tpu.parallel.pipeline import GraphPipelineTrainer
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    text = synthetic_char_text(6000, seed=1)
    cs = char_vocab(text)
    batches = char_lm_batches(text, SEQ, BATCH, charset=cs,
                              max_batches=STEPS)

    def build():
        conf = gpt_tiny(vocab_size=len(cs), seq_len=SEQ, seed=7)
        findings = conf.validate(batch_size=BATCH)
        if findings:
            raise AssertionError(f"gpt config not clean: {findings}")
        return ComputationGraph(conf).init()

    def train_pt(n_data, n_seq=1, wus=None, precision=None, accum=1):
        net = build()
        trainer = ParallelTrainer(
            net, MeshContext.create(n_data=n_data, n_model=1,
                                    n_seq=n_seq),
            gradient_accumulation=accum, weight_update_sharding=wus,
            precision=precision)
        losses = [np.float32(np.asarray(trainer.fit_batch(b)))
                  for b in batches]
        return net, trainer, losses

    def bitwise(name, a, b, na, nb, params=True):
        if any(x.tobytes() != y.tobytes() for x, y in zip(a, b)):
            print(f"lm_smoke: FAIL {name}: loss sequences differ\n"
                  f"  {[float(x) for x in a]}\n  {[float(y) for y in b]}")
            return False
        if params:
            pa = np.asarray(na.params_flat())
            pb = np.asarray(nb.params_flat())
            if pa.tobytes() != pb.tobytes():
                print(f"lm_smoke: FAIL {name}: params diverged bitwise")
                return False
        print(f"lm_smoke: {name}: bitwise OK")
        return True

    # single-replica reference program (plain graph fit)
    ref_net = build()
    ref = [np.float32(np.asarray(ref_net.fit_batch(b))) for b in batches]

    # 1. dp x zero2 x accum vs its replicated twin
    n_off, _, l_off = train_pt(4, accum=2)
    n_z2, _, l_z2 = train_pt(4, wus="zero2", accum=2)
    if not bitwise("dp4 x zero2 x ga2 == dp4 x replicated x ga2",
                   l_z2, l_off, n_z2, n_off):
        return 1

    # 2. dp x sp (ring attention) x zero1 vs its replicated twin
    n_sp, _, l_sp = train_pt(2, n_seq=2)
    n_spz, tr_spz, l_spz = train_pt(2, n_seq=2, wus="zero1")
    if not bitwise("dp2 x sp2 x zero1 == dp2 x sp2 x replicated",
                   l_spz, l_sp, n_spz, n_sp):
        return 1
    # static proof the ring formed (SC008) and the sp-mesh zero
    # contract holds (no SC001/SC003 regressions on this program)
    from deeplearning4j_tpu.analysis.findings import Severity
    findings = [f for f in tr_spz.shardcheck(batches[0])
                if f.severity != Severity.INFO]
    if findings:
        print("lm_smoke: FAIL shardcheck on the dp2 x sp2 x zero1 "
              "program:\n  " + "\n  ".join(str(f) for f in findings))
        return 1
    print("lm_smoke: shardcheck dp2 x sp2 x zero1: ring present, "
          "contracts clean")

    # 3. dp x sp x zero2 x bf16: bitwise losses vs the bf16 replicated
    # twin, fp32 masters, finite
    n_bf, _, l_bf = train_pt(2, n_seq=2, precision="bf16")
    n_bfz, _, l_bfz = train_pt(2, n_seq=2, wus="zero2", precision="bf16")
    if not all(np.isfinite(l_bfz)):
        print(f"lm_smoke: FAIL bf16 composed run non-finite: {l_bfz}")
        return 1
    if not bitwise("dp2 x sp2 x zero2 x bf16 == dp2 x sp2 x bf16 "
                   "(losses)", l_bfz, l_bf, n_bfz, n_bf, params=False):
        return 1
    np.testing.assert_allclose(  # master drift: last-ulp association
        np.asarray(n_bfz.params_flat()), np.asarray(n_bf.params_flat()),
        rtol=0, atol=1e-7, err_msg="bf16 master weights drifted past ulp")
    master_dtypes = {str(p.dtype)
                     for p in jax.tree_util.tree_leaves(n_bfz.params)}
    if master_dtypes != {"float32"}:
        print(f"lm_smoke: FAIL bf16 masters not fp32: {master_dtypes}")
        return 1
    print("lm_smoke: bf16 masters fp32, drift <= 1e-7")

    # 4. GPipe pipeline (graph stage partitioning at the residual-stream
    # cut points) vs the single-replica program — BITWISE losses
    pp_net = build()
    devs = np.array(jax.devices()[:2])
    pp_tr = GraphPipelineTrainer(pp_net, Mesh(devs.reshape(2), ("pp",)),
                                 n_microbatches=1)
    l_pp = [np.float32(np.asarray(pp_tr.fit_batch(b))) for b in batches]
    if not bitwise("pp2 GPipe (M=1) == single-replica program",
                   l_pp, ref, pp_net, ref_net, params=False):
        return 1
    np.testing.assert_allclose(
        np.asarray(pp_net.params_flat()), np.asarray(ref_net.params_flat()),
        rtol=0, atol=1e-6, err_msg="pipeline params drifted")

    # 5. cross-mesh tolerance: every fp32 composed trajectory tracks the
    # single-replica program (loss reductions reassociate across meshes)
    for name, ls in (("dp4-zero2-ga2", l_z2), ("dp2-sp2-zero1", l_spz)):
        err = max(abs(float(a) - float(b)) for a, b in zip(ls, ref))
        if err > TOL:
            print(f"lm_smoke: FAIL {name} vs single-replica: {err:.2e} "
                  f"> {TOL}")
            return 1
    print(f"lm_smoke: OK — {STEPS} steps; composed configs "
          "dp4xzero2xga2, dp2xsp2xzero1, dp2xsp2xzero2xbf16 bitwise vs "
          "their single-replica-state twins; pp2 GPipe bitwise vs the "
          "single-replica program; ring statically proven (SC008); "
          "bf16 masters fp32")
    return 0


if __name__ == "__main__":
    sys.exit(main())
