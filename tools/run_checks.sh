#!/usr/bin/env bash
# The repo's check entrypoint: lint gate + analyzer self-check + tier-1
# tests. Exits nonzero on ANY failure. This is what a PR must pass.
#
#   tools/run_checks.sh            # everything (tests take ~10 min)
#   tools/run_checks.sh --fast     # static checks only (seconds)

set -uo pipefail
cd "$(dirname "$0")/.."

fail=0

echo "== jaxlint (deeplearning4j_tpu) =="
python tools/jaxlint.py deeplearning4j_tpu || fail=1

echo "== jaxlint --self-check =="
python tools/jaxlint.py --self-check || fail=1

echo "== graphcheck --self-check =="
JAX_PLATFORMS=cpu python tools/graphcheck.py --self-check || fail=1

if [ "${1:-}" != "--fast" ]; then
    echo "== profiling smoke (trace export + metrics + cost analysis) =="
    JAX_PLATFORMS=cpu python tools/profiling_smoke.py || fail=1

    echo "== chaos smoke (NaN injection under skip_batch + resume) =="
    JAX_PLATFORMS=cpu python tools/chaos_smoke.py || fail=1

    echo "== serve smoke (burst shed + /readyz drain flip + clean drain + batching) =="
    JAX_PLATFORMS=cpu python tools/serve_smoke.py || fail=1

    echo "== serve+input bench smoke (batching + input-pipeline rungs, CPU) =="
    rm -f /tmp/_bench_smoke.jsonl
    JAX_PLATFORMS=cpu BENCH_SMOKE=1 BENCH_RUNGS=input,serve BENCH_CHILD=1 \
        python bench.py | tee /tmp/_bench_smoke.jsonl || fail=1
    # every rung record must carry the ISSUE-10 precision fields
    python - <<'PY' || fail=1
import json
recs = []
for line in open("/tmp/_bench_smoke.jsonl"):
    line = line.strip()
    if line.startswith("{"):
        recs.append(json.loads(line))
# failure/timeout records (_failure_record / _RungWatchdog) carry no
# precision fields by design — only successful rung records must
recs = [r for r in recs if not r.get("failed")]
assert recs, "bench smoke emitted no successful records"
missing = [r.get("metric") for r in recs
           if "compute_dtype" not in r or "params_dtype" not in r]
assert not missing, f"records missing compute_dtype/params_dtype: {missing}"
print(f"bench precision fields: {len(recs)} records OK")
PY

    echo "== zero1 smoke (dp=2 bitwise loss parity + sharded updater state) =="
    JAX_PLATFORMS=cpu python tools/zero1_smoke.py || fail=1

    echo "== zero2 smoke (dp=2 bitwise parity + gradient sharding + bf16 masters) =="
    JAX_PLATFORMS=cpu python tools/zero2_smoke.py || fail=1

    echo "== input smoke (pipeline vs sync: loss parity + lower stall) =="
    JAX_PLATFORMS=cpu python tools/input_smoke.py || fail=1

    echo "== elastic smoke (kill_host -> dp=1 resume, bitwise + /api/metrics) =="
    JAX_PLATFORMS=cpu python tools/elastic_smoke.py || fail=1

    echo "== tier-1 tests (ROADMAP.md) =="
    rm -f /tmp/_t1.log
    timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
        | tee /tmp/_t1.log
    rc=${PIPESTATUS[0]}
    echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
        | tr -cd . | wc -c)
    [ "$rc" -ne 0 ] && fail=1
fi

if [ "$fail" -eq 0 ]; then
    echo "run_checks: ALL CHECKS PASSED"
else
    echo "run_checks: FAILURES (see above)" >&2
fi
exit $fail
