#!/usr/bin/env bash
# The repo's check entrypoint: lint gates + analyzer self-checks + the
# shardcheck compiled-program contracts + smoke gates + tier-1 tests.
# Exits nonzero on ANY failure. This is what a PR must pass.
#
#   tools/run_checks.sh            # everything (tests take ~20 min)
#   tools/run_checks.sh --fast     # static checks only (seconds)
#
# Every stage is timed and the run ends with a summary table
# (stage -> pass/fail -> seconds) so the slowest gates stay visible and
# check-time regressions get noticed.

set -uo pipefail
cd "$(dirname "$0")/.."

fail=0
declare -a ST_NAME=() ST_RC=() ST_SEC=()

stage() {
    local name="$1"; shift
    echo "== $name =="
    local t0=$SECONDS
    "$@"
    local rc=$?
    ST_NAME+=("$name"); ST_RC+=("$rc"); ST_SEC+=($((SECONDS - t0)))
    [ "$rc" -ne 0 ] && fail=1
    return 0
}

bench_smoke() {
    rm -f /tmp/_bench_smoke.jsonl
    JAX_PLATFORMS=cpu BENCH_SMOKE=1 \
        BENCH_RUNGS=lenet,input,serve,lm,lm_serve,fleet \
        BENCH_AUTOTUNE=1 BENCH_CHILD=1 \
        python bench.py | tee /tmp/_bench_smoke.jsonl || return 1
    # every successful rung record must carry the ISSUE-10 precision
    # fields, the ISSUE-11 comm_bytes_hlo calibration field, and the
    # ISSUE-13 autotune fields; the autotuned lenet rung must land a
    # finite measured-vs-predicted calibration gap
    python - <<'PY'
import json, math
recs = []
for line in open("/tmp/_bench_smoke.jsonl"):
    line = line.strip()
    if line.startswith("{"):
        recs.append(json.loads(line))
# failure/timeout records (_failure_record / _RungWatchdog) carry no
# schema fields by design — only successful rung records must
recs = [r for r in recs if not r.get("failed")]
assert recs, "bench smoke emitted no successful records"
missing = [r.get("metric") for r in recs
           if "compute_dtype" not in r or "params_dtype" not in r]
assert not missing, f"records missing compute_dtype/params_dtype: {missing}"
missing = [r.get("metric") for r in recs if "comm_bytes_hlo" not in r]
assert not missing, f"records missing comm_bytes_hlo: {missing}"
missing = [r.get("metric") for r in recs
           if not {"autotuned", "predicted_step_s",
                   "measured_vs_predicted_gap"} <= set(r)]
assert not missing, f"records missing autotune fields: {missing}"
tuned = [r for r in recs if r.get("autotuned")]
assert tuned, "BENCH_AUTOTUNE=1 but no record ran autotuned"
bad = [r["metric"] for r in tuned
       if not (r.get("predicted_step_s") and r.get(
           "measured_vs_predicted_gap") is not None
           and math.isfinite(r["measured_vs_predicted_gap"]))]
assert not bad, f"autotuned records without a finite calibration gap: {bad}"
# ISSUE 14: the lm rung's record must carry the token-throughput schema
# with a finite analytic MFU
lm = [r for r in recs if r.get("rung") == "lm"]
assert lm, "no lm rung record emitted"
for r in lm:
    for fld in ("tokens_per_sec_per_chip", "seq_len", "analytic_mfu"):
        v = r.get(fld)
        assert v is not None and math.isfinite(float(v)), \
            f"lm record {fld} missing or non-finite: {v!r}"
# ISSUE 15: the lm_serve rung must carry the token-level serving
# schema (tokens/sec-at-SLO + TTFT p50/p99), run its timed wave with
# zero decode recompiles, and BEAT the whole-predict baseline on the
# same mixed-length workload
ls_ = [r for r in recs if r.get("rung") == "lm_serve"]
assert ls_, "no lm_serve rung record emitted"
for r in ls_:
    for fld in ("tokens_per_sec_at_slo", "ttft_p50_ms", "ttft_p99_ms",
                "whole_predict_tokens_per_sec", "vs_whole_predict",
                # ISSUE 20: block-paged KV pool + prefix-cache census
                "prefix_cache_hit_rate", "kv_pages_total",
                "kv_pages_shared"):
        v = r.get(fld)
        assert v is not None and math.isfinite(float(v)), \
            f"lm_serve record {fld} missing or non-finite: {v!r}"
    assert r["decode_recompiles_timed_wave"] == 0, \
        f"lm_serve timed wave recompiled: {r['decode_recompiles_timed_wave']}"
    assert r["vs_whole_predict"] > 1.0, \
        f"token-level serving did not beat whole-predict: {r['vs_whole_predict']}"
# ISSUE 18: the fleet rung must carry the multi-replica serving schema
# (aggregate rps-at-SLO + the single-server ratio measured on the same
# workload) with R >= 2 replicas and zero request errors.
# vs_single_server itself is not gated in smoke: R replicas share one
# CPU there, so the ratio only means something on real parallel hardware
fl = [r for r in recs if r.get("rung") == "fleet"]
assert fl, "no fleet rung record emitted"
for r in fl:
    for fld in ("value", "single_server_rps", "vs_single_server",
                "p50_ms", "p99_ms", "slo_attained"):
        v = r.get(fld)
        assert v is not None and math.isfinite(float(v)), \
            f"fleet record {fld} missing or non-finite: {v!r}"
    assert r.get("replicas", 0) >= 2, \
        f"fleet rung ran with {r.get('replicas')} replica(s)"
    assert r.get("comm_bytes_hlo", "MISSING") is None, \
        "fleet record comm_bytes_hlo convention broken"
    assert not r.get("request_errors"), \
        f"fleet rung dropped requests: {r['request_errors']}"
print(f"bench record schema: {len(recs)} records OK "
      f"({len(tuned)} autotuned, lm tokens/sec/chip "
      f"{lm[0]['tokens_per_sec_per_chip']} @ seq {lm[0]['seq_len']}, "
      f"lm_serve {ls_[0]['tokens_per_sec_at_slo']} tok/s@SLO = "
      f"{ls_[0]['vs_whole_predict']}x whole-predict, ttft p50 "
      f"{ls_[0]['ttft_p50_ms']}ms)")
PY
}

tier1() {
    rm -f /tmp/_t1.log
    timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
        -m 'not slow' --continue-on-collection-errors \
        -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 \
        | tee /tmp/_t1.log
    local rc=${PIPESTATUS[0]}
    echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
        | tr -cd . | wc -c)
    return "$rc"
}

# the static-analysis layers route through the umbrella CLI
# (tools/analyze.py): per-layer sweep + self-check, unified exit codes
# (1 = findings, 2 = the analyzer itself is broken)
stage "analyze: jaxlint (sweep + self-check)" \
    python tools/analyze.py --layer jaxlint
stage "analyze: lockcheck (sweep + self-check)" \
    python tools/analyze.py --layer lockcheck
stage "analyze: postmortem (self-check)" \
    python tools/analyze.py --layer postmortem
stage "analyze: graphcheck (self-check)" env JAX_PLATFORMS=cpu \
    python tools/analyze.py --layer graphcheck

if [ "${1:-}" != "--fast" ]; then
    # shardcheck FIRST: the compiled-program contracts (reduce-scatter
    # layout, ga-scan anchor, bf16 boundary, fp32 identity, donation)
    # fail in seconds here instead of minutes in the bitwise smokes
    stage "analyze: shardcheck (self-check)" env JAX_PLATFORMS=cpu \
        python tools/analyze.py --layer shardcheck
    stage "shardcheck --contracts"  env JAX_PLATFORMS=cpu \
        python tools/shardcheck.py --contracts

    stage "profiling smoke"  env JAX_PLATFORMS=cpu python tools/profiling_smoke.py
    stage "chaos smoke"      env JAX_PLATFORMS=cpu python tools/chaos_smoke.py
    stage "serve smoke"      env JAX_PLATFORMS=cpu python tools/serve_smoke.py
    stage "lm serve smoke (token-level + shared-prefix + page chaos)" \
        env JAX_PLATFORMS=cpu python tools/lm_serve_smoke.py
    stage "fleet smoke (kill/failover/rolling drain)" env JAX_PLATFORMS=cpu \
        python tools/fleet_smoke.py
    stage "autoscale smoke (ramp/brownout/quarantine)" env JAX_PLATFORMS=cpu \
        python tools/autoscale_smoke.py
    stage "bench smoke (autotuned lenet + input + serve + lm + lm_serve + fleet)" \
        bench_smoke
    stage "zero1 smoke"      env JAX_PLATFORMS=cpu python tools/zero1_smoke.py
    stage "zero2 smoke"      env JAX_PLATFORMS=cpu python tools/zero2_smoke.py
    stage "lm composition smoke" env JAX_PLATFORMS=cpu \
        python tools/lm_smoke.py
    stage "autotune smoke"   env JAX_PLATFORMS=cpu python tools/autotune_smoke.py
    stage "input smoke (+shuffle resume)" env JAX_PLATFORMS=cpu \
        python tools/input_smoke.py
    stage "elastic smoke (3 phases)" env JAX_PLATFORMS=cpu \
        python tools/elastic_smoke.py
    stage "tier-1 tests"     tier1
fi

echo
echo "== run_checks summary =="
printf '%-40s %-6s %8s\n' "stage" "result" "seconds"
total=0
for i in "${!ST_NAME[@]}"; do
    res=PASS; [ "${ST_RC[$i]}" -ne 0 ] && res=FAIL
    printf '%-40s %-6s %8s\n' "${ST_NAME[$i]}" "$res" "${ST_SEC[$i]}"
    total=$((total + ST_SEC[i]))
done
printf '%-40s %-6s %8s\n' "total" "" "$total"

if [ "$fail" -eq 0 ]; then
    echo "run_checks: ALL CHECKS PASSED"
else
    echo "run_checks: FAILURES (see above)" >&2
fi
exit $fail
