#!/usr/bin/env python
"""lockcheck CLI: AST concurrency analysis for the threaded host stack.

Usage:
    python tools/lockcheck.py <file-or-dir> [...]   # analyze (default: package)
    python tools/lockcheck.py --list-rules          # print the rule table
    python tools/lockcheck.py --self-check          # fixture gate (CI)

``--self-check`` analyzes one bad/good fixture pair per rule: the bad
snippet must fire exactly its rule, the good twin must be clean — the
same fixture-gate shape as jaxlint's and graphcheck's. Run by
tools/run_checks.sh.

Exit status: 0 when no findings survive suppression, 1 otherwise.
Suppress a finding inline with ``# lockcheck: disable=<RULE> -- <reason>``
(the reason is mandatory — reasonless suppressions are LC000 findings,
and suppressions that stop silencing anything are LC007 findings).

No imports of the analyzed code, no execution: safe to run anywhere,
fast enough for a pre-commit hook. Wired into tools/run_checks.sh as
the fourth analyzer stage (after graphcheck, jaxlint, shardcheck).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.analysis.findings import format_findings  # noqa: E402
from deeplearning4j_tpu.analysis.lockcheck import (  # noqa: E402
    RULES, RULE_SEVERITY, lint_paths, lint_source,
)


def self_check() -> int:
    """Every rule's bad fixture fires exactly that rule; every good
    twin is clean. Nonzero exit on any drift. Fixtures live in
    ``analysis/fixtures.py`` (``LC_FIXTURES``) next to the graphcheck,
    jaxlint and shardcheck families, under the same coverage
    meta-test."""
    from deeplearning4j_tpu.analysis.fixtures import LC_FIXTURES
    failures = []
    for rule, (bad, good) in sorted(LC_FIXTURES.items()):
        got = [f.rule for f in lint_source(bad, f"<{rule}-bad>")]
        if got != [rule]:
            failures.append(f"{rule}: bad fixture fired {got or 'nothing'}, "
                            f"expected [{rule}]")
        got = [f.rule for f in lint_source(good, f"<{rule}-good>")]
        if got:
            failures.append(f"{rule}: good fixture fired {got}")
    missing = set(RULES) - set(LC_FIXTURES) - {"LC000"}  # LC000 = meta rule
    if missing:
        failures.append(f"rules without fixtures: {sorted(missing)}")
    if failures:
        print("lockcheck --self-check FAILED:")
        for f in failures:
            print("  " + f)
        return 1
    print(f"lockcheck --self-check: {len(LC_FIXTURES)} rule fixtures OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to analyze "
                         "(default: deeplearning4j_tpu)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--self-check", action="store_true",
                    help="analyze the built-in per-rule fixtures and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, (slug, desc) in sorted(RULES.items()):
            print(f"{rule}  {slug:<22} {RULE_SEVERITY[rule]:<8} {desc}")
        return 0
    if args.self_check:
        return self_check()

    paths = args.paths or [os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deeplearning4j_tpu")]
    findings = lint_paths(paths)
    if findings:
        print(format_findings(findings, header="lockcheck findings:"))
        return 1
    print("lockcheck: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
