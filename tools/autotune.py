#!/usr/bin/env python
"""autotune CLI: cost-model-driven configuration search.

Usage:
    python tools/autotune.py model.json --devices 8 \
        [--batch 64] [--hbm-budget-gib 16] [--top-k 3] [--no-probe] \
        [--out tuned.json]
    python tools/autotune.py --model lenet --devices 2 --batch 16

File mode loads a serialized ``MultiLayerConfiguration`` (JSON or
YAML), initializes the container, runs the search (graphcheck-pruned,
cost-model-ranked, measured-probe-validated on whatever backend is
attached — CPU included), prints the TunedConfig summary + probe
table, and optionally writes the JSON artifact so the tuned config can
be checked in next to the model. ``--model`` picks a named built-in
family instead of a file. ``--no-probe`` stops after the analytic
ranking (no compile, no measurement — fast planning mode).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_net(args):
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    if args.model:
        if args.model == "lenet":
            from deeplearning4j_tpu.models.lenet import lenet_mnist
            conf = lenet_mnist()
        elif args.model == "mlp":
            from deeplearning4j_tpu.analysis.fixtures import good_mlp
            conf, _ = good_mlp()
        elif args.model == "gpt":
            # the composition workload (ISSUE 14): a graph config —
            # probe batches are synthesized from its declared types
            # (autotune/probe.synthesize_batch graph path)
            from deeplearning4j_tpu.models.gpt import gpt_tiny
            conf = gpt_tiny(vocab_size=16, seq_len=8)
        else:
            raise SystemExit(f"unknown --model {args.model!r}; "
                             "have: lenet, mlp, gpt")
    else:
        with open(args.config, "r", encoding="utf-8") as fh:
            text = fh.read()
        if args.config.endswith((".yaml", ".yml")):
            import yaml
            d = yaml.safe_load(text)
        else:
            d = json.loads(text)
        from deeplearning4j_tpu.analysis.graphcheck import load_config_dict
        conf = load_config_dict(d)
    if hasattr(conf, "nodes"):
        if not getattr(conf, "resolved_types", None):
            conf._resolve_shapes()
        return ComputationGraph(conf).init()
    return MultiLayerNetwork(conf).init()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("config", nargs="?",
                    help="serialized config (.json/.yaml)")
    ap.add_argument("--model", default=None,
                    help="named built-in model family (lenet, mlp, gpt) "
                         "instead of a config file")
    ap.add_argument("--devices", type=int, default=None,
                    help="chips to plan for (default: all attached)")
    ap.add_argument("--batch", type=int, default=32,
                    help="global training batch size to plan for")
    ap.add_argument("--hbm-budget-gib", type=float, default=None,
                    help="per-chip HBM budget in GiB (default: the "
                         "graphcheck DEFAULT_HBM_BYTES budget)")
    ap.add_argument("--top-k", type=int, default=3,
                    help="candidates to validate with measured probes")
    ap.add_argument("--no-probe", action="store_true",
                    help="analytic ranking only (no compile/measure)")
    ap.add_argument("--probe-steps", type=int, default=3)
    ap.add_argument("--out", default=None,
                    help="write the TunedConfig JSON here (atomic)")
    args = ap.parse_args(argv)

    if not args.config and not args.model:
        ap.error("a config file or --model is required")

    from deeplearning4j_tpu.autotune import AutotuneError, autotune
    net = _load_net(args)
    budget = (int(args.hbm_budget_gib * 1024 ** 3)
              if args.hbm_budget_gib else None)
    try:
        tuned = autotune(net, devices=args.devices, hbm_budget=budget,
                         global_batch=args.batch,
                         top_k=0 if args.no_probe else args.top_k,
                         probe_steps=args.probe_steps)
    except AutotuneError as e:
        print(f"autotune: {e}", file=sys.stderr)
        return 1
    print(tuned.summary())
    if args.out:
        tuned.save(args.out)
        print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
