#!/usr/bin/env python
"""graphcheck CLI: static model-config validation.

Usage:
    python tools/graphcheck.py model.json [--mesh dp=8,pp=2] \
        [--batch-size 64] [--memory]
    python tools/graphcheck.py --self-check

File mode loads a serialized ``MultiLayerConfiguration`` or
``ComputationGraphConfiguration`` (JSON or YAML, dispatched on the
``format`` tag), runs every graphcheck rule, prints findings, and exits
1 when any ERROR finding is present. ``--memory`` additionally prints
the MemoryReport (parameter counts + HBM/VMEM estimate).

``--self-check`` validates the analyzer itself: every known-bad fixture
config (one or more per GC rule — coverage enforced by
tests/test_fixture_coverage.py) must produce its named finding and the
known-good model families must validate clean — the CI gate
tools/run_checks.sh runs.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from deeplearning4j_tpu.analysis.findings import (  # noqa: E402
    Severity, format_findings, has_errors,
)
from deeplearning4j_tpu.analysis.graphcheck import (  # noqa: E402
    load_config_dict, validate_config,
)


def _parse_mesh(spec):
    """'dp=8,pp=2' -> {'dp': 8, 'pp': 2}."""
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        if not size:
            raise SystemExit(f"bad --mesh entry {part!r}; want axis=size")
        axes[name.strip()] = int(size)
    return axes


def _self_check() -> int:
    from deeplearning4j_tpu.analysis.fixtures import KNOWN_BAD, KNOWN_GOOD
    ok = True
    for name, rule, make in KNOWN_BAD:
        conf, kw = make()
        rules = {f.rule for f in validate_config(conf, **kw)}
        if rule in rules:
            print(f"  known-bad  {name:<24} rejected with {rule} (ok)")
        else:
            ok = False
            print(f"  known-bad  {name:<24} FAILED: wanted {rule}, "
                  f"got {sorted(rules) or 'no findings'}")
    for name, make in KNOWN_GOOD:
        conf, kw = make()
        findings = validate_config(conf, **kw)
        if findings:
            ok = False
            print(f"  known-good {name:<24} FAILED: unexpected findings")
            for f in findings:
                print(f"    {f}")
        else:
            print(f"  known-good {name:<24} clean (ok)")
    print("graphcheck self-check:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("config", nargs="?", help="serialized config (.json/.yaml)")
    ap.add_argument("--mesh", default=None,
                    help="mesh axes, e.g. dp=8,pp=2,ep=4")
    ap.add_argument("--batch-size", type=int, default=None,
                    help="global batch size for dp/HBM checks")
    ap.add_argument("--memory", action="store_true",
                    help="print the MemoryReport too")
    ap.add_argument("--self-check", action="store_true",
                    help="validate the analyzer against its known-bad/"
                         "known-good fixtures")
    args = ap.parse_args(argv)

    if args.self_check:
        return _self_check()
    if not args.config:
        ap.error("a config file (or --self-check) is required")

    with open(args.config, "r", encoding="utf-8") as fh:
        text = fh.read()
    if args.config.endswith((".yaml", ".yml")):
        import yaml
        d = yaml.safe_load(text)
    else:
        d = json.loads(text)
    conf = load_config_dict(d)
    findings = validate_config(conf, mesh=_parse_mesh(args.mesh),
                               batch_size=args.batch_size)
    if findings:
        print(format_findings(findings, header=f"{args.config}:"))
    else:
        print(f"{args.config}: clean")
    if args.memory:
        from deeplearning4j_tpu.analysis.memory import memory_report
        print(memory_report(conf, batch_size=args.batch_size or 32).to_text())
    return 1 if has_errors(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
