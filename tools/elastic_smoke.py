#!/usr/bin/env python
"""Elastic-training smoke stage (tools/run_checks.sh): a 2-process CPU
run (1 device per process, dp=2, zero1) loses rank 1 to a hard
``kill_host`` at step 4. The surviving rank 0 must

1. detect the loss within its bounded step-barrier/heartbeat windows
   (never a silent hang — the driver enforces a wall clock),
2. resize the mesh to dp=1 and reshard-restore the latest valid
   sharded checkpoint (zero1 ``(2, chunk)`` updater views un-padded to
   full shape),
3. finish the epoch consuming exactly the unconsumed tail — every
   batch index once, none dropped or doubled,
4. produce a post-resume loss trajectory that is BITWISE identical to
   a clean dp=1 run restarted from the same checkpoint + cursor, and
5. serve ``/api/metrics`` showing exactly one ``elastic_resizes_total``
   (fetched over a real HTTP socket, the PR-2 wiring).

Exit 0 = the detect -> resize -> reshard-restore -> tail-resume
lifecycle is wired end to end.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KILL_STEP = 4
# Must equal faultinject.KILL_HOST_EXIT_CODE (tested in
# tests/test_elastic.py); hand-copied because importing the package
# pulls in jax, and this driver process must stay jax-free.
KILL_HOST_EXIT_CODE = 117
N_BATCHES = 6


# ---------------------------------------------------------------------------
# worker halves (re-exec'd subprocesses; the driver never imports jax)
# ---------------------------------------------------------------------------

def _factory():
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder().seed(2024)
        .updater("adam").learning_rate(0.05)
        .list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(10)).build()).init()


def _batches():
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.default_rng(7)
    return [DataSet(rng.normal(size=(8, 10)).astype(np.float32),
                    np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)])
            for _ in range(N_BATCHES)]


def _worker(rank: int, port: str, ckpt: str) -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=1")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.parallel import multihost
    from deeplearning4j_tpu.resilience import faultinject
    from deeplearning4j_tpu.resilience.elastic import ElasticTrainer
    from deeplearning4j_tpu.resilience.faultinject import (Fault,
                                                           FaultSchedule)
    multihost.initialize(coordinator=f"localhost:{port}", num_processes=2,
                         process_id=rank, elastic=True)
    if rank == 1:
        faultinject.set_schedule(FaultSchedule(
            [Fault(kind="kill_host", step=KILL_STEP)]))
    trainer = ElasticTrainer(
        _factory, ckpt, weight_update_sharding="zero1",
        checkpoint_every=1, keep_last=50,
        step_timeout_s=2.0, heartbeat_timeout_s=3.0, commit_timeout_s=30.0)
    trainer.fit(_batches(), epochs=1)
    print("TRAJ " + json.dumps(trainer.trajectory), flush=True)

    # the /api/metrics gate: serve the registry on an ephemeral port and
    # read elastic_resizes_total back over a real HTTP socket
    import urllib.request

    from deeplearning4j_tpu.ui.server import UIServer
    server = UIServer(port=0).start()
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/api/metrics", timeout=10
    ).read().decode()
    resizes = [ln.split()[-1] for ln in text.splitlines()
               if ln.startswith("elastic_resizes_total")]
    print("HTTP_RESIZES " + (resizes[0] if resizes else "absent"),
          flush=True)
    server.stop()
    trainer.close()
    return 0


def _ref(ckpt: str, resume_step: int) -> int:
    """Clean dp=1 restart from the resume checkpoint: the bitwise
    reference trajectory."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=1")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from deeplearning4j_tpu.parallel import MeshContext, ParallelTrainer
    from deeplearning4j_tpu.resilience.manager import CheckpointManager
    net = _factory()
    mesh = MeshContext.create(n_data=1)
    mgr = CheckpointManager(ckpt, sharded=True, mesh_ctx=mesh)
    info = next(i for i in mgr.checkpoints() if i.step == resume_step)
    cursor = mgr.restore(net, info, reshard=True)
    trainer = ParallelTrainer(net, mesh)
    batches = _batches()
    losses = [float(trainer.fit_batch(batches[i]))
              for i in range(cursor.data_position, len(batches))]
    print("REFLOSSES " + " ".join(f"{l:.17g}" for l in losses), flush=True)
    return 0


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _tagged(out: str, tag: str) -> str:
    return next(ln for ln in out.splitlines()
                if ln.startswith(tag + " "))[len(tag) + 1:]


def main() -> int:
    port = _free_port()
    ckpt = tempfile.mkdtemp(prefix="elastic_smoke_ckpt")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    me = os.path.abspath(__file__)
    logs = [tempfile.NamedTemporaryFile("w+", suffix=f"_w{i}.log",
                                        delete=False) for i in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, me, "--worker", str(i), str(port), ckpt],
        stdout=logs[i], stderr=subprocess.STDOUT, env=env)
        for i in range(2)]
    outs = []
    for i, p in enumerate(procs):
        try:
            # the wall clock IS the no-silent-hang gate: detection +
            # resume must complete well inside it
            p.wait(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            logs[i].seek(0)
            print("elastic_smoke: FAIL worker hung (detection must be "
                  "bounded)\n" + logs[i].read()[-3000:])
            return 1
        logs[i].seek(0)
        outs.append(logs[i].read())
    if procs[1].returncode != KILL_HOST_EXIT_CODE:
        print(f"elastic_smoke: FAIL rank 1 exited {procs[1].returncode}, "
              f"wanted kill_host's {KILL_HOST_EXIT_CODE}\n" + outs[1][-3000:])
        return 1
    if procs[0].returncode != 0:
        print("elastic_smoke: FAIL survivor crashed\n" + outs[0][-3000:])
        return 1

    traj = json.loads(_tagged(outs[0], "TRAJ"))
    indices = [e["index"] for e in traj if e["epoch"] == 0]
    if indices != list(range(N_BATCHES)):
        print(f"elastic_smoke: FAIL batch indices {indices} != exactly-once "
              f"{list(range(N_BATCHES))}")
        return 1

    resizes = _tagged(outs[0], "HTTP_RESIZES")
    try:
        resizes = float(resizes)
    except ValueError:
        resizes = None
    if resizes != 1.0:
        print(f"elastic_smoke: FAIL /api/metrics elastic_resizes_total = "
              f"{resizes!r}, wanted exactly one")
        return 1

    ref = subprocess.run(
        [sys.executable, me, "--ref", ckpt, str(KILL_STEP - 1)],
        capture_output=True, text=True, timeout=300, env=env)
    if ref.returncode != 0:
        print("elastic_smoke: FAIL reference run\n"
              + ref.stdout[-2000:] + ref.stderr[-2000:])
        return 1
    ref_losses = [float(v) for v in
                  _tagged(ref.stdout, "REFLOSSES").split()]
    tail = [e["loss"] for e in traj if e["step"] > KILL_STEP - 1]
    if tail != ref_losses:
        print(f"elastic_smoke: FAIL post-resume trajectory {tail} is not "
              f"bitwise the clean dp=1 restart's {ref_losses}")
        return 1

    print(f"elastic_smoke: PASS kill_host@{KILL_STEP} -> dp=1 resume, "
          f"{len(tail)} post-resume steps bitwise-matched, exactly one "
          "resize on /api/metrics")
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        sys.exit(_worker(int(sys.argv[2]), sys.argv[3], sys.argv[4]))
    if len(sys.argv) > 1 and sys.argv[1] == "--ref":
        sys.exit(_ref(sys.argv[2], int(sys.argv[3])))
    sys.exit(main())
