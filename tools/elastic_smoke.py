#!/usr/bin/env python
"""Elastic-training smoke stage (tools/run_checks.sh): three phases on
2-process CPU (1 device per process, gloo collectives), each ending in
a BITWISE comparison against a clean restart at the resulting width and
an exactly-once cursor-tail check. Exit 0 = the whole detect ->
elect -> resize/grow -> reshard-restore -> tail-resume lifecycle is
wired end to end.

Phase 1 — kill_host (the PR-8 classic): rank 1 dies at step 4; rank 0
  detects within its bounded windows, resizes to dp=1,
  reshard-restores (zero1 ``(2, chunk)`` views un-padded), finishes the
  epoch consuming exactly the unconsumed tail, bitwise vs a clean dp=1
  restart — and serves ``/api/metrics`` over a real HTTP socket with
  exactly one ``elastic_resizes_total``.

Phase 2 — kill_coordinator (ISSUE 12): rank 0 — the coordinator — dies
  at step 4. The coordination service runs EXTERNALLY (sidecar
  process; ``multihost.serve_coordination``), so rank 1 survives the
  service host's death, ELECTS itself (lowest surviving rank takes the
  epoch-1 lease), resizes to dp=1 in process, and finishes
  exactly-once, bitwise vs the same clean dp=1 restart. The driver
  reads the lease back from disk: epoch 1, coordinator 1, world [1].

Phase 3 — rejoin -> scale-UP (ISSUE 12): a sole host trains epoch 0 at
  dp=1 while a ``rejoin_host`` fault announces a replacement (rank 1)
  at step 3; the epoch boundary must ADMIT it
  (``ElasticRestartRequired(grow=True)`` + the epoch-1 lease naming
  world [0, 1]). The restarted 2-process group resumes epoch 1 at
  dp=2, consuming it exactly once — bitwise vs a clean 2-process dp=2
  (zero1) restart from the boundary checkpoint.

The driver process stays jax-free; every compute half is a re-exec'd
subprocess, reaped on all failure paths. Bounded retries apply ONLY on
the documented upstream gloo slot-race signature
(``gloo::EnforceNotMet`` — see tests/test_multihost.py).
"""

import json
import os
import socket
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

KILL_STEP = 4
REJOIN_STEP = 3
# Must equal faultinject.KILL_HOST_EXIT_CODE (tested in
# tests/test_elastic.py); hand-copied because importing the package
# pulls in jax, and this driver process must stay jax-free.
KILL_HOST_EXIT_CODE = 117
N_BATCHES = 6
_GLOO_RACE_MARKER = "gloo::EnforceNotMet"


# ---------------------------------------------------------------------------
# worker halves (re-exec'd subprocesses; the driver never imports jax)
# ---------------------------------------------------------------------------

def _factory():
    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    return MultiLayerNetwork(
        NeuralNetConfiguration.builder().seed(2024)
        .updater("adam").learning_rate(0.05)
        .list()
        .layer(DenseLayer(n_out=16, activation="relu"))
        .layer(OutputLayer(n_out=4, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(10)).build()).init()


def _batches():
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    rng = np.random.default_rng(7)
    return [DataSet(rng.normal(size=(8, 10)).astype(np.float32),
                    np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)])
            for _ in range(N_BATCHES)]


def _jax_cpu():
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=1")
    import jax
    jax.config.update("jax_platforms", "cpu")
    return jax


def _worker(rank: int, port: str, ckpt: str) -> int:
    _jax_cpu()
    from deeplearning4j_tpu.parallel import multihost
    from deeplearning4j_tpu.profiling.metrics import get_registry
    from deeplearning4j_tpu.resilience import faultinject
    from deeplearning4j_tpu.resilience.elastic import (
        ElasticRestartRequired, ElasticTrainer)
    from deeplearning4j_tpu.resilience.faultinject import (Fault,
                                                           FaultSchedule)
    nprocs = int(os.environ.get("SMOKE_NPROCS", "2"))
    multihost.initialize(
        coordinator=f"localhost:{port}", num_processes=nprocs,
        process_id=rank, elastic=True,
        host_service=(False if os.environ.get("SMOKE_EXTERNAL") else None))
    fault_step = int(os.environ.get("SMOKE_FAULT_STEP", "0"))
    if fault_step and rank == int(os.environ.get("SMOKE_VICTIM", "1")):
        faultinject.set_schedule(FaultSchedule([Fault(
            kind=os.environ.get("SMOKE_KIND", "kill_host"),
            step=fault_step,
            rank=int(os.environ.get("SMOKE_JOIN_RANK", "-1")))]))
    trainer = ElasticTrainer(
        _factory, ckpt, weight_update_sharding="zero1",
        checkpoint_every=1, keep_last=50,
        step_timeout_s=2.0, heartbeat_timeout_s=3.0, commit_timeout_s=30.0)
    try:
        trainer.fit(_batches(),
                    epochs=int(os.environ.get("SMOKE_EPOCHS", "1")))
    except ElasticRestartRequired as e:
        print("RESTART " + json.dumps(
            {"survivors": e.survivors, "coordinator": e.coordinator,
             "epoch": e.epoch, "grow": e.grow}), flush=True)
    print("TRAJ " + json.dumps(trainer.trajectory), flush=True)
    reg = get_registry()
    print("METRICS " + json.dumps(
        dict(reg.snapshot("elastic_")) | dict(reg.snapshot(
            "resilience_host"))), flush=True)

    if os.environ.get("SMOKE_HTTP"):
        # the /api/metrics gate: serve the registry on an ephemeral
        # port and read elastic_resizes_total back over a real socket
        import urllib.request

        from deeplearning4j_tpu.ui.server import UIServer
        server = UIServer(port=0).start()
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/api/metrics", timeout=10
        ).read().decode()
        resizes = [ln.split()[-1] for ln in text.splitlines()
                   if ln.startswith("elastic_resizes_total")]
        print("HTTP_RESIZES " + (resizes[0] if resizes else "absent"),
              flush=True)
        server.stop()
    trainer.close()
    return 0


def _ref(ckpt: str, resume_step: int) -> int:
    """Clean dp=1 restart from the resume checkpoint: the bitwise
    reference trajectory for phases 1 and 2."""
    _jax_cpu()
    from deeplearning4j_tpu.parallel import MeshContext, ParallelTrainer
    from deeplearning4j_tpu.resilience.manager import CheckpointManager
    net = _factory()
    mesh = MeshContext.create(n_data=1)
    mgr = CheckpointManager(ckpt, sharded=True, mesh_ctx=mesh)
    info = next(i for i in mgr.checkpoints() if i.step == resume_step)
    cursor = mgr.restore(net, info, reshard=True)
    trainer = ParallelTrainer(net, mesh)
    batches = _batches()
    losses = [float(trainer.fit_batch(batches[i]))
              for i in range(cursor.data_position, len(batches))]
    print("REFLOSSES " + " ".join(f"{l:.17g}" for l in losses), flush=True)
    return 0


def _ref2(rank: int, port: str, ckpt: str, resume_step: int) -> int:
    """Clean 2-process dp=2 (zero1) restart from the scale-up boundary
    checkpoint: the bitwise reference for phase 3's grown world."""
    jax = _jax_cpu()
    from deeplearning4j_tpu.parallel import (MeshContext, ParallelTrainer,
                                             multihost)
    from deeplearning4j_tpu.resilience.elastic import ElasticTrainer
    from deeplearning4j_tpu.resilience.manager import CheckpointManager
    multihost.initialize(coordinator=f"localhost:{port}",
                         num_processes=2, process_id=rank)
    net = _factory()
    mesh = MeshContext.create(n_data=2)
    mgr = CheckpointManager(ckpt, sharded=True, mesh_ctx=mesh,
                            weight_update_sharding="zero1")
    info = next(i for i in mgr.checkpoints() if i.step == resume_step)
    cursor = mgr.restore(net, info, reshard=True)
    trainer = ParallelTrainer(net, mesh, weight_update_sharding="zero1")
    batches = _batches()
    losses = []
    for i in range(cursor.data_position, len(batches)):
        local = ElasticTrainer._slice_batch(
            batches[i], multihost.local_batch_slice(
                batches[i].num_examples()))
        losses.append(float(trainer.fit_batch(local)))
        # serialize steps on the gloo path (slot-race discipline)
        jax.block_until_ready((net.params, net.opt_state))
    print("REFLOSSES " + " ".join(f"{l:.17g}" for l in losses), flush=True)
    return 0


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _tagged(out: str, tag: str) -> str:
    return next(ln for ln in out.splitlines()
                if ln.startswith(tag + " "))[len(tag) + 1:]


def _base_env() -> dict:
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    for k in list(env):
        if k.startswith("SMOKE_"):
            del env[k]
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


class _GlooRace(Exception):
    """A worker died of the documented upstream gloo slot race — the
    attempt (only) is retryable."""


def _spawn(argv_per_proc, env, tag, timeout=300):
    """Spawn one subprocess per argv, wait, reap EVERYTHING on every
    path, return (returncodes, outputs)."""
    logs = [tempfile.NamedTemporaryFile("w+", suffix=f"_{tag}{i}.log",
                                        delete=False)
            for i in range(len(argv_per_proc))]
    procs = [subprocess.Popen(argv, stdout=logs[i],
                              stderr=subprocess.STDOUT, env=env)
             for i, argv in enumerate(argv_per_proc)]
    rcs, outs = [], []
    try:
        for i, p in enumerate(procs):
            try:
                # the wall clock IS the no-silent-hang gate: detection +
                # resume must complete well inside it
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                logs[i].seek(0)
                raise AssertionError(
                    f"elastic_smoke: {tag} worker {i} hung (detection "
                    "must be bounded)\n" + logs[i].read()[-3000:])
            logs[i].seek(0)
            rcs.append(p.returncode)
            outs.append(logs[i].read())
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait(timeout=30)
    return rcs, outs


def _start_sidecar(port: int, nprocs: int, env: dict, timeout: float = 60.0):
    """Bounded READY wait: stdout goes to a file polled under a wall
    clock — a sidecar that wedges before printing READY (port bind,
    import stall) fails the smoke inside ``timeout`` instead of
    hanging the driver on a blocking readline forever."""
    import time
    log = tempfile.NamedTemporaryFile("w+", suffix="_sidecar.log",
                                      delete=False)
    proc = subprocess.Popen(
        [sys.executable, "-m", "deeplearning4j_tpu.parallel.multihost",
         "serve", str(port), str(nprocs)],
        stdout=log, stderr=subprocess.STDOUT, env=env)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        log.seek(0)
        out = log.read()
        if "READY" in out:
            return proc
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    proc.kill()
    proc.wait(timeout=30)
    log.seek(0)
    raise AssertionError(
        "elastic_smoke: coordination sidecar failed to report READY "
        f"within {timeout:.0f}s (rc={proc.returncode}):\n"
        + log.read()[-2000:])


def _check_gloo_race(rcs, outs, expected_kill_ranks=()):
    """Raise _GlooRace when a worker death carries the upstream race's
    own signature (retryable); pass otherwise."""
    for i, (rc, out) in enumerate(zip(rcs, outs)):
        if rc not in (0, None) and i not in expected_kill_ranks \
                and _GLOO_RACE_MARKER in out:
            raise _GlooRace(f"worker {i} hit the gloo slot race")


def _me():
    return os.path.abspath(__file__)


def _kill_phase(name, victim, kind, external):
    """Phases 1 and 2 share one shape: 2-process run, hard-kill one
    rank at KILL_STEP, survivor finishes exactly-once and matches the
    clean dp=1 restart bitwise."""
    port = _free_port()
    ckpt = tempfile.mkdtemp(prefix=f"elastic_smoke_{name}")
    env = _base_env()
    env.update({"SMOKE_FAULT_STEP": str(KILL_STEP), "SMOKE_VICTIM":
                str(victim), "SMOKE_KIND": kind})
    survivor = 1 - victim
    if survivor == 0:
        env["SMOKE_HTTP"] = "1"   # phase 1 carries the HTTP metrics gate
    sidecar = None
    if external:
        env["SMOKE_EXTERNAL"] = "1"
        sidecar = _start_sidecar(port, 2, _base_env())
    try:
        rcs, outs = _spawn(
            [[sys.executable, _me(), "--worker", str(i), str(port), ckpt]
             for i in range(2)], env, name)
    finally:
        if sidecar is not None:
            sidecar.kill()
            sidecar.wait(timeout=30)
    if rcs[victim] != KILL_HOST_EXIT_CODE:
        _check_gloo_race(rcs, outs)
        print(f"elastic_smoke: FAIL {name} rank {victim} exited "
              f"{rcs[victim]}, wanted kill's {KILL_HOST_EXIT_CODE}\n"
              + outs[victim][-3000:])
        return False
    if rcs[survivor] != 0:
        _check_gloo_race(rcs, outs, expected_kill_ranks=(victim,))
        print(f"elastic_smoke: FAIL {name} survivor crashed\n"
              + outs[survivor][-3000:])
        return False

    traj = json.loads(_tagged(outs[survivor], "TRAJ"))
    indices = [e["index"] for e in traj if e["epoch"] == 0]
    if indices != list(range(N_BATCHES)):
        print(f"elastic_smoke: FAIL {name} batch indices {indices} != "
              f"exactly-once {list(range(N_BATCHES))}")
        return False
    metrics = json.loads(_tagged(outs[survivor], "METRICS"))
    want = {"elastic_resizes_total": 1.0,
            "resilience_host_failures_total": 1.0,
            "elastic_dp_width": 1.0}
    if victim == 0:
        # the coordinator died: the survivor must have held an election
        # and the epoch-1 lease must name it on disk
        want |= {"elastic_elections_total": 1.0, "elastic_epoch": 1.0}
        lease = json.loads(open(os.path.join(
            ckpt, "heartbeats", "lease.json")).read())
        if (lease["epoch"], lease["coordinator"],
                lease["world"]) != (1, survivor, [survivor]):
            print(f"elastic_smoke: FAIL {name} lease {lease} does not "
                  f"record rank {survivor}'s election at epoch 1")
            return False
    bad = {k: metrics.get(k) for k, v in want.items()
           if metrics.get(k) != v}
    if bad:
        print(f"elastic_smoke: FAIL {name} counters {bad} != "
              f"{ {k: want[k] for k in bad} }")
        return False

    if victim == 1:
        resizes = _tagged(outs[survivor], "HTTP_RESIZES")
        try:
            resizes = float(resizes)
        except ValueError:
            resizes = None
        if resizes != 1.0:
            print(f"elastic_smoke: FAIL /api/metrics "
                  f"elastic_resizes_total = {resizes!r}, wanted one")
            return False

    ref = subprocess.run(
        [sys.executable, _me(), "--ref", ckpt, str(KILL_STEP - 1)],
        capture_output=True, text=True, timeout=300, env=_base_env())
    if ref.returncode != 0:
        print(f"elastic_smoke: FAIL {name} reference run\n"
              + ref.stdout[-2000:] + ref.stderr[-2000:])
        return False
    ref_losses = [float(v) for v in _tagged(ref.stdout,
                                            "REFLOSSES").split()]
    tail = [e["loss"] for e in traj if e["step"] > KILL_STEP - 1]
    if tail != ref_losses:
        print(f"elastic_smoke: FAIL {name} post-resume trajectory "
              f"{tail} is not bitwise the clean dp=1 restart's "
              f"{ref_losses}")
        return False
    print(f"elastic_smoke: {name} OK — {kind}@{KILL_STEP} -> rank "
          f"{survivor} resumed at dp=1, {len(tail)} post-resume steps "
          "bitwise-matched")
    return True


def _rejoin_phase():
    """Phase 3: sole host + rejoin announcement -> boundary admission ->
    restarted 2-process world resumes epoch 1 at dp=2, bitwise vs the
    clean wide restart."""
    ckpt = tempfile.mkdtemp(prefix="elastic_smoke_p3")
    env = _base_env()
    env.update({"SMOKE_NPROCS": "1", "SMOKE_FAULT_STEP": str(REJOIN_STEP),
                "SMOKE_VICTIM": "0", "SMOKE_KIND": "rejoin_host",
                "SMOKE_JOIN_RANK": "1", "SMOKE_EPOCHS": "2"})
    rcs, outs = _spawn(
        [[sys.executable, _me(), "--worker", "0", str(_free_port()), ckpt]],
        env, "p3a")
    if rcs != [0]:
        print("elastic_smoke: FAIL rejoin stage A crashed\n"
              + outs[0][-3000:])
        return False
    restart = json.loads(_tagged(outs[0], "RESTART"))
    if restart != {"survivors": [0, 1], "coordinator": 0, "epoch": 1,
                   "grow": True}:
        print(f"elastic_smoke: FAIL admission record {restart} != grown "
              "world [0, 1] at epoch 1")
        return False
    metrics = json.loads(_tagged(outs[0], "METRICS"))
    if metrics.get("elastic_scale_ups_total") != 1.0:
        print(f"elastic_smoke: FAIL elastic_scale_ups_total = "
              f"{metrics.get('elastic_scale_ups_total')!r}, wanted one")
        return False
    lease = json.loads(open(os.path.join(ckpt, "heartbeats",
                                         "lease.json")).read())
    if (lease["epoch"], lease["world"]) != (1, [0, 1]):
        print(f"elastic_smoke: FAIL lease {lease} does not admit "
              "world [0, 1] at epoch 1")
        return False

    # stage B: the scheduler's restart of the grown world
    port = _free_port()
    env_b = _base_env()
    env_b["SMOKE_EPOCHS"] = "2"
    rcs, outs = _spawn(
        [[sys.executable, _me(), "--worker", str(i), str(port), ckpt]
         for i in range(2)], env_b, "p3b")
    if rcs != [0, 0]:
        _check_gloo_race(rcs, outs)
        print("elastic_smoke: FAIL grown world crashed\n"
              + outs[0][-2000:] + outs[1][-2000:])
        return False
    trajs = [json.loads(_tagged(o, "TRAJ")) for o in outs]
    if trajs[0] != trajs[1]:
        print("elastic_smoke: FAIL grown-world trajectories diverge "
              "across processes")
        return False
    epoch1 = [e for e in trajs[0] if e["epoch"] == 1]
    if [e["index"] for e in epoch1] != list(range(N_BATCHES)) \
            or [e for e in trajs[0] if e["epoch"] == 0]:
        print(f"elastic_smoke: FAIL grown world consumed "
              f"{[e['index'] for e in epoch1]} of epoch 1 (and "
              f"{len(trajs[0]) - len(epoch1)} stale epoch-0 entries) — "
              "wanted exactly the unconsumed epoch")
        return False

    # stage C: clean 2-process dp=2 restart from the boundary checkpoint
    port = _free_port()
    rcs, outs = _spawn(
        [[sys.executable, _me(), "--ref2", str(i), str(port), ckpt,
          str(N_BATCHES)] for i in range(2)], _base_env(), "p3c")
    if rcs != [0, 0]:
        _check_gloo_race(rcs, outs)
        print("elastic_smoke: FAIL wide reference run crashed\n"
              + outs[0][-2000:] + outs[1][-2000:])
        return False
    ref_losses = [float(v) for v in _tagged(outs[0], "REFLOSSES").split()]
    got = [e["loss"] for e in epoch1]
    if got != ref_losses:
        print(f"elastic_smoke: FAIL post-scale-up trajectory {got} is "
              f"not bitwise the clean dp=2 restart's {ref_losses}")
        return False
    print(f"elastic_smoke: p3 OK — rejoin@{REJOIN_STEP} admitted at the "
          f"epoch boundary, dp=1 -> dp=2, {len(got)} grown-world steps "
          "bitwise-matched vs the clean wide restart")
    return True


def main() -> int:
    phases = [
        ("p1", lambda: _kill_phase("p1", victim=1, kind="kill_host",
                                   external=False)),
        ("p2", lambda: _kill_phase("p2", victim=0,
                                   kind="kill_coordinator",
                                   external=True)),
        ("p3", _rejoin_phase),
    ]
    for name, phase in phases:
        ok = False
        for attempt in range(3):
            try:
                ok = phase()
                break
            except _GlooRace as e:
                print(f"elastic_smoke: {name} attempt {attempt + 1} hit "
                      f"the upstream gloo race ({e}); retrying")
            except AssertionError as e:
                print(str(e))
                break
        if not ok:
            print(f"elastic_smoke: FAIL ({name})")
            return 1
    print("elastic_smoke: PASS all three phases — kill-host resume, "
          "kill-coordinator election, rejoin scale-up: each tail "
          "bitwise vs a clean restart at the resulting width, cursor "
          "consumed exactly once")
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        sys.exit(_worker(int(sys.argv[2]), sys.argv[3], sys.argv[4]))
    if len(sys.argv) > 1 and sys.argv[1] == "--ref":
        sys.exit(_ref(sys.argv[2], int(sys.argv[3])))
    if len(sys.argv) > 1 and sys.argv[1] == "--ref2":
        sys.exit(_ref2(int(sys.argv[2]), sys.argv[3], sys.argv[4],
                       int(sys.argv[5])))
    sys.exit(main())
