#!/usr/bin/env python
"""Input-pipeline smoke stage (tools/run_checks.sh): the same LeNet fit
twice on CPU — once through the plain sync iterator, once through the
sharded streaming input pipeline — over a deliberately SLOWED source
(50ms of sleepy decode per batch, the host-bound profile the pipeline
exists to hide). Gates, per ISSUE 7's acceptance criteria:

1. **Loss parity** — the pipeline preserves batch order, so the two
   runs' loss trajectories (and final params) must be BITWISE equal:
   the pipeline is an execution change, never an algorithm change.
2. **Stall strictly lower** — the sync run eats every decode sleep in
   ``next()`` (``input_stall_s`` ~= batches x delay); the pipeline's
   parallel decode + double-buffered device staging must overlap that
   work with the step, so its measured ``input_stall_s`` is STRICTLY
   below the sync baseline's.
3. The ``input_*`` stage counters actually accumulated on the metrics
   registry (the /api/metrics wiring).
4. **Shuffle-on resume parity (ISSUE 12)** — with the windowed shuffle
   enabled, a run broken after 3 batches and resumed through a FRESH
   pipeline restored from ``cursor_state()`` must be BITWISE identical
   (per-step losses and final params) to the unbroken shuffled run:
   the shuffle RNG + window cursor replay the exact same emission
   order, the consumed prefix exactly once skipped, the tail exactly
   once trained, nothing re-randomized.

Exit 0 = the input pipeline is wired end to end, measurably faster
than the sync feed on a slow source, and shuffled-yet-resumable.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

DP = 2
BATCHES = 6
BATCH = 8
DECODE_DELAY_S = 0.05


def main() -> int:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass  # XLA_FLAGS above already forced the device count
    if len(jax.devices()) < DP:
        print(f"input_smoke: FAIL need {DP} cpu devices, "
              f"have {jax.devices()}")
        return 1

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
    from deeplearning4j_tpu.datasets.pipeline import StreamingInputPipeline
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import MeshContext, ParallelTrainer
    from deeplearning4j_tpu.profiling.metrics import get_registry

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(BATCHES):
        x = rng.normal(size=(BATCH, 28, 28, 1)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, BATCH)]
        batches.append(DataSet(x, y))

    def build():
        return MultiLayerNetwork(lenet_mnist(
            updater="nesterovs", learning_rate=0.01, seed=12345)).init()

    class SleepyIterator(ListDataSetIterator):
        """The slowed source, sync shape: every next() pays the decode
        delay serially on the consumer thread."""

        def next(self):
            time.sleep(DECODE_DELAY_S)
            return super().next()

        def async_supported(self):
            return False  # the SYNC baseline: no prefetch thread

    def sleepy_source(b):
        def synth():
            time.sleep(DECODE_DELAY_S)  # the same delay, decode-stage side
            return b
        return synth

    # -- sync baseline ------------------------------------------------------
    net_sync = build()
    tr_sync = ParallelTrainer(net_sync, MeshContext.create(n_data=DP,
                                                           n_model=1),
                              collect_training_stats=True)
    tr_sync.fit(SleepyIterator(list(batches)), use_async=False)
    stall_sync = tr_sync.training_stats.input_stall_s()

    # -- pipeline -----------------------------------------------------------
    net_pipe = build()
    tr_pipe = ParallelTrainer(net_pipe, MeshContext.create(n_data=DP,
                                                           n_model=1),
                              collect_training_stats=True)
    pipe = StreamingInputPipeline([sleepy_source(b) for b in batches],
                                  num_shards=1, shard_index=0,
                                  reader_workers=2, decode_workers=2)
    tr_pipe.fit(pipe)
    stall_pipe = tr_pipe.training_stats.input_stall_s()

    # -- gates --------------------------------------------------------------
    ls = float(np.asarray(net_sync.score_value))
    lp = float(np.asarray(net_pipe.score_value))
    if np.float32(ls).tobytes() != np.float32(lp).tobytes():
        print(f"input_smoke: FAIL loss parity broken — sync {ls!r} vs "
              f"pipeline {lp!r} (batch order must be identical)")
        return 1
    ps = np.asarray(net_sync.params_flat())
    pp = np.asarray(net_pipe.params_flat())
    if ps.tobytes() != pp.tobytes():
        print("input_smoke: FAIL params diverged bitwise between the "
              "sync and pipeline runs")
        return 1
    if not stall_pipe < stall_sync:
        print(f"input_smoke: FAIL pipeline stall {stall_pipe:.3f}s is not "
              f"strictly below the sync baseline's {stall_sync:.3f}s — "
              "the staged decode is not overlapping the step")
        return 1
    snap = get_registry().snapshot("input_")
    missing = [k for k in ("input_batches_total", "input_stall_seconds_total",
                           "input_decode_seconds_total",
                           "input_h2d_seconds_total") if not snap.get(k)]
    if missing:
        print(f"input_smoke: FAIL input_* metrics never accumulated: "
              f"{missing} (have {sorted(snap)})")
        return 1

    # -- shuffle-on resume parity (ISSUE 12) --------------------------------
    SHUF = {"shuffle_window": 4, "shuffle_seed": 17,
            "num_shards": 1, "shard_index": 0}
    BREAK_AT = 3

    def run_shuffled(resume: bool):
        net = build()
        tr = ParallelTrainer(net, MeshContext.create(n_data=DP, n_model=1))
        losses = []

        def consume(pipe, upto=None):
            while (upto is None or len(losses) < upto) and pipe.has_next():
                losses.append(float(tr.fit_batch(pipe.next())))

        pipe = StreamingInputPipeline(list(batches), **SHUF)
        if not resume:
            consume(pipe)
        else:
            consume(pipe, upto=BREAK_AT)
            state = pipe.cursor_state()
            pipe.close()                      # the "crash"
            pipe = StreamingInputPipeline(list(batches), **SHUF)
            pipe.restore_cursor(state)        # fresh pipeline, same order
            consume(pipe)
        return losses, np.asarray(net.params_flat())

    unbroken_losses, unbroken_params = run_shuffled(resume=False)
    resumed_losses, resumed_params = run_shuffled(resume=True)
    if len(unbroken_losses) != BATCHES:
        print(f"input_smoke: FAIL shuffled run consumed "
              f"{len(unbroken_losses)} batches, wanted {BATCHES}")
        return 1
    if np.float64(unbroken_losses).tobytes() \
            != np.float64(resumed_losses).tobytes():
        print(f"input_smoke: FAIL shuffled resume re-randomized the "
              f"order — unbroken {unbroken_losses} vs resumed "
              f"{resumed_losses}")
        return 1
    if unbroken_params.tobytes() != resumed_params.tobytes():
        print("input_smoke: FAIL shuffled resumed params diverged "
              "bitwise from the unbroken run")
        return 1
    print(f"input_smoke: OK — {BATCHES} LeNet steps bitwise loss-equal, "
          f"input_stall_s {stall_pipe:.3f}s (pipeline) < "
          f"{stall_sync:.3f}s (sync, {DECODE_DELAY_S * 1e3:.0f}ms sleepy "
          f"decode/batch), {stall_pipe / max(stall_sync, 1e-9):.2f}x; "
          f"shuffled resume@{BREAK_AT} bitwise == unbroken shuffled run")
    return 0


if __name__ == "__main__":
    sys.exit(main())
