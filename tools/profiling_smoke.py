#!/usr/bin/env python
"""Profiling smoke stage (tools/run_checks.sh): a 3-step LeNet fit on
CPU must produce (1) a Chrome trace-event JSON that parses and carries
the expected spans, (2) compile-watcher metrics in the registry and a
valid Prometheus rendering, and (3) a cost analysis whose FLOPs and
analytic MFU are present and positive. Exit 0 = healthy subsystem.
"""

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import numpy as np

    from deeplearning4j_tpu.datasets import DataSet
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.profiling import (
        CompileWatcher, Tracer, analytic_mfu, get_registry, set_tracer,
    )

    tracer = Tracer()
    prev = set_tracer(tracer)
    watcher = CompileWatcher().install()
    try:
        rng = np.random.default_rng(0)
        batches = [DataSet(
            rng.normal(size=(8, 28, 28, 1)).astype(np.float32),
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, 8)])
            for _ in range(3)]
        net = MultiLayerNetwork(lenet_mnist()).init()
        with tracer.span("lenet_fit", steps=3):
            for b in batches:
                net.fit_batch(b)
        cost = net.cost_analysis(batches[0])
    finally:
        watcher.uninstall()
        set_tracer(prev)

    failures = []

    # 1) trace exports, round-trips through JSON, and carries the spans
    with tempfile.TemporaryDirectory() as td:
        path = tracer.save(os.path.join(td, "trace.json"))
        with open(path) as f:
            blob = json.load(f)
    events = blob.get("traceEvents")
    if not isinstance(events, list) or not events:
        failures.append("trace has no traceEvents")
    else:
        names = {e.get("name") for e in events}
        for want in ("lenet_fit", "fit_batch"):
            if want not in names:
                failures.append(f"span {want!r} missing from trace "
                                f"(got {sorted(names)})")
        bad = [e for e in events
               if e.get("ph") not in ("X", "i")
               or not isinstance(e.get("ts"), (int, float))]
        if bad:
            failures.append(f"{len(bad)} malformed trace events")

    # 2) compile watcher fed the registry; Prometheus text renders
    reg = get_registry()
    if reg.counter("jax_compile_total").value < 1:
        failures.append("CompileWatcher counted no compiles")
    text = reg.to_prometheus()
    if "jax_compile_total" not in text or "# TYPE" not in text:
        failures.append("Prometheus rendering incomplete")

    # 3) cost analysis: FLOPs and a defined analytic MFU
    flops = cost.get("flops_per_step")
    if not flops or flops <= 0:
        failures.append(f"cost analysis flops_per_step={flops!r}")
    mfu = analytic_mfu(flops or 0, 0.05, cost.get("peak_flops_per_chip"))
    if mfu is None or mfu <= 0:
        failures.append(f"analytic MFU undefined (peak="
                        f"{cost.get('peak_flops_per_chip')!r})")

    if failures:
        print("profiling smoke FAILED:")
        for f in failures:
            print("  " + f)
        return 1
    print(f"profiling smoke OK: {len(events)} trace events, "
          f"{int(reg.counter('jax_compile_total').value)} compiles "
          f"watched, {flops:.3e} FLOPs/step, analytic_mfu@50ms={mfu:.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
