#!/usr/bin/env python
"""Serving smoke stage (tools/run_checks.sh): a burst of concurrent
predicts against a tiny model behind the PR 4 service-hardening kit
must (1) resolve every request as either a prediction or a structured
shed/deadline/breaker error — zero crashes, zero garbage; (2) actually
shed under pressure (``serving_shed_total`` > 0); (3) flip the UI
server's ``/readyz`` to 503 while the gateway drains; (4) finish the
drain cleanly with in-flight work completed and handler threads
reclaimed. Exit 0 = the serving edge's hardening is wired end to end.
"""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
from urllib.error import HTTPError

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _readyz(port):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=5) as r:
            return r.status, json.loads(r.read())
    except HTTPError as e:
        return e.code, json.loads(e.read())


def main() -> int:
    import numpy as np

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.iris import load_iris
    from deeplearning4j_tpu.keras.server import KerasClient, KerasServer
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.profiling.metrics import (MetricsRegistry,
                                                      set_registry)
    from deeplearning4j_tpu.resilience import faultinject
    from deeplearning4j_tpu.resilience.faultinject import (Fault,
                                                           FaultSchedule)
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.util.serializer import ModelSerializer

    registry = MetricsRegistry()
    prev = set_registry(registry)
    n0 = threading.active_count()
    try:
        conf = (NeuralNetConfiguration.builder().updater("adam")
                .learning_rate(0.05).seed(7).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        with tempfile.TemporaryDirectory() as d:
            model = os.path.join(d, "iris.zip")
            ModelSerializer.write_model(net, model)
            x = os.path.join(d, "x.npy")
            np.save(x, load_iris().features[:4])

            ui = UIServer(port=0).start()
            srv = KerasServer(max_concurrency=1, queue_depth=2,
                              default_deadline_ms=5000)
            warm = KerasClient(srv.host, srv.port)
            warm.predict(x, model=model)  # load + compile
            code, _ = _readyz(ui.port)
            if code != 200:
                print(f"serve_smoke: FAIL /readyz {code} before burst")
                return 1

            # burst: 16 concurrent predicts, two dispatches hung by the
            # chaos harness so the queue (depth 2) backs up and sheds
            faultinject.set_schedule(FaultSchedule(
                [Fault("hang_backend", at_call=k, duration=0.3)
                 for k in (1, 2)] + [Fault("burst", count=16)]))
            n_burst = faultinject.burst_size()
            outcomes, lock = [], threading.Lock()

            def one():
                try:
                    c = KerasClient(srv.host, srv.port)
                    try:
                        c.request(op="predict", features=x, model=model,
                                  deadline_ms=400)
                        r = "ok"
                    finally:
                        c.close()
                except RuntimeError as e:
                    r = str(e).split(":")[0]
                except Exception as e:  # a crash, not a structured shed
                    r = f"CRASH({type(e).__name__})"
                with lock:
                    outcomes.append(r)

            threads = [threading.Thread(target=one, daemon=True)
                       for _ in range(n_burst)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            faultinject.clear()

            allowed = {"ok", "SHED", "DEADLINE", "BREAKER_OPEN"}
            bad = [r for r in outcomes if r not in allowed]
            if bad or len(outcomes) != n_burst:
                print(f"serve_smoke: FAIL outcomes {outcomes}")
                return 1
            shed = registry.snapshot("serving_").get(
                "serving_shed_total", 0)
            if shed < 1:
                print(f"serve_smoke: FAIL no shedding under burst "
                      f"(outcomes {outcomes})")
                return 1

            # drain with one request in flight: /readyz must flip to
            # 503 while it runs, and the in-flight predict must finish
            faultinject.set_schedule(FaultSchedule(
                [Fault("hang_backend", at_call=1, duration=0.5)]))
            slow = {}

            def slow_predict():
                c = KerasClient(srv.host, srv.port)
                slow["resp"] = c.request(op="predict", features=x,
                                         model=model)
                c.close()

            t = threading.Thread(target=slow_predict, daemon=True)
            t.start()
            t_end = time.monotonic() + 5.0
            while srv._guard.inflight == 0:
                if time.monotonic() > t_end:
                    print("serve_smoke: FAIL slow predict never admitted")
                    return 1
                time.sleep(0.01)
            drained = {}
            dt = threading.Thread(
                target=lambda: drained.update(ok=srv.drain(grace_s=5.0)),
                daemon=True)
            dt.start()
            while not srv.draining:
                time.sleep(0.01)
            code, body = _readyz(ui.port)
            if code != 503:
                print(f"serve_smoke: FAIL /readyz {code} during drain "
                      f"({body})")
                return 1
            t.join(10.0)
            dt.join(10.0)
            faultinject.clear()
            if not slow.get("resp", {}).get("ok"):
                print(f"serve_smoke: FAIL in-flight predict lost in "
                      f"drain ({slow})")
                return 1
            if drained.get("ok") is not True:
                print("serve_smoke: FAIL drain grace expired with work "
                      "in flight")
                return 1
            warm.close()
            ui.stop()
            t_end = time.monotonic() + 10.0
            while threading.active_count() > n0 + 2:
                if time.monotonic() > t_end:
                    print(f"serve_smoke: FAIL thread leak "
                          f"({threading.active_count()} vs {n0})")
                    return 1
                time.sleep(0.05)
        n_ok = sum(1 for r in outcomes if r == "ok")
        print(f"serve_smoke: OK — burst of {n_burst}: {n_ok} served, "
              f"{int(shed)} shed, zero crashes; /readyz flipped during "
              f"drain; in-flight work finished; threads reclaimed")
        return 0
    finally:
        set_registry(prev)


if __name__ == "__main__":
    sys.exit(main())
