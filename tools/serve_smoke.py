#!/usr/bin/env python
"""Serving smoke stage (tools/run_checks.sh): a burst of concurrent
predicts against a tiny model behind the PR 4 service-hardening kit
must (1) resolve every request as either a prediction or a structured
shed/deadline/breaker error — zero crashes, zero garbage; (2) actually
shed under pressure (``serving_shed_total`` > 0); (3) flip the UI
server's ``/readyz`` to 503 while the gateway drains; (4) finish the
drain cleanly with in-flight work completed and handler threads
reclaimed. A second, continuous-batching phase (PR 6) then proves the
scheduler end to end: concurrent clients must coalesce into
multi-request batches (fewer batches than requests), batched
predictions must be BITWISE equal to the singleton warmup predictions,
the compile count must stay flat across a second wave of
identical-bucket requests (zero per-request recompiles), and no
request may blow its deadline. Exit 0 = the serving edge is wired end
to end."""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.request
from urllib.error import HTTPError

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _readyz(port):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/readyz", timeout=5) as r:
            return r.status, json.loads(r.read())
    except HTTPError as e:
        return e.code, json.loads(e.read())


def main() -> int:
    import numpy as np

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.iris import load_iris
    from deeplearning4j_tpu.keras.server import KerasClient, KerasServer
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.profiling.metrics import (MetricsRegistry,
                                                      set_registry)
    from deeplearning4j_tpu.resilience import faultinject
    from deeplearning4j_tpu.resilience.faultinject import (Fault,
                                                           FaultSchedule)
    from deeplearning4j_tpu.ui.server import UIServer
    from deeplearning4j_tpu.util.serializer import ModelSerializer

    registry = MetricsRegistry()
    prev = set_registry(registry)
    n0 = threading.active_count()
    try:
        conf = (NeuralNetConfiguration.builder().updater("adam")
                .learning_rate(0.05).seed(7).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        with tempfile.TemporaryDirectory() as d:
            model = os.path.join(d, "iris.zip")
            ModelSerializer.write_model(net, model)
            x = os.path.join(d, "x.npy")
            np.save(x, load_iris().features[:4])

            ui = UIServer(port=0).start()
            srv = KerasServer(max_concurrency=1, queue_depth=2,
                              default_deadline_ms=5000)
            warm = KerasClient(srv.host, srv.port)
            warm.predict(x, model=model)  # load + compile
            code, _ = _readyz(ui.port)
            if code != 200:
                print(f"serve_smoke: FAIL /readyz {code} before burst")
                return 1

            # burst: 16 concurrent predicts, two dispatches hung by the
            # chaos harness so the queue (depth 2) backs up and sheds
            faultinject.set_schedule(FaultSchedule(
                [Fault("hang_backend", at_call=k, duration=0.3)
                 for k in (1, 2)] + [Fault("burst", count=16)]))
            n_burst = faultinject.burst_size()
            outcomes, lock = [], threading.Lock()

            def one():
                try:
                    c = KerasClient(srv.host, srv.port)
                    try:
                        c.request(op="predict", features=x, model=model,
                                  deadline_ms=400)
                        r = "ok"
                    finally:
                        c.close()
                except RuntimeError as e:
                    r = str(e).split(":")[0]
                except Exception as e:  # a crash, not a structured shed
                    r = f"CRASH({type(e).__name__})"
                with lock:
                    outcomes.append(r)

            threads = [threading.Thread(target=one, daemon=True)
                       for _ in range(n_burst)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            faultinject.clear()

            allowed = {"ok", "SHED", "DEADLINE", "BREAKER_OPEN"}
            bad = [r for r in outcomes if r not in allowed]
            if bad or len(outcomes) != n_burst:
                print(f"serve_smoke: FAIL outcomes {outcomes}")
                return 1
            shed = registry.snapshot("serving_").get(
                "serving_shed_total", 0)
            if shed < 1:
                print(f"serve_smoke: FAIL no shedding under burst "
                      f"(outcomes {outcomes})")
                return 1

            # drain with one request in flight: /readyz must flip to
            # 503 while it runs, and the in-flight predict must finish
            faultinject.set_schedule(FaultSchedule(
                [Fault("hang_backend", at_call=1, duration=0.5)]))
            slow = {}

            def slow_predict():
                c = KerasClient(srv.host, srv.port)
                slow["resp"] = c.request(op="predict", features=x,
                                         model=model)
                c.close()

            t = threading.Thread(target=slow_predict, daemon=True)
            t.start()
            t_end = time.monotonic() + 5.0
            while srv._guard.inflight == 0:
                if time.monotonic() > t_end:
                    print("serve_smoke: FAIL slow predict never admitted")
                    return 1
                time.sleep(0.01)
            drained = {}
            dt = threading.Thread(
                target=lambda: drained.update(ok=srv.drain(grace_s=5.0)),
                daemon=True)
            dt.start()
            while not srv.draining:
                time.sleep(0.01)
            code, body = _readyz(ui.port)
            if code != 503:
                print(f"serve_smoke: FAIL /readyz {code} during drain "
                      f"({body})")
                return 1
            t.join(10.0)
            dt.join(10.0)
            faultinject.clear()
            if not slow.get("resp", {}).get("ok"):
                print(f"serve_smoke: FAIL in-flight predict lost in "
                      f"drain ({slow})")
                return 1
            if drained.get("ok") is not True:
                print("serve_smoke: FAIL drain grace expired with work "
                      "in flight")
                return 1
            warm.close()
            ui.stop()
            t_end = time.monotonic() + 10.0
            while threading.active_count() > n0 + 2:
                if time.monotonic() > t_end:
                    print(f"serve_smoke: FAIL thread leak "
                          f"({threading.active_count()} vs {n0})")
                    return 1
                time.sleep(0.05)

            # ---- continuous-batching phase (PR 6): fresh registry so
            # the burst phase's deadline counts can't mask this one's
            batch_registry = MetricsRegistry()
            set_registry(batch_registry)
            rc = _batching_phase(d, model, np)
            if rc != 0:
                return rc
        n_ok = sum(1 for r in outcomes if r == "ok")
        print(f"serve_smoke: OK — burst of {n_burst}: {n_ok} served, "
              f"{int(shed)} shed, zero crashes; /readyz flipped during "
              f"drain; in-flight work finished; threads reclaimed; "
              f"batching phase passed")
        return 0
    finally:
        set_registry(prev)


def _batching_phase(d, model, np) -> int:
    """Concurrent clients against the continuous-batching scheduler:
    multi-request batches must form (batches < requests), batched
    results must bitwise-match the singleton warmup results, the
    compile count must stay flat across the second wave, and zero
    deadlines may blow."""
    import os
    import threading

    from deeplearning4j_tpu.keras.server import KerasClient, KerasServer
    from deeplearning4j_tpu.profiling.metrics import get_registry

    n_clients, n_waves = 12, 2
    srv = KerasServer(max_concurrency=n_clients, queue_depth=2 * n_clients,
                      max_batch=8, max_wait_ms=50.0,
                      default_deadline_ms=30_000)
    try:
        # feature files for every power-of-two bucket the waves can hit
        rng = np.random.default_rng(11)
        files = {}
        for rows in (1, 2, 4, 8):
            p = os.path.join(d, f"bx{rows}.npy")
            np.save(p, rng.normal(size=(rows, 4)).astype(np.float32))
            files[rows] = p
        warm = KerasClient(srv.host, srv.port)
        singleton = {rows: warm.predict(p, model=model)
                     for rows, p in files.items()}  # also warms buckets
        warm.close()
        net = next(iter(srv._models.values()))
        traces_after_warm = net._infer_traces

        results, failures = {}, []
        res_lock = threading.Lock()

        def one(wave, idx):
            try:
                cli = KerasClient(srv.host, srv.port)
                try:
                    got = cli.predict(files[1], model=model)
                    with res_lock:
                        results[(wave, idx)] = got
                finally:
                    cli.close()
            except Exception as e:  # noqa: BLE001 — reported below
                with res_lock:
                    failures.append(f"{type(e).__name__}: {e}")

        traces_per_wave = []
        for wave in range(n_waves):
            threads = [threading.Thread(target=one, args=(wave, i),
                                        daemon=True)
                       for i in range(n_clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            traces_per_wave.append(net._infer_traces)
        if failures:
            print(f"serve_smoke: FAIL batching wave errors {failures}")
            return 1
        # zero per-request recompiles after warmup: compile count flat
        # across BOTH waves of identical-bucket requests
        if traces_per_wave != [traces_after_warm] * n_waves:
            print(f"serve_smoke: FAIL recompiles under batching "
                  f"(traces {traces_after_warm} -> {traces_per_wave})")
            return 1
        # batched == singleton, bitwise
        for (wave, idx), got in results.items():
            if not np.array_equal(got, singleton[1]):
                print(f"serve_smoke: FAIL batched prediction diverged "
                      f"from singleton (wave {wave}, client {idx})")
                return 1
        reg = get_registry()
        batched = reg.get("serving_batched_requests_total")
        hist = reg.get("serving_batch_size")
        n_req = n_clients * n_waves
        if batched is None or batched.value < n_req:
            print(f"serve_smoke: FAIL batched path not taken "
                  f"({batched and batched.value} < {n_req})")
            return 1
        if hist is None or hist.count >= batched.value:
            print(f"serve_smoke: FAIL no multi-request batch formed "
                  f"({hist and hist.count} batches for "
                  f"{batched.value} requests)")
            return 1
        deadline = reg.get("serving_deadline_exceeded_total")
        if deadline is not None and deadline.value > 0:
            print(f"serve_smoke: FAIL {deadline.value} requests blew "
                  "their deadline under batching")
            return 1
        print(f"serve_smoke: batching — {int(batched.value)} requests "
              f"in {hist.count} batches, compile count flat at "
              f"{traces_after_warm}, bitwise parity, zero blown "
              f"deadlines")
        return 0
    finally:
        srv.drain(grace_s=5.0)


if __name__ == "__main__":
    sys.exit(main())
