#!/usr/bin/env python
"""Autoscaling + overload-degradation smoke stage (tools/run_checks.sh,
ISSUE 19).

An in-process fleet behind a ``FleetRouter`` with a ``FleetAutoscaler``
controller must prove, end to end over real sockets, the elasticity
and graceful-degradation contract:

1. **Ramp 1→3→1** — a predict storm against a deliberately slowed
   replica breaches the queue SLO; the controller spawns to
   ``max_replicas`` (readyz-gated admission), the storm ends, and the
   pool drains back to the floor through the zero-drop seam. Zero
   client-visible failures across the whole ramp, and every decision
   is in the flight recorder.
2. **Kill during ramp + budget-capped amplification** — a replica is
   hard-killed mid-ramp: clients still see zero failures (failover +
   respawn), and a separate dry-budget microcheck proves a dispatch
   against a dying pool is amplified at most once (initial + one free
   reroute) before the structured error surfaces.
3. **Brownout** — sustained overload at ``max_replicas`` flips the
   router into brownout: bulk-class requests shed with a structured
   ``SHED`` (retry_after_ms, connection stays up) while interactive
   requests keep serving inside their SLO; calm exits brownout.
4. **Flap quarantine** — a crash-looping replica (``flap_replica``
   chaos) is quarantined after two strikes while the stable pool keeps
   serving; its next healthy incarnation is re-admitted after the
   probation delay.

Exit 0 = the elasticity/overload edge is wired end to end.
"""

import json
import os
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _counter(registry, name):
    m = registry.get(name)
    return 0 if m is None else m.value


def _wait(pred, timeout_s, poll_s=0.05):
    t_end = time.monotonic() + timeout_s
    while time.monotonic() < t_end:
        if pred():
            return True
        time.sleep(poll_s)
    return pred()


def _stall_schedule(Fault, FaultSchedule, ranks, per_rank, duration):
    """Arm ``per_rank`` consecutive slow_replica stalls on each rank —
    the sustained overload that backs the storm up into the router's
    admission queue."""
    return FaultSchedule(faults=[
        Fault("slow_replica", rank=r, at_call=i, duration=duration)
        for r in ranks for i in range(1, per_rank + 1)])


def main() -> int:
    import numpy as np

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.iris import load_iris
    from deeplearning4j_tpu.keras.autoscale import FleetAutoscaler
    from deeplearning4j_tpu.keras.fleet import FleetReplica, FleetRouter
    from deeplearning4j_tpu.keras.server import KerasClient
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.profiling.flightrec import (FlightRecorder,
                                                        set_flightrec)
    from deeplearning4j_tpu.profiling.metrics import (MetricsRegistry,
                                                      set_registry)
    from deeplearning4j_tpu.resilience import faultinject
    from deeplearning4j_tpu.resilience.faultinject import (Fault,
                                                           FaultSchedule)
    from deeplearning4j_tpu.util.serializer import ModelSerializer

    registry = MetricsRegistry()
    prev = set_registry(registry)
    prev_rec = set_flightrec(FlightRecorder())
    n0 = threading.active_count()
    try:
        conf = (NeuralNetConfiguration.builder().updater("adam")
                .learning_rate(0.05).seed(7).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        mlp = MultiLayerNetwork(conf).init()
        with tempfile.TemporaryDirectory() as d:
            mlp_zip = os.path.join(d, "iris.zip")
            ModelSerializer.write_model(mlp, mlp_zip)
            x = os.path.join(d, "x.npy")
            np.save(x, load_iris().features[:4])
            ctx = (d, mlp_zip, x, KerasClient, FleetReplica,
                   FleetRouter, FleetAutoscaler, faultinject, Fault,
                   FaultSchedule, registry)
            for phase, fn in (("ramp 1→3→1", _phase_ramp),
                              ("kill during ramp + capped "
                               "amplification", _phase_kill_and_budget),
                              ("brownout sheds bulk only",
                               _phase_brownout),
                              ("flap quarantine", _phase_quarantine)):
                rc = fn(*ctx)
                faultinject.clear()
                if rc != 0:
                    return rc
                print(f"autoscale_smoke: phase OK — {phase}")

        t_end = time.monotonic() + 15.0
        while threading.active_count() > n0 + 2:
            if time.monotonic() > t_end:
                print(f"autoscale_smoke: FAIL thread leak "
                      f"({threading.active_count()} vs baseline {n0})")
                return 1
            time.sleep(0.05)
        print("autoscale_smoke: OK — ramp 1→3→1 (zero failures), "
              "kill-under-ramp (budget-capped amplification), brownout "
              "(bulk shed, interactive in SLO), flap quarantine + "
              "release")
        return 0
    finally:
        faultinject.clear()
        set_registry(prev)
        set_flightrec(prev_rec)


def _spawn_fn(fdir, mlp_zip, FleetReplica):
    def spawn(rank):
        return FleetReplica(fdir, rank, model=mlp_zip,
                            max_concurrency=8, queue_depth=32,
                            default_deadline_ms=60_000)
    return spawn


def _start_loaders(n, router, x, mlp_zip, KerasClient, stop, failures,
                   lock, counts, pause=0.02):
    def load(i):
        while not stop.is_set():
            try:
                cli = KerasClient(router.host, router.port)
                try:
                    cli.predict(x, model=mlp_zip)
                finally:
                    cli.close()
                with lock:
                    counts["ok"] += 1
            except Exception as e:  # noqa: BLE001 — the gate itself
                with lock:
                    failures.append(f"loader {i}: "
                                    f"{type(e).__name__}: {e}")
                return
            time.sleep(pause)

    loaders = [threading.Thread(target=load, args=(i,), daemon=True)
               for i in range(n)]
    for t in loaders:
        t.start()
    return loaders


def _phase_ramp(d, mlp_zip, x, KerasClient, FleetReplica, FleetRouter,
                FleetAutoscaler, faultinject, Fault, FaultSchedule,
                registry) -> int:
    """Storm against a slowed pool: the controller ramps 1→3, the storm
    ends, the pool drains back to 1 — zero client failures end to end."""
    from deeplearning4j_tpu.profiling.flightrec import get_flightrec

    fdir = os.path.join(d, "fleet_ramp")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.5,
                         max_concurrency=4, queue_depth=64,
                         max_queue_wait_s=15.0,
                         default_deadline_ms=120_000)
    rep0 = FleetReplica(fdir, 0, model=mlp_zip, max_concurrency=8,
                        queue_depth=32, default_deadline_ms=60_000)
    auto = FleetAutoscaler(router, _spawn_fn(fdir, mlp_zip, FleetReplica),
                           min_replicas=1, max_replicas=3, queue_high=2,
                           up_ticks=2, down_ticks=4, up_cooldown_s=1.0,
                           down_cooldown_s=1.0, tick_s=0.25,
                           brownout=False, drain_grace_s=15.0)
    stop = threading.Event()
    failures, lock, counts = [], threading.Lock(), {"ok": 0}
    loaders = []
    try:
        if not router.wait_for_replicas(1, timeout_s=30.0):
            print("autoscale_smoke: FAIL seed replica never admitted")
            return 1
        # every rank the controller may spawn is pre-slowed: the breach
        # persists until the pool is actually wider
        faultinject.set_schedule(_stall_schedule(
            Fault, FaultSchedule, ranks=range(0, 6), per_rank=400,
            duration=0.15))
        loaders = _start_loaders(8, router, x, mlp_zip, KerasClient,
                                 stop, failures, lock, counts)
        if not _wait(lambda: len(router.replicas()) >= 3, 60.0):
            print(f"autoscale_smoke: FAIL never ramped to 3 "
                  f"(members {router.replicas()}, "
                  f"ups {_counter(registry, 'fleet_autoscale_up_total')})")
            return 1
        # storm over: stalls off, load down to a trickle that proves
        # the scale-down drains are zero-drop under live traffic
        faultinject.clear()
        stop.set()
        for t in loaders:
            t.join(60.0)
        stop = threading.Event()
        loaders = _start_loaders(1, router, x, mlp_zip, KerasClient,
                                 stop, failures, lock, counts,
                                 pause=0.05)
        if not _wait(lambda: router.replicas() == [0], 60.0):
            print(f"autoscale_smoke: FAIL never drained back to floor "
                  f"(members {router.replicas()})")
            return 1
        time.sleep(0.3)  # post-drain load lands on the survivor
        stop.set()
        for t in loaders:
            t.join(30.0)
        if failures:
            print(f"autoscale_smoke: FAIL client failures during ramp: "
                  f"{failures[:3]}")
            return 1
        ups = _counter(registry, "fleet_autoscale_up_total")
        downs = _counter(registry, "fleet_autoscale_down_total")
        if ups < 2 or downs < 2:
            print(f"autoscale_smoke: FAIL decision accounting "
                  f"(ups {ups}, downs {downs})")
            return 1
        if counts["ok"] < 50:
            print(f"autoscale_smoke: FAIL implausibly little load "
                  f"survived the ramp ({counts['ok']})")
            return 1
        kinds = {(e["subsystem"], e["kind"])
                 for e in get_flightrec().tail(2000)}
        needed = {("autoscale", "scale_up"),
                  ("autoscale", "scale_down"),
                  ("autoscale", "scale_down_drained")}
        if not needed <= kinds:
            print(f"autoscale_smoke: FAIL flight recorder missing "
                  f"{needed - kinds}")
            return 1
        print(f"autoscale_smoke: ramp — {counts['ok']} requests, "
              f"zero failures, ups {ups}, downs {downs}")
        return 0
    finally:
        stop.set()
        for t in loaders:
            t.join(10.0)
        faultinject.clear()
        auto.drain(drain_owned=True)
        router.close()
        rep0.drain(grace_s=5.0)


def _phase_kill_and_budget(d, mlp_zip, x, KerasClient, FleetReplica,
                           FleetRouter, FleetAutoscaler, faultinject,
                           Fault, FaultSchedule, registry) -> int:
    """A controller-spawned replica is hard-killed mid-ramp: zero
    client failures (failover + the controller replaces it). Then a
    dry-budget microcheck pins the amplification cap: a dying pool
    costs one dispatch plus ONE free reroute, never a retry storm."""
    fdir = os.path.join(d, "fleet_kill")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.5,
                         max_concurrency=4, queue_depth=64,
                         max_queue_wait_s=15.0,
                         default_deadline_ms=120_000)
    rep0 = FleetReplica(fdir, 0, model=mlp_zip, max_concurrency=8,
                        queue_depth=32, default_deadline_ms=60_000)
    auto = FleetAutoscaler(router, _spawn_fn(fdir, mlp_zip, FleetReplica),
                           min_replicas=1, max_replicas=3, queue_high=2,
                           up_ticks=2, down_ticks=1000,
                           up_cooldown_s=1.0, tick_s=0.25,
                           brownout=False)
    stop = threading.Event()
    failures, lock, counts = [], threading.Lock(), {"ok": 0}
    loaders = []
    try:
        if not router.wait_for_replicas(1, timeout_s=30.0):
            print("autoscale_smoke: FAIL seed replica never admitted")
            return 1
        kill = Fault("kill_replica", rank=1, at_call=2)
        faultinject.set_schedule(FaultSchedule(faults=(
            _stall_schedule(Fault, FaultSchedule, ranks=range(0, 6),
                            per_rank=400, duration=0.15).faults
            + [kill])))
        loaders = _start_loaders(8, router, x, mlp_zip, KerasClient,
                                 stop, failures, lock, counts)
        # rank 1 (the first spawn) dies on its 2nd admitted request;
        # the ramp must still reach a wider, working pool
        if not _wait(lambda: kill.fired, 60.0):
            print("autoscale_smoke: FAIL kill_replica never fired")
            return 1
        if not _wait(lambda: len(router.replicas()) >= 2
                     and 1 not in router.replicas(), 60.0):
            print(f"autoscale_smoke: FAIL pool never recovered past "
                  f"the kill (members {router.replicas()})")
            return 1
        stop.set()
        for t in loaders:
            t.join(60.0)
        if failures:
            print(f"autoscale_smoke: FAIL client failures across the "
                  f"mid-ramp kill: {failures[:3]}")
            return 1
        if _counter(registry, "fleet_failovers_total") < 1:
            print("autoscale_smoke: FAIL no failover recorded "
                  "despite kill")
            return 1
    finally:
        stop.set()
        for t in loaders:
            t.join(10.0)
        faultinject.clear()
        auto.drain(drain_owned=True)
        router.close()
        rep0.drain(grace_s=5.0)

    # ---- dry-budget amplification cap (fresh, tiny, deterministic)
    fdir = os.path.join(d, "fleet_budget")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.5,
                         retries=4, retry_budget_capacity=0.0,
                         retry_budget_ratio=0.0, empty_pool_wait_s=1.0,
                         default_deadline_ms=30_000)
    reps = {r: FleetReplica(fdir, r, model=mlp_zip,
                            default_deadline_ms=30_000)
            for r in (0, 1)}
    try:
        if not router.wait_for_replicas(2, timeout_s=30.0):
            print("autoscale_smoke: FAIL budget fleet never formed")
            return 1
        faultinject.set_schedule(FaultSchedule(faults=[
            Fault("kill_replica", rank=0, at_call=1),
            Fault("kill_replica", rank=1, at_call=1)]))
        d0 = _counter(registry, "fleet_dispatches_total")
        cli = KerasClient(router.host, router.port)
        err = None
        try:
            cli.predict(x, model=mlp_zip)
        except RuntimeError as e:
            err = str(e)
        finally:
            cli.close()
        dispatches = _counter(registry, "fleet_dispatches_total") - d0
        if err is None or "retry budget exhausted" not in err:
            print(f"autoscale_smoke: FAIL dry-budget dispatch should "
                  f"surface the structured exhaustion error, got "
                  f"{err!r}")
            return 1
        if dispatches != 2:
            print(f"autoscale_smoke: FAIL amplification not capped "
                  f"({dispatches} dispatches; want initial + one free "
                  f"reroute = 2)")
            return 1
        if _counter(registry, "fleet_retry_budget_exhausted_total") < 1:
            print("autoscale_smoke: FAIL budget exhaustion never "
                  "counted")
            return 1
        print(f"autoscale_smoke: kill+budget — zero failures across "
              f"kill, dry-budget amplification {dispatches} dispatches")
        return 0
    finally:
        faultinject.clear()
        router.close()
        for rep in reps.values():
            rep.drain(grace_s=5.0)


def _phase_brownout(d, mlp_zip, x, KerasClient, FleetReplica,
                    FleetRouter, FleetAutoscaler, faultinject, Fault,
                    FaultSchedule, registry) -> int:
    """Sustained overload with nothing left to spawn: the controller
    flips brownout; bulk sheds structurally (live connection,
    retry_after_ms) while interactive latency stays inside the SLO."""
    slo_s = 2.5
    fdir = os.path.join(d, "fleet_brownout")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.5,
                         max_concurrency=2, queue_depth=24,
                         max_queue_wait_s=10.0,
                         default_deadline_ms=60_000)
    rep0 = FleetReplica(fdir, 0, model=mlp_zip, max_concurrency=8,
                        queue_depth=32, default_deadline_ms=30_000)
    auto = FleetAutoscaler(router, _spawn_fn(fdir, mlp_zip, FleetReplica),
                           min_replicas=1, max_replicas=1, queue_high=3,
                           up_ticks=2, down_ticks=1000, tick_s=0.25,
                           brownout=True, brownout_enter_ticks=3,
                           brownout_exit_ticks=6)
    stop = threading.Event()
    lock = threading.Lock()
    failures, lat_after = [], []
    sheds = {"n": 0, "structured": True}
    loaders = []
    try:
        if not router.wait_for_replicas(1, timeout_s=30.0):
            print("autoscale_smoke: FAIL seed replica never admitted")
            return 1
        faultinject.set_schedule(_stall_schedule(
            Fault, FaultSchedule, ranks=(0,), per_rank=3000,
            duration=0.15))

        def interactive(i):
            while not stop.is_set():
                t0 = time.monotonic()
                try:
                    cli = KerasClient(router.host, router.port)
                    try:
                        cli.predict(x, model=mlp_zip)
                    finally:
                        cli.close()
                except Exception as e:  # noqa: BLE001 — the gate
                    with lock:
                        failures.append(f"interactive {i}: "
                                        f"{type(e).__name__}: {e}")
                    return
                if router.brownout:
                    with lock:
                        lat_after.append(time.monotonic() - t0)
                time.sleep(0.02)

        def bulk(i):
            # one persistent raw connection per loader: a shed must be
            # an envelope on a LIVE socket (the next request on the
            # same connection still answers), never a hangup
            try:
                with socket.create_connection(
                        (router.host, router.port), timeout=60) as s:
                    s.settimeout(60)
                    f = s.makefile("rwb")
                    while not stop.is_set():
                        f.write((json.dumps(
                            {"op": "predict", "features": x,
                             "model": mlp_zip, "priority": "bulk"})
                            + "\n").encode())
                        f.flush()
                        line = f.readline()
                        if not line:
                            raise ConnectionError("hangup on shed")
                        resp = json.loads(line)
                        if resp.get("error") == "SHED":
                            with lock:
                                sheds["n"] += 1
                                if resp.get("retry_after_ms") is None:
                                    sheds["structured"] = False
                        elif resp.get("error") is not None \
                                and resp["error"] != "DEADLINE":
                            raise RuntimeError(str(resp))
                        time.sleep(0.05)
                    f.close()
            except Exception as e:  # noqa: BLE001 — the gate itself
                with lock:
                    failures.append(f"bulk {i}: "
                                    f"{type(e).__name__}: {e}")

        loaders = [threading.Thread(target=interactive, args=(i,),
                                    daemon=True) for i in range(6)]
        loaders += [threading.Thread(target=bulk, args=(i,),
                                     daemon=True) for i in range(3)]
        for t in loaders:
            t.start()
        if not _wait(lambda: router.brownout, 45.0):
            print(f"autoscale_smoke: FAIL brownout never entered "
                  f"(queued {router.load_snapshot()['queued']})")
            return 1
        rz = router._readyz()
        if not rz.get("brownout") or not rz.get("ready"):
            print(f"autoscale_smoke: FAIL readyz during brownout "
                  f"(brownout {rz.get('brownout')}, ready "
                  f"{rz.get('ready')})")
            return 1
        time.sleep(3.0)  # serve a while inside brownout
        with lock:
            if not sheds["n"] or not sheds["structured"]:
                print(f"autoscale_smoke: FAIL sheds during brownout "
                      f"(n {sheds['n']}, structured "
                      f"{sheds['structured']})")
                return 1
        # storm over: stalls off, loaders stopped, calm exits brownout
        faultinject.clear()
        stop.set()
        for t in loaders:
            t.join(60.0)
        if failures:
            print(f"autoscale_smoke: FAIL hard failures during "
                  f"brownout: {failures[:3]}")
            return 1
        with lock:
            lat = sorted(lat_after)
        if not lat:
            print("autoscale_smoke: FAIL no interactive requests "
                  "completed inside brownout")
            return 1
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        if p99 > slo_s:
            print(f"autoscale_smoke: FAIL interactive p99 {p99:.2f}s "
                  f"breached the {slo_s}s SLO inside brownout "
                  f"({len(lat)} samples)")
            return 1
        if not _wait(lambda: not router.brownout, 30.0):
            print("autoscale_smoke: FAIL brownout never exited after "
                  "the storm")
            return 1
        # degraded mode over: bulk serves again
        cli = KerasClient(router.host, router.port)
        try:
            cli.request(op="predict", features=x, model=mlp_zip,
                        priority="bulk")
        finally:
            cli.close()
        entries = _counter(registry, "fleet_brownout_entries_total")
        shed_total = _counter(registry, "fleet_brownout_sheds_total")
        if entries < 1 or shed_total < 1:
            print(f"autoscale_smoke: FAIL brownout accounting "
                  f"(entries {entries}, sheds {shed_total})")
            return 1
        print(f"autoscale_smoke: brownout — {sheds['n']} bulk sheds "
              f"(structured), interactive p99 {p99:.2f}s over "
              f"{len(lat)} in-brownout requests")
        return 0
    finally:
        stop.set()
        for t in loaders:
            t.join(10.0)
        faultinject.clear()
        auto.drain(drain_owned=True)
        router.close()
        rep0.drain(grace_s=5.0)


def _phase_quarantine(d, mlp_zip, x, KerasClient, FleetReplica,
                      FleetRouter, FleetAutoscaler, faultinject, Fault,
                      FaultSchedule, registry) -> int:
    """A crash-looping rank is quarantined after two strikes while the
    stable member keeps serving; the next healthy incarnation is
    re-admitted once the probation delay elapses."""
    fdir = os.path.join(d, "fleet_flap")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.0,
                         flap_window_s=10.0, flap_strikes=2,
                         flap_quarantine_base_s=1.5,
                         flap_quarantine_max_s=6.0,
                         default_deadline_ms=60_000)
    rep0 = FleetReplica(fdir, 0, model=mlp_zip,
                        default_deadline_ms=30_000)
    flapper = None
    try:
        if not router.wait_for_replicas(1, timeout_s=30.0):
            print("autoscale_smoke: FAIL stable replica never admitted")
            return 1
        faultinject.set_schedule(FaultSchedule(faults=[
            Fault("flap_replica", rank=5, count=2, duration=0.2)]))
        flapper = FleetReplica(fdir, 5, model=mlp_zip,
                               default_deadline_ms=30_000)
        t_end = time.monotonic() + 60.0
        while (_counter(registry, "fleet_quarantines_total") < 1
               and time.monotonic() < t_end):
            if not flapper.alive:
                flapper = FleetReplica(fdir, 5, model=mlp_zip,
                                       default_deadline_ms=30_000)
            time.sleep(0.1)
        if _counter(registry, "fleet_quarantines_total") < 1:
            print("autoscale_smoke: FAIL flapping rank never "
                  "quarantined")
            return 1
        if not router.quarantined(5):
            print("autoscale_smoke: FAIL quarantine not visible on "
                  "the router")
            return 1
        # the pool serves on the stable member throughout probation
        cli = KerasClient(router.host, router.port)
        try:
            cli.predict(x, model=mlp_zip)
        finally:
            cli.close()
        # the fault spent its incarnations: the next spawn is healthy
        if not flapper.alive:
            flapper = FleetReplica(fdir, 5, model=mlp_zip,
                                   default_deadline_ms=30_000)
        if not router.wait_for_replicas(2, timeout_s=30.0) \
                or 5 not in router.replicas():
            print(f"autoscale_smoke: FAIL healthy incarnation never "
                  f"re-admitted after probation "
                  f"(members {router.replicas()})")
            return 1
        if not flapper.alive:
            print("autoscale_smoke: FAIL re-admitted incarnation died "
                  "(fault should be spent)")
            return 1
        print("autoscale_smoke: quarantine — 2 strikes, probation, "
              "healthy incarnation re-admitted")
        return 0
    finally:
        faultinject.clear()
        router.close()
        if flapper is not None:
            flapper.drain(grace_s=5.0)
        rep0.drain(grace_s=5.0)


if __name__ == "__main__":
    sys.exit(main())
