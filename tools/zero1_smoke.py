#!/usr/bin/env python
"""zero1 parity smoke stage (tools/run_checks.sh): on a dp=2 CPU mesh,
train the same seeded MLP under the replicated and the ZeRO-1
weight-update layouts — with ``gradient_accumulation=4`` and a label
mask — and require (1) the fp32 loss sequences to be BITWISE equal (the
tentpole's exact-parity guarantee: zero1 is an execution-layout change,
not an algorithm change), (2) the optax state leaves to actually live
as (2, chunk) views sharded over 'data' (1/2 per replica), and (3) the
analytic per-update comm bytes reported by ``profiling/cost.py`` to
drop vs the replicated layout at that accumulation depth. Exit 0 = the
weight-update sharding path is wired end to end.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

DP = 2
STEPS = 4
ACCUM = 4


def main() -> int:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass  # XLA_FLAGS above already forced the device count
    if len(jax.devices()) < DP:
        print(f"zero1_smoke: FAIL need {DP} cpu devices, "
              f"have {jax.devices()}")
        return 1

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import MeshContext, ParallelTrainer
    from deeplearning4j_tpu.profiling.cost import dp_comm_bytes_per_update

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(12345).updater("adam", learning_rate=0.05)
                .weight_init("xavier")
                .list()
                .layer(DenseLayer(n_out=17, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(6))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    ds = DataSet(x, y)
    ds.labels_mask = (rng.random(16) > 0.25).astype(np.float32)

    def run(mode):
        net = build()
        trainer = ParallelTrainer(
            net, MeshContext.create(n_data=DP, n_model=1),
            gradient_accumulation=ACCUM, weight_update_sharding=mode)
        losses = [np.float32(np.asarray(trainer.fit_batch(ds)))
                  for _ in range(STEPS)]
        return net, losses

    net_rep, losses_rep = run("off")
    net_z, losses_z = run("zero1")

    if any(a.tobytes() != b.tobytes()
           for a, b in zip(losses_rep, losses_z)):
        print(f"zero1_smoke: FAIL loss sequences differ\n"
              f"  replicated: {losses_rep}\n  zero1:      {losses_z}")
        return 1
    pr = np.asarray(net_rep.params_flat())
    pz = np.asarray(net_z.params_flat())
    if pr.tobytes() != pz.tobytes():
        print("zero1_smoke: FAIL params diverged bitwise")
        return 1

    sharded = [l for l in jax.tree_util.tree_leaves(net_z.opt_state)
               if getattr(l, "ndim", 0) >= 1]
    bad = [l for l in sharded
           if l.shape[0] != DP
           or str(getattr(l.sharding, "spec", "")) != "PartitionSpec('data',)"]
    if not sharded or bad:
        print(f"zero1_smoke: FAIL updater state not (dp, chunk)-sharded "
              f"over 'data': {[(l.shape, str(l.sharding)) for l in bad]}")
        return 1
    full = sum(l.size for l in sharded)
    local = sum(s.data.size for l in sharded
                for s in l.addressable_shards
                if s.device == jax.devices()[0])
    if local * DP != full:
        print(f"zero1_smoke: FAIL device 0 holds {local} of {full} "
              f"updater elements (want 1/{DP})")
        return 1

    p = pr.size
    rep_bytes = dp_comm_bytes_per_update(p, DP, 4, ACCUM, "off")
    z_bytes = dp_comm_bytes_per_update(p, DP, 4, ACCUM, "zero1")
    if not z_bytes < rep_bytes:
        print(f"zero1_smoke: FAIL comm model: zero1 {z_bytes} >= "
              f"replicated {rep_bytes} bytes/update at accum={ACCUM}")
        return 1

    print(f"zero1_smoke: OK — {STEPS} steps bitwise loss-equal "
          f"(accum={ACCUM}, masked), updater state 1/{DP} per replica, "
          f"comm/update {z_bytes} vs {rep_bytes} bytes "
          f"({z_bytes / rep_bytes:.2f}x)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
