"""Pipeline-vs-data-parallel efficiency on the 8-virtual-device CPU mesh
(VERDICT r4 next #4). Times the same global batch through:

- pure dp (ParallelTrainer, 8-way data sharding)
- pp=8 GPipe ring (PipelineTrainer, M=8 microbatches)
- dp4 x pp2 composition (M=2)

and reports the activation-aware partitioner's ring payloads — on the
real conv stack (where both objectives agree) and on the synthetic
divergence case where the DP trades param balance for a 100x smaller
ring payload. Writes PIPELINE_EFFICIENCY.md next to the repo root.

This is a semantics/overhead comparison on virtual CPU devices — it
bounds the GPipe bubble + switch + padded-ring cost relative to dp on
identical hardware, not real ICI bandwidth. Run on a real pod slice the
same way (the script only needs jax.devices()).
"""

import os
import sys
import time

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from jax.sharding import Mesh  # noqa: E402

from deeplearning4j_tpu import InputType, NeuralNetConfiguration  # noqa: E402
from deeplearning4j_tpu.datasets import DataSet  # noqa: E402
from deeplearning4j_tpu.nn.layers import (  # noqa: E402
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.parallel import MeshContext, ParallelTrainer  # noqa: E402
from deeplearning4j_tpu.parallel.pipeline import (  # noqa: E402
    PipelineTrainer, _mln_boundary_elems, partition_stages,
)

STEPS, WARMUP, BATCH = 12, 2, 32


def conv_conf(seed=7):
    return (NeuralNetConfiguration.builder().seed(seed)
            .updater("sgd", learning_rate=0.05).weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3),
                                    convolution_mode="same",
                                    activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=16, kernel_size=(3, 3),
                                    convolution_mode="same",
                                    activation="relu"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=64, activation="relu"))
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(16, 16, 1)).build())


def batch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, 16, 16, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, BATCH)]
    return DataSet(x, y)


def timed(trainer, ds):
    for _ in range(WARMUP):
        trainer.fit_batch(ds)
    jax.block_until_ready(trainer.net.params)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        trainer.fit_batch(ds)
    jax.block_until_ready(trainer.net.params)
    dt = time.perf_counter() - t0
    return BATCH * STEPS / dt, 1000 * dt / STEPS


def main():
    devs = jax.devices()
    assert len(devs) >= 8, devs
    ds = batch()
    rows = []

    tr = ParallelTrainer(MultiLayerNetwork(conv_conf()).init(),
                         MeshContext.create(n_data=8, n_model=1))
    sps, ms = timed(tr, ds)
    rows.append(("pure dp (dp=8)", sps, ms, "-"))
    dp_sps = sps

    net = MultiLayerNetwork(conv_conf()).init()
    mesh = Mesh(np.array(devs[:8]), axis_names=("pp",))
    tr = PipelineTrainer(net, mesh=mesh, n_microbatches=8)
    sps, ms = timed(tr, ds)
    rows.append((f"pp=8, M=8 (stages {tr.stages})", sps, ms,
                 f"{sps / dp_sps:.2f}"))

    net = MultiLayerNetwork(conv_conf()).init()
    mesh = Mesh(np.array(devs[:8]).reshape(4, 2), axis_names=("dp", "pp"))
    tr = PipelineTrainer(net, mesh=mesh, n_microbatches=2)
    sps, ms = timed(tr, ds)
    rows.append((f"dp=4 x pp=2, M=2 (stages {tr.stages})", sps, ms,
                 f"{sps / dp_sps:.2f}"))

    # partitioner: ring payload under param-only vs activation-aware cuts
    net = MultiLayerNetwork(conv_conf()).init()
    body = net.layers[:-1]
    act = _mln_boundary_elems(net.conf, body)
    p_only = partition_stages(body, net.params, 2)
    p_act = partition_stages(body, net.params, 2, act_elems=act)

    def payload(stages):
        cuts = []
        pos = 0
        for st in stages[:-1]:
            pos += len(st)
            cuts.append(act[pos - 1])
        return max(cuts) if cuts else 0

    lines = [
        "# Pipeline efficiency (8 virtual CPU devices)",
        "",
        f"Model: conv stack {[type(l).__name__ for l in body]} -> softmax, "
        f"global batch {BATCH}, {STEPS} timed steps after {WARMUP} warmup.",
        "Generated by tools/pipeline_efficiency.py; re-run on a pod slice "
        "for ICI numbers.",
        "",
        "| configuration | samples/s | ms/step | vs dp |",
        "|---|---|---|---|",
    ]
    for name, sps, ms, rel in rows:
        lines.append(f"| {name} | {sps:.0f} | {ms:.1f} | {rel} |")
    # divergence demo: a fat tensor at the param-balanced boundary forces
    # the DP to trade a 100-vs-300 param imbalance for a 100x smaller
    # ring payload (same case as the pinned unit test)
    dlayers = [object()] * 4
    dparams = {i: {"W": np.zeros((100,))} for i in range(4)}
    dact = [10.0, 1000.0, 10.0]
    d_only = partition_stages(dlayers, dparams, 2)
    d_act = partition_stages(dlayers, dparams, 2, act_elems=dact)

    def dpayload(st):
        return dact[len(st[0]) - 1]

    lines += [
        "",
        "## Activation-aware partitioning (S=2)",
        "",
        f"- this conv stack: per-boundary activation elems/sample {act}; "
        f"param-balanced cut {p_only} (payload {payload(p_only):.0f}) == "
        f"activation-aware cut {p_act} (payload {payload(p_act):.0f}) — "
        "in shallow feed-forward stacks the fat boundaries are also the "
        "param-light ones, so both objectives pick the same late cut.",
        "- where they diverge (equal-param layers, fat middle tensor "
        f"{dact}): param-balanced {d_only} crosses payload "
        f"{dpayload(d_only):.0f}; activation-aware {d_act} accepts a "
        f"100-vs-300 param imbalance for payload {dpayload(d_act):.0f} "
        "(100x less ppermute traffic every tick; pinned by "
        "tests/test_pipeline_trainer.py::"
        "test_partition_activation_aware_moves_cut).",
        "",
        "The GPipe bubble costs (S-1)/(M+S-1) of ideal throughput (pp=8, "
        "M=8 -> 47% ceiling before ring costs), so pure dp wins whenever "
        "the model fits — pipeline is for models that do NOT fit one "
        "device; the dp x pp composition is the practical point.",
    ]
    # flagship: ResNet-50 DAG cut choice + ring payloads at S=4
    from deeplearning4j_tpu.models.resnet import resnet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel.pipeline import (
        GraphPipelineTrainer, _type_elems)
    rnet = ComputationGraph(resnet50(height=32, width=32,
                                     dtype="float32")).init()
    gmesh = Mesh(np.array(devs[:4]), axis_names=("pp",))
    gtr = GraphPipelineTrainer(rnet, mesh=gmesh, n_microbatches=4)
    rt = rnet.conf.resolved_types
    payloads = [sum(int(_type_elems(rt[n])) for n in b)
                for b in gtr.boundaries[1:]]
    lines += [
        "",
        "## ResNet-50 (32x32) DAG partition, S=4 (activation-aware DP)",
        "",
        f"- stage sizes (nodes): {[len(s) for s in gtr.stages]}",
        f"- stage boundaries: {gtr.boundaries[1:]} -> ring payloads "
        f"{payloads} elems/sample (max {max(payloads)})",
    ]

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "PIPELINE_EFFICIENCY.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print("\n".join(lines))


if __name__ == "__main__":
    main()
