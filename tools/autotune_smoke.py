#!/usr/bin/env python
"""autotune smoke stage (tools/run_checks.sh): on a dp=2 CPU mesh,
search a LeNet-sized configuration space end to end and gate the
ISSUE-13 acceptance criteria:

1. the whole search — enumerate, graphcheck-prune, rank, probe —
   completes in under 60 seconds;
2. the winner's MEASURED probe step time is no slower than the naive
   default config's (MeshContext.create()'s all-devices dp, fp32,
   replicated update) — the tuner can speed you up or leave you where
   you were, never slow you down;
3. every probed config recorded a finite ``measured_vs_predicted_gap``
   and the ``autotune_*`` calibration metrics landed in the process
   registry (the same objects ``/api/metrics`` serves);
4. probe parity: training at the chosen config through the
   ``TunedConfig`` (``tuned=``) is BITWISE identical — losses and final
   params — to hand-building the same trainer, so autotuning changes
   *which* config runs but never the math of a given config.

Exit 0 = the self-driving configuration loop is wired end to end.
"""

import math
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

DP = 2
BATCH = 16
SEARCH_BUDGET_S = 60.0


def main() -> int:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass
    if len(jax.devices()) < DP:
        print(f"autotune_smoke: FAIL need {DP} cpu devices, "
              f"have {jax.devices()}")
        return 1

    from deeplearning4j_tpu.autotune import autotune, default_candidate
    from deeplearning4j_tpu.models.lenet import lenet_mnist
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel import MeshContext, ParallelTrainer
    from deeplearning4j_tpu.profiling.metrics import get_registry

    net = MultiLayerNetwork(lenet_mnist()).init()

    # ---- 1. the search completes inside the budget
    t0 = time.perf_counter()
    tuned = autotune(net, devices=DP, global_batch=BATCH, top_k=2,
                     probe_steps=2)
    elapsed = time.perf_counter() - t0
    print(tuned.summary())
    if elapsed >= SEARCH_BUDGET_S:
        print(f"autotune_smoke: FAIL search took {elapsed:.1f}s "
              f"(budget {SEARCH_BUDGET_S:.0f}s)")
        return 1

    # ---- 2. the winner measures no slower than the naive default
    default = default_candidate(DP, BATCH)
    by_cfg = {p.config: p for p in tuned.probes}
    if default.slug() not in by_cfg:
        print(f"autotune_smoke: FAIL default config {default.slug()} "
              f"was not probed (probes: {sorted(by_cfg)})")
        return 1
    default_s = by_cfg[default.slug()].measured_step_s
    if tuned.measured_step_s is None \
            or tuned.measured_step_s > default_s:
        print(f"autotune_smoke: FAIL winner measured "
              f"{tuned.measured_step_s}s/step, slower than the default "
              f"config's {default_s}s/step")
        return 1

    # ---- 3. finite calibration gaps, exported as autotune_* metrics
    bad = [p.config for p in tuned.probes
           if not math.isfinite(p.measured_vs_predicted_gap)
           or p.measured_vs_predicted_gap <= 0]
    if not tuned.probes or bad:
        print(f"autotune_smoke: FAIL probes without a finite positive "
              f"gap: {bad or '(no probes ran)'}")
        return 1
    snap = get_registry().snapshot("autotune_")
    want = ("autotune_searches_total", "autotune_probes_total",
            "autotune_best_measured_step_s",
            "autotune_measured_vs_predicted_gap")
    missing = [k for k in want if not snap.get(k)]
    if missing:
        print(f"autotune_smoke: FAIL autotune_* metrics missing/zero: "
              f"{missing} (have {sorted(snap)})")
        return 1
    gap_gauges = [k for k in snap if k.startswith("autotune_gap_")]
    if len(gap_gauges) < len(tuned.probes):
        print(f"autotune_smoke: FAIL per-config gap gauges missing: "
              f"{gap_gauges} for {len(tuned.probes)} probes")
        return 1

    # ---- 4. probe parity: tuned= vs hand-built, bitwise
    from deeplearning4j_tpu.autotune.probe import synthesize_batch
    ds = synthesize_batch(net.conf, BATCH)

    def run(build_trainer, steps=3):
        fresh = MultiLayerNetwork(lenet_mnist()).init()
        trainer = build_trainer(fresh)
        losses = [np.float32(np.asarray(trainer.fit_batch(ds)))
                  for _ in range(steps)]
        return losses, np.asarray(fresh.params_flat())

    losses_t, params_t = run(lambda n: tuned.trainer(n))
    losses_h, params_h = run(lambda n: ParallelTrainer(
        n, MeshContext.create(n_data=tuned.dp, n_model=tuned.tp,
                              n_seq=tuned.sp),
        **tuned.trainer_kwargs()))
    if any(a.tobytes() != b.tobytes() for a, b in zip(losses_t, losses_h)):
        print(f"autotune_smoke: FAIL tuned-vs-hand loss sequences "
              f"differ\n  tuned: {losses_t}\n  hand:  {losses_h}")
        return 1
    if params_t.tobytes() != params_h.tobytes():
        print("autotune_smoke: FAIL tuned-vs-hand params diverged")
        return 1

    print(f"autotune_smoke: OK — {tuned.candidate.slug()} in "
          f"{elapsed:.1f}s ({tuned.search.get('candidates')} candidates, "
          f"{tuned.search.get('pruned_illegal')} illegal, "
          f"{tuned.search.get('pruned_hbm')} over-budget, "
          f"{len(tuned.probes)} probed), winner "
          f"{tuned.measured_step_s:.4f}s/step <= default "
          f"{default_s:.4f}s/step, gaps finite, tuned==hand bitwise")
    return 0


if __name__ == "__main__":
    sys.exit(main())
