#!/usr/bin/env python
"""zero2 parity smoke stage (tools/run_checks.sh): on a dp=2 CPU mesh,
train the same seeded MLP under the replicated and the ZeRO-2
weight-update layouts — with ``gradient_accumulation=4`` and a label
mask — and require (1) the fp32 loss sequences AND final params to be
BITWISE equal (zero2, like zero1, is an execution-layout change, not an
algorithm change), (2) the optax state leaves to live as (2, chunk)
views sharded over 'data' (1/2 per replica), (3) the analytic cost
model to report zero2 per-update comm <= zero1's and gradient HBM
divided by dp (``profiling/cost.py``), and (4) the bf16 mixed-precision
policy to compose: a bf16 zero2 run trains finitely while the fp32
master weights stay float32. Exit 0 = the zero2 + precision path is
wired end to end.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

DP = 2
STEPS = 4
ACCUM = 4


def main() -> int:
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", DP)
    except AttributeError:
        pass  # XLA_FLAGS above already forced the device count
    if len(jax.devices()) < DP:
        print(f"zero2_smoke: FAIL need {DP} cpu devices, "
              f"have {jax.devices()}")
        return 1

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import MeshContext, ParallelTrainer
    from deeplearning4j_tpu.profiling.cost import (dp_comm_bytes_per_update,
                                                   dp_gradient_hbm_bytes)

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(12345).updater("adam", learning_rate=0.05)
                .weight_init("xavier")
                .list()
                .layer(DenseLayer(n_out=17, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(6))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    ds = DataSet(x, y)
    ds.labels_mask = (rng.random(16) > 0.25).astype(np.float32)

    def run(mode, precision=None):
        net = build()
        trainer = ParallelTrainer(
            net, MeshContext.create(n_data=DP, n_model=1),
            gradient_accumulation=ACCUM, weight_update_sharding=mode,
            precision=precision)
        losses = [np.float32(np.asarray(trainer.fit_batch(ds)))
                  for _ in range(STEPS)]
        return net, losses

    net_rep, losses_rep = run("off")
    net_z, losses_z = run("zero2")

    if any(a.tobytes() != b.tobytes()
           for a, b in zip(losses_rep, losses_z)):
        print(f"zero2_smoke: FAIL loss sequences differ\n"
              f"  replicated: {losses_rep}\n  zero2:      {losses_z}")
        return 1
    pr = np.asarray(net_rep.params_flat())
    pz = np.asarray(net_z.params_flat())
    if pr.tobytes() != pz.tobytes():
        print("zero2_smoke: FAIL params diverged bitwise")
        return 1

    sharded = [l for l in jax.tree_util.tree_leaves(net_z.opt_state)
               if getattr(l, "ndim", 0) >= 1]
    bad = [l for l in sharded
           if l.shape[0] != DP
           or str(getattr(l.sharding, "spec", "")) != "PartitionSpec('data',)"]
    if not sharded or bad:
        print(f"zero2_smoke: FAIL updater state not (dp, chunk)-sharded "
              f"over 'data': {[(l.shape, str(l.sharding)) for l in bad]}")
        return 1

    p = pr.size
    z1_bytes = dp_comm_bytes_per_update(p, DP, 4, ACCUM, "zero1")
    z2_bytes = dp_comm_bytes_per_update(p, DP, 4, ACCUM, "zero2")
    if not z2_bytes <= z1_bytes:
        print(f"zero2_smoke: FAIL comm model: zero2 {z2_bytes} > "
              f"zero1 {z1_bytes} bytes/update at accum={ACCUM}")
        return 1
    g_full = dp_gradient_hbm_bytes(p, DP, 4, "zero1")
    g_z2 = dp_gradient_hbm_bytes(p, DP, 4, "zero2")
    if not (g_z2 < g_full and g_z2 == -(-g_full // DP)):
        print(f"zero2_smoke: FAIL gradient HBM model: zero2 {g_z2} vs "
              f"zero1 {g_full} (want exactly 1/{DP})")
        return 1

    # bf16 policy composes with zero2: finite losses, fp32 masters
    net_bf, losses_bf = run("zero2", precision="bf16")
    if not all(np.isfinite(losses_bf)):
        print(f"zero2_smoke: FAIL bf16 zero2 run went non-finite: "
              f"{losses_bf}")
        return 1
    master_dtypes = {str(l.dtype)
                     for l in jax.tree_util.tree_leaves(net_bf.params)}
    if master_dtypes != {"float32"}:
        print(f"zero2_smoke: FAIL bf16 master weights not fp32: "
              f"{master_dtypes}")
        return 1

    print(f"zero2_smoke: OK — {STEPS} steps bitwise loss-equal "
          f"(accum={ACCUM}, masked), updater state 1/{DP} per replica, "
          f"comm/update {z2_bytes} <= zero1 {z1_bytes} bytes, gradient "
          f"HBM {g_z2} = zero1 {g_full} / {DP}, bf16 masters fp32")
    return 0


if __name__ == "__main__":
    sys.exit(main())
