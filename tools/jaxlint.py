#!/usr/bin/env python
"""jaxlint CLI: AST lint for JAX anti-patterns in traced code.

Usage:
    python tools/jaxlint.py <file-or-dir> [...]   # lint (default: package)
    python tools/jaxlint.py --list-rules          # print the rule table

Exit status: 0 when no findings survive suppression, 1 otherwise.
Suppress a finding inline with ``# jaxlint: disable=<RULE> -- <reason>``
(the reason is mandatory — reasonless suppressions are JL000 findings).

No jax import, no code execution: safe to run anywhere, fast enough for
a pre-commit hook. Wired into tools/run_checks.sh as the lint gate.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.analysis.findings import format_findings  # noqa: E402
from deeplearning4j_tpu.analysis.jaxlint import RULES, RULE_SEVERITY, lint_paths  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/directories to lint "
                         "(default: deeplearning4j_tpu)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, (slug, desc) in sorted(RULES.items()):
            print(f"{rule}  {slug:<22} {RULE_SEVERITY[rule]:<8} {desc}")
        return 0

    paths = args.paths or [os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deeplearning4j_tpu")]
    findings = lint_paths(paths)
    if findings:
        print(format_findings(findings, header="jaxlint findings:"))
        return 1
    print("jaxlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
