#!/usr/bin/env python
"""shardcheck CLI: static analysis of COMPILED step programs.

Usage:
    python tools/shardcheck.py --self-check    # fixture gate (CI)
    python tools/shardcheck.py --contracts     # zero1/zero2/bf16 gate
    python tools/shardcheck.py step.hlo --wus zero1 --dp 2 \
        [--accum K] [--param-count N] [--precision bf16]
    python tools/shardcheck.py --list-rules

``--self-check`` validates the analyzer itself against the
compiled-program fixtures in ``analysis/fixtures.py``: every SC rule
must fire on its KNOWN_BAD program and stay silent (nothing above INFO)
on every KNOWN_GOOD program.

``--contracts`` statically re-proves the compiled-program contracts the
bitwise smoke gates (zero1_smoke / zero2_smoke) then verify at runtime
— on CPU, in seconds, with no training step executed:

  1. zero1 and zero2 accum=1 steps carry a reduce-scatter(-form)
     gradient reduction + one param all-gather per leaf and NO
     full-size gradient all-reduce on the update path (SC001/SC002);
  2. the gradient-accumulation scan body keeps its per-microbatch
     replicated anchor — no collective inside the while body (SC003);
  3. the bf16 policy computes dots in bf16 while masters/loss cross the
     step boundary in fp32 (SC004);
  4. the fp32 preset is convert-op-identical to the pre-policy program
     (SC004);
  5. donation aliases are present in every compiled step (SC005);
  6. the HLO-vs-cost-model comm-bytes delta is within tolerance
     (SC007).

File mode parses a saved ``compiled.as_text()`` dump (no jax needed for
the parse; the declared layout comes from the flags) — useful for
analyzing a program captured on a TPU host from a dev box.

Wired into ``tools/run_checks.sh`` BEFORE the bitwise smokes: a
contract violation fails in seconds instead of minutes.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the fixture/contract programs lower on a dp=2 CPU mesh
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

from deeplearning4j_tpu.analysis.findings import (  # noqa: E402
    Severity, format_findings, has_errors,
)
from deeplearning4j_tpu.analysis.shardcheck import (  # noqa: E402
    RULES, RULE_SEVERITY, StepProgram, check_step_program,
)


def _significant(findings):
    """Findings above INFO — the self-check/contract 'dirty' bar."""
    return [f for f in findings if f.severity != Severity.INFO]


def self_check() -> int:
    from deeplearning4j_tpu.analysis.fixtures import (
        SC_KNOWN_BAD, SC_KNOWN_GOOD,
    )
    ok = True
    for name, rule, make in SC_KNOWN_BAD:
        t0 = time.perf_counter()
        program, kwargs = make()
        rules = {f.rule for f in check_step_program(program, **kwargs)}
        dt = time.perf_counter() - t0
        if rule in rules:
            print(f"  known-bad  {name:<24} fired {rule} ({dt:.1f}s, ok)")
        else:
            ok = False
            print(f"  known-bad  {name:<24} FAILED: wanted {rule}, "
                  f"got {sorted(rules) or 'no findings'}")
    for name, make in SC_KNOWN_GOOD:
        t0 = time.perf_counter()
        program, kwargs = make()
        bad = _significant(check_step_program(program, **kwargs))
        dt = time.perf_counter() - t0
        if bad:
            ok = False
            print(f"  known-good {name:<24} FAILED: unexpected findings")
            for f in bad:
                print(f"    {f}")
        else:
            print(f"  known-good {name:<24} clean ({dt:.1f}s, ok)")
    print("shardcheck self-check:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


def contracts() -> int:
    """Statically re-prove the zero1/zero2/bf16/donation program
    contracts on the REAL ParallelTrainer steps (dp=2 CPU mesh)."""
    from deeplearning4j_tpu.analysis.fixtures import _sc_trainer_program
    t_total = time.perf_counter()
    failures = []

    def gate(label, check):
        t0 = time.perf_counter()
        try:
            problems = check()
        except Exception as e:  # noqa: BLE001 — a crash IS a gate failure
            problems = [f"crashed: {e!r}"]
        dt = time.perf_counter() - t0
        status = "PASS" if not problems else "FAIL"
        print(f"  {label:<52} {status}  ({dt:4.1f}s)")
        for p in problems:
            print(f"      {p}")
            failures.append(f"{label}: {p}")

    def sharded_update_contract(wus):
        def check():
            program, ctx = _sc_trainer_program(wus, 1)
            problems = [str(f) for f in
                        _significant(check_step_program(program, **ctx))]
            mod = program.module
            rs = [c for c in mod.collectives
                  if c.kind == "reduce-scatter" or c.reduce_scatter_form]
            ags = [c for c in mod.collectives if c.kind == "all-gather"]
            n_leaves = len(ctx["param_leaf_sizes"])
            if len(rs) < n_leaves:
                problems.append(
                    f"expected >= {n_leaves} reduce-scatter(-form) "
                    f"gradient reductions, found {len(rs)}")
            if len(ags) != n_leaves:
                problems.append(
                    f"expected exactly {n_leaves} param all-gathers, "
                    f"found {len(ags)}")
            if not program.donation_landed:
                problems.append("no input_output_alias in the compiled "
                                "step (donation dropped)")
            return problems
        return check

    def ga_scan_contract():
        def check():
            program, ctx = _sc_trainer_program("zero2", 2)
            problems = [str(f) for f in
                        _significant(check_step_program(program, **ctx))]
            # per-microbatch all-reduces in the body are the contract's
            # expected traffic; WEIGHT re-gathers are the hazard
            body_gathers = [c for c in program.module.collectives
                            if c.in_loop_body and c.kind == "all-gather"]
            if body_gathers:
                problems.append(
                    f"{len(body_gathers)} all-gather(s) inside the "
                    "ga-scan body — the replicated anchor was lost")
            if not program.module.while_bodies:
                problems.append("no while loop found — the ga scan did "
                                "not lower as a loop (contract stale?)")
            return problems
        return check

    def bf16_contract():
        def check():
            program, ctx = _sc_trainer_program("zero2", 1, "bf16")
            problems = [str(f) for f in
                        _significant(check_step_program(program, **ctx))]
            if not any(dt == "bf16" for dt in program.dot_dtypes()):
                problems.append("no bf16 dot in the StableHLO — the "
                                "policy's casts were gated out")
            return problems
        return check

    def fp32_identity_contract():
        def check():
            program, ctx = _sc_trainer_program("zero1", 1, "fp32")
            baseline, _ = _sc_trainer_program("zero1", 1, None)
            ctx = dict(ctx)
            ctx["baseline"] = baseline
            return [str(f) for f in
                    _significant(check_step_program(program, **ctx))]
        return check

    print("shardcheck contracts (dp=2 CPU mesh, no training run):")
    gate("zero1: reduce-scatter + param gather, no full AR",
         sharded_update_contract("zero1"))
    gate("zero2: reduce-scatter + param gather, no full AR",
         sharded_update_contract("zero2"))
    gate("ga scan: replicated anchor kept (no body collective)",
         ga_scan_contract())
    gate("bf16: half dots, fp32 masters/loss at the boundary",
         bf16_contract())
    gate("fp32 preset: convert-op-identical to pre-policy",
         fp32_identity_contract())
    dt = time.perf_counter() - t_total
    print(f"shardcheck contracts: "
          f"{'PASS' if not failures else 'FAIL'} in {dt:.1f}s")
    return 0 if not failures else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("hlo", nargs="?",
                    help="a saved compiled-HLO text dump to analyze")
    ap.add_argument("--stablehlo", default=None,
                    help="the matching lowered StableHLO dump (enables "
                         "the precision/donation-request rules)")
    ap.add_argument("--wus", default="off",
                    help="declared weight_update_sharding (off|zero1|zero2)")
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--accum", type=int, default=1,
                    help="declared gradient_accumulation")
    ap.add_argument("--param-count", type=int, default=None)
    ap.add_argument("--precision", default=None)
    ap.add_argument("--expect-donation", action="store_true")
    ap.add_argument("--self-check", action="store_true",
                    help="fixture gate: every SC rule fires on its "
                         "KNOWN_BAD program, silent on KNOWN_GOOD")
    ap.add_argument("--contracts", action="store_true",
                    help="statically re-prove the zero1/zero2/bf16 "
                         "program contracts (run by run_checks.sh)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, (slug, desc) in sorted(RULES.items()):
            print(f"{rule}  {slug:<26} {RULE_SEVERITY[rule]:<8} {desc}")
        return 0
    if args.self_check:
        return self_check()
    if args.contracts:
        return contracts()
    if not args.hlo:
        ap.error("an HLO dump (or --self-check / --contracts) is required")

    with open(args.hlo, "r", encoding="utf-8") as fh:
        hlo = fh.read()
    stablehlo = ""
    if args.stablehlo:
        with open(args.stablehlo, "r", encoding="utf-8") as fh:
            stablehlo = fh.read()
    program = StepProgram(stablehlo=stablehlo, hlo=hlo)
    findings = check_step_program(
        program, weight_update_sharding=args.wus, dp=args.dp,
        gradient_accumulation=args.accum, param_count=args.param_count,
        precision=args.precision,
        expect_donation=True if args.expect_donation else None)
    if findings:
        print(format_findings(findings, header=f"{args.hlo}:"))
    else:
        print(f"{args.hlo}: clean")
    return 1 if has_errors(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
