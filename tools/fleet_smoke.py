#!/usr/bin/env python
"""Serving-fleet smoke stage (tools/run_checks.sh, ISSUE 18).

Three in-process replicas behind a ``FleetRouter`` must prove, end to
end over real sockets, the fleet's whole robustness contract:

1. **Kill-replica mid-load** — with a predict storm in flight, a
   ``kill_replica`` fault hard-kills one of three replicas (listener
   closed, connections severed, heartbeat stopped cold). Every client
   request still completes — zero client-visible failures — and
   ``fleet_failovers_total`` shows the router actually rerouted.
2. **Mid-stream generate failover** — a replica dies by schedule after
   streaming its 3rd token; the router re-prefills on a survivor (which
   joins late, behind the readyz gate) from prompt + tokens-so-far, and
   the client's assembled token stream is BITWISE the singleton
   ``greedy_generate`` sequence.
3. **Rolling drain-restart** — every replica in the fleet is replaced
   (admit successor, drain predecessor) under continuous client load
   with zero dropped requests: the drained member retires its
   heartbeat, finishes in-flight work, and raced requests reroute on
   ``DRAINING`` without charging anyone's breaker.
4. **Observability** — the ``fleet_*`` counter family is visible on the
   router's ``/api/metrics`` (Prometheus text + JSON mirror) and its
   ``/readyz`` answers 200 while members exist.

Exit 0 = the fleet edge is wired end to end.
"""

import json
import os
import socket
import sys
import tempfile
import threading
import time
import urllib.request
from urllib.error import HTTPError

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _stream_generate(host, port, tokens, max_new, model):
    """Raw streaming client: returns (partials, final response)."""
    partials = []
    with socket.create_connection((host, port), timeout=120) as s:
        s.settimeout(120)
        f = s.makefile("rwb")
        f.write((json.dumps({"op": "generate", "tokens": tokens,
                             "max_new_tokens": max_new, "model": model,
                             "stream": True}) + "\n").encode())
        f.flush()
        while True:
            line = f.readline()
            if not line:
                raise ConnectionError("router closed mid-stream")
            resp = json.loads(line)
            if resp.get("partial"):
                partials.append(int(resp["t"]))
                continue
            f.close()
            return partials, resp


def _wait_removed(router, rank, timeout_s=15.0):
    t_end = time.monotonic() + timeout_s
    while time.monotonic() < t_end:
        if rank not in router.replicas():
            return True
        time.sleep(0.05)
    return False


def _counter(registry, name):
    m = registry.get(name)
    return 0 if m is None else m.value


def main() -> int:
    import numpy as np

    from deeplearning4j_tpu import (InputType, MultiLayerNetwork,
                                    NeuralNetConfiguration)
    from deeplearning4j_tpu.datasets.iris import load_iris
    from deeplearning4j_tpu.keras.fleet import FleetReplica, FleetRouter
    from deeplearning4j_tpu.keras.server import KerasClient
    from deeplearning4j_tpu.models.gpt import gpt_tiny, greedy_generate
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.profiling.metrics import (MetricsRegistry,
                                                      set_registry)
    from deeplearning4j_tpu.resilience import faultinject
    from deeplearning4j_tpu.resilience.faultinject import (Fault,
                                                           FaultSchedule)
    from deeplearning4j_tpu.util.serializer import ModelSerializer

    registry = MetricsRegistry()
    prev = set_registry(registry)
    n0 = threading.active_count()
    try:
        conf = (NeuralNetConfiguration.builder().updater("adam")
                .learning_rate(0.05).seed(7).list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        mlp = MultiLayerNetwork(conf).init()
        gpt = ComputationGraph(gpt_tiny(vocab_size=13, seq_len=16)).init()
        with tempfile.TemporaryDirectory() as d:
            mlp_zip = os.path.join(d, "iris.zip")
            gpt_zip = os.path.join(d, "gpt.zip")
            ModelSerializer.write_model(mlp, mlp_zip)
            ModelSerializer.write_model(gpt, gpt_zip)
            x = os.path.join(d, "x.npy")
            np.save(x, load_iris().features[:4])

            for phase, fn in (("kill-under-load", _phase_kill),
                              ("mid-stream failover", _phase_midstream),
                              ("rolling drain", _phase_rolling)):
                rc = fn(d, mlp_zip, gpt_zip, x, gpt, np, KerasClient,
                        FleetReplica, FleetRouter, faultinject, Fault,
                        FaultSchedule, registry, greedy_generate)
                faultinject.clear()
                if rc != 0:
                    return rc
                print(f"fleet_smoke: phase OK — {phase}")

        t_end = time.monotonic() + 15.0
        while threading.active_count() > n0 + 2:
            if time.monotonic() > t_end:
                print(f"fleet_smoke: FAIL thread leak "
                      f"({threading.active_count()} vs baseline {n0})")
                return 1
            time.sleep(0.05)
        print("fleet_smoke: OK — kill-under-load, mid-stream generate "
              "failover (bitwise), rolling drain-restart (zero drops), "
              "fleet_* metrics served")
        return 0
    finally:
        faultinject.clear()
        set_registry(prev)


def _phase_kill(d, mlp_zip, gpt_zip, x, gpt, np, KerasClient,
                FleetReplica, FleetRouter, faultinject, Fault,
                FaultSchedule, registry, greedy_generate) -> int:
    """Three replicas, 24-predict storm, one hard-killed by schedule on
    its 3rd admitted request: zero client-visible failures."""
    fdir = os.path.join(d, "fleet_a")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.5,
                         max_concurrency=24, queue_depth=64,
                         default_deadline_ms=120_000)
    reps = {r: FleetReplica(fdir, r, model=mlp_zip, max_concurrency=8,
                            queue_depth=32, default_deadline_ms=60_000)
            for r in (0, 1, 2)}
    try:
        if not router.wait_for_replicas(3, timeout_s=30.0):
            print(f"fleet_smoke: FAIL fleet never formed "
                  f"({router.replicas()})")
            return 1
        kill = Fault("kill_replica", rank=0, at_call=3)
        faultinject.set_schedule(FaultSchedule([kill]))
        ref = None
        failures, lock = [], threading.Lock()

        def one(i):
            nonlocal ref
            try:
                cli = KerasClient(router.host, router.port)
                try:
                    got = cli.predict(x, model=mlp_zip)
                finally:
                    cli.close()
                with lock:
                    if ref is None:
                        ref = got
                    elif not np.array_equal(got, ref):
                        failures.append(f"req {i}: prediction diverged")
            except Exception as e:  # noqa: BLE001 — the gate itself
                with lock:
                    failures.append(f"req {i}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(24)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
        if failures:
            print(f"fleet_smoke: FAIL client-visible failures under "
                  f"kill_replica: {failures}")
            return 1
        if not kill.fired:
            print("fleet_smoke: FAIL kill_replica never fired")
            return 1
        if _counter(registry, "fleet_failovers_total") < 1:
            print("fleet_smoke: FAIL no failover recorded despite kill")
            return 1
        if not _wait_removed(router, 0):
            print("fleet_smoke: FAIL killed replica never removed "
                  "from membership")
            return 1
        return 0
    finally:
        faultinject.clear()
        router.close()
        for rep in reps.values():
            rep.drain(grace_s=5.0)


def _phase_midstream(d, mlp_zip, gpt_zip, x, gpt, np, KerasClient,
                     FleetReplica, FleetRouter, faultinject, Fault,
                     FaultSchedule, registry, greedy_generate) -> int:
    """A generate's replica dies after streaming 3 tokens; a survivor
    joining late (readyz-gated) continues the stream bitwise."""
    prompt = [3, 1, 4, 1, 5]
    max_new = 10
    ref = greedy_generate(gpt, prompt, max_new)
    fdir = os.path.join(d, "fleet_b")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.5,
                         empty_pool_wait_s=60.0,
                         default_deadline_ms=300_000)
    victim = FleetReplica(fdir, 10, model=gpt_zip, max_batch=4,
                          default_deadline_ms=120_000)
    survivor = None
    try:
        if not router.wait_for_replicas(1, timeout_s=30.0):
            print("fleet_smoke: FAIL victim replica never admitted")
            return 1
        kill = Fault("kill_replica", rank=10, step=3)
        faultinject.set_schedule(FaultSchedule([kill]))
        out, errs = {}, []

        def gen():
            try:
                out["partials"], out["resp"] = _stream_generate(
                    router.host, router.port, prompt, max_new, gpt_zip)
            except Exception as e:  # noqa: BLE001 — the gate itself
                errs.append(f"{type(e).__name__}: {e}")

        t = threading.Thread(target=gen, daemon=True)
        t.start()
        # the survivor arrives only AFTER the stream is already running
        # — admission rides the readyz gate while the router waits
        survivor = FleetReplica(fdir, 11, model=gpt_zip, max_batch=4,
                                default_deadline_ms=120_000)
        t.join(240.0)
        if errs or "resp" not in out:
            print(f"fleet_smoke: FAIL mid-stream generate errored "
                  f"({errs or 'timed out'})")
            return 1
        resp = out["resp"]
        if not resp.get("ok"):
            print(f"fleet_smoke: FAIL generate response {resp}")
            return 1
        if not kill.fired:
            print("fleet_smoke: FAIL mid-stream kill never fired")
            return 1
        if resp["tokens"] != ref or out["partials"] != ref:
            print(f"fleet_smoke: FAIL failover stream diverged from "
                  f"singleton (final {resp['tokens']}, streamed "
                  f"{out['partials']}, ref {ref})")
            return 1
        if resp.get("failovers", 0) < 1 \
                or _counter(registry, "fleet_generate_resumes_total") < 1:
            print(f"fleet_smoke: FAIL no mid-stream resume recorded "
                  f"({resp})")
            return 1
        return 0
    finally:
        faultinject.clear()
        router.close()
        victim.drain(grace_s=5.0)
        if survivor is not None:
            survivor.drain(grace_s=5.0)


def _phase_rolling(d, mlp_zip, gpt_zip, x, gpt, np, KerasClient,
                   FleetReplica, FleetRouter, faultinject, Fault,
                   FaultSchedule, registry, greedy_generate) -> int:
    """Replace every replica (admit successor, drain predecessor) under
    continuous load: zero dropped requests."""
    fdir = os.path.join(d, "fleet_c")
    adm0 = _counter(registry, "fleet_admissions_total")
    rem0 = _counter(registry, "fleet_removals_total")
    router = FleetRouter(fdir, poll_s=0.1, heartbeat_timeout_s=1.5,
                         max_concurrency=16, queue_depth=64,
                         default_deadline_ms=120_000)
    reps = {r: FleetReplica(fdir, r, model=mlp_zip, max_concurrency=8,
                            queue_depth=32, default_deadline_ms=60_000)
            for r in (0, 1, 2)}
    stop = threading.Event()
    counts = {"ok": 0}
    failures, lock = [], threading.Lock()

    def load(i):
        while not stop.is_set():
            try:
                cli = KerasClient(router.host, router.port)
                try:
                    cli.predict(x, model=mlp_zip)
                finally:
                    cli.close()
                with lock:
                    counts["ok"] += 1
            except Exception as e:  # noqa: BLE001 — the gate itself
                with lock:
                    failures.append(f"loader {i}: "
                                    f"{type(e).__name__}: {e}")
                return
            time.sleep(0.01)

    loaders = []
    try:
        if not router.wait_for_replicas(3, timeout_s=30.0):
            print("fleet_smoke: FAIL rolling fleet never formed")
            return 1
        loaders = [threading.Thread(target=load, args=(i,), daemon=True)
                   for i in range(4)]
        for t in loaders:
            t.start()
        for old in (0, 1, 2):
            new = old + 10
            reps[new] = FleetReplica(fdir, new, model=mlp_zip,
                                     max_concurrency=8, queue_depth=32,
                                     default_deadline_ms=60_000)
            if not router.wait_for_replicas(4, timeout_s=30.0):
                print(f"fleet_smoke: FAIL replacement {new} never "
                      f"admitted")
                return 1
            if not reps[old].drain(grace_s=15.0):
                print(f"fleet_smoke: FAIL replica {old} drain grace "
                      f"expired with work in flight")
                return 1
            if not _wait_removed(router, old):
                print(f"fleet_smoke: FAIL drained replica {old} never "
                      f"left membership")
                return 1
        time.sleep(0.3)  # a little post-roll load on the new fleet
        stop.set()
        for t in loaders:
            t.join(60.0)
        if failures:
            print(f"fleet_smoke: FAIL dropped requests during rolling "
                  f"drain: {failures}")
            return 1
        if counts["ok"] < 50:
            print(f"fleet_smoke: FAIL implausibly little load survived "
                  f"the roll ({counts['ok']} requests)")
            return 1
        if sorted(router.replicas()) != [10, 11, 12]:
            print(f"fleet_smoke: FAIL post-roll membership "
                  f"{router.replicas()}")
            return 1
        adm = _counter(registry, "fleet_admissions_total") - adm0
        rem = _counter(registry, "fleet_removals_total") - rem0
        if adm < 6 or rem < 3:
            print(f"fleet_smoke: FAIL membership accounting "
                  f"(admissions {adm}, removals {rem})")
            return 1
        # ---- observability: fleet_* on the router's /api/metrics
        base = f"http://127.0.0.1:{router.metrics_port}"
        with urllib.request.urlopen(f"{base}/api/metrics",
                                    timeout=10) as r:
            text = r.read().decode()
        needed = ("fleet_replicas", "fleet_epoch",
                  "fleet_dispatches_total", "fleet_failovers_total",
                  "fleet_admissions_total", "fleet_removals_total",
                  "fleet_generate_resumes_total")
        missing = [n for n in needed if f"\n{n} " not in "\n" + text
                   and not text.startswith(f"{n} ")]
        if missing:
            print(f"fleet_smoke: FAIL /api/metrics missing {missing}")
            return 1
        with urllib.request.urlopen(f"{base}/api/metrics.json",
                                    timeout=10) as r:
            as_json = json.loads(r.read())
        if "fleet_replicas" not in as_json:
            print("fleet_smoke: FAIL /api/metrics.json missing "
                  "fleet_replicas")
            return 1
        try:
            with urllib.request.urlopen(f"{base}/readyz",
                                        timeout=10) as r:
                code = r.status
        except HTTPError as e:
            code = e.code
        if code != 200:
            print(f"fleet_smoke: FAIL router /readyz {code} with "
                  f"members present")
            return 1
        print(f"fleet_smoke: rolling — {counts['ok']} requests, zero "
              f"drops, admissions {adm}, removals {rem}")
        return 0
    finally:
        stop.set()
        for t in loaders:
            t.join(10.0)
        router.close()
        for rep in reps.values():
            rep.drain(grace_s=5.0)


if __name__ == "__main__":
    sys.exit(main())
