"""Multi-replica serving fleet: lease-based membership, failover
routing, and zero-drop drains (ISSUE 18).

DL4J's scale-out story was data-parallel *training* (ParallelWrapper /
parameter averaging); serving stayed a single process. This module is
the serving analog of the elastic-training arc: N ``KerasServer``
replicas behind a :class:`FleetRouter`, membership coordinated by the
same shared-directory lease rendezvous ``resilience/elastic.py`` built
for training hosts (PR 11), so replica death, partition, and rolling
restarts are invisible to clients.

**Membership is the PR-11 lease lifecycle with a serving payload.**
Each :class:`FleetReplica` heartbeats ``hb_p<rank>.json`` into the
fleet directory carrying ``{host, port}`` — the beat IS the
registration record — and announces itself with a ``join_p<rank>.json``
request. The router (the lease holder, ``coordinator = -1``) admits a
joiner only after its structured ``readyz`` op reports ready (model
loaded, buckets prewarmed — never on bare TCP connect), and removes a
member whose heartbeat goes stale or whose connection drops dead. Every
membership change bumps the lease epoch and rewrites ``lease.json``;
routing decisions only ever read the router's own lease snapshot.
A replica that returns (partition healed, rolling restart) re-admits
through the same readyz gate at a fresh epoch.

**Dispatch** is power-of-two-choices least-loaded: two random members
are sampled and the one with the lower score (router-side in-flight,
polled queue depth, TTFT p99) wins. Per-replica load comes from each
replica's ``readyz`` responses — NOT from process-global gauges, which
in-process replicas share.

**Failure taxonomy** (the PR-4/6 discipline, applied per replica):

- connection failure / timeout / unstructured server error → REPLICA
  fault: charges that replica's circuit breaker, and the op (predict /
  generate — both idempotent) retries on a survivor with bounded
  backoff (``fleet_failovers_total``). A dead connection also removes
  the replica at an epoch bump.
- ``SHED`` / ``DRAINING`` / ``BREAKER_OPEN`` → load/lifecycle signal:
  reroute to another replica WITHOUT charging (a draining replica is
  healthy — that is what zero-drop drains rely on).
- ``NONFINITE`` / ``DEADLINE`` / client input errors (bad paths, bad
  tokens) → CLIENT-side: passed through unchanged, never retried,
  never charged — a poisoned request must not open circuits or bounce
  around the fleet.

**Hedged duplicates** (optional, ``hedge_ms``): a predict whose primary
has not answered within the hedge delay is duplicated to a second
replica; the first good answer wins and the loser's connection is cut
(``fleet_hedges_total`` / ``fleet_hedge_wins_total``).

**Mid-stream generate failover.** Generates forward with
``stream=true``: the replica emits each token as a partial line and the
router accumulates them (optionally re-streaming to its own client).
When a replica dies mid-generation, the router re-dispatches to a
survivor from ``prompt + tokens-so-far`` with the remaining budget —
the PR-14 eviction re-prefill discipline generalized across processes —
so the client's final token stream is BITWISE the singleton
``greedy_generate`` stream (same weights, deterministic CPU decode;
``fleet_generate_resumes_total`` counts the seam).

**Overload degradation (ISSUE 19).** Failover retries and hedges are
load *amplifiers* — they add traffic exactly when the pool is sickest —
so both are gated by a shared SRE-style :class:`RetryBudget` (refilled
as a fraction of successful dispatches): when the budget is dry a
failed dispatch gets at most ONE free reroute then surfaces the
structured error, and hedges are skipped entirely
(``fleet_retry_budget_exhausted_total``). Under sustained overload at
max capacity the :class:`~deeplearning4j_tpu.keras.autoscale.
FleetAutoscaler` flips the router into **brownout**: bulk-class
requests (the PR-14 priority taxonomy) shed with a structured
``{"error": "SHED", "retry_after_ms": ...}`` while interactive traffic
keeps its SLO. And a replica that repeatedly joins and dies within a
window is **flap-quarantined** (``autoscale.FlapTracker``): the
membership scan skips it for an exponentially growing, bounded delay
instead of letting a crash-looper keep eating mid-stream generates.

The router itself admits through its own ``ServiceGuard`` (bounded
queue, deadlines, drain, ``/readyz``) and serves Prometheus metrics at
``http://host:metrics_port/api/metrics``.
"""

from __future__ import annotations

import json
import logging
import queue
import random
import socket
import socketserver
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple, Union

from deeplearning4j_tpu.keras.autoscale import FlapTracker
from deeplearning4j_tpu.keras.batching import priority_rank
from deeplearning4j_tpu.keras.server import KerasServer
from deeplearning4j_tpu.profiling.flightrec import record as flight_record
from deeplearning4j_tpu.profiling.metrics import get_registry
from deeplearning4j_tpu.profiling.tracer import get_tracer
from deeplearning4j_tpu.resilience import faultinject
from deeplearning4j_tpu.resilience.elastic import (HostHeartbeat,
                                                   clear_join_requests,
                                                   pending_join_ranks,
                                                   read_heartbeats,
                                                   read_lease, request_join,
                                                   write_lease)
from deeplearning4j_tpu.resilience.service import (Deadline, RetryBudget,
                                                   ServiceError,
                                                   ServiceGuard, ShedError,
                                                   CircuitBreaker,
                                                   backoff_delay,
                                                   register_guard,
                                                   unregister_guard)

logger = logging.getLogger(__name__)

#: the lease's ``coordinator`` field when the ROUTER holds it — the
#: router is not a replica, so it sits outside the rank space (training
#: fleets use the lowest rank; the serving fleet has a dedicated holder)
ROUTER_COORDINATOR = -1


class NoReplicaAvailable(ServiceError):
    """No member can take this request (fleet empty, every breaker
    open, or retries exhausted against a flapping fleet)."""

    code = "NO_REPLICA"


class UnroutableOp(ServiceError):
    """Op not served by the fleet (``fit``/``evaluate`` mutate or scan
    ONE replica's state — they belong on a direct connection)."""

    code = "UNROUTABLE"


#: replica error codes that stay CLIENT-side: pass through, never
#: retried, never charged to a breaker (PR-4/6 taxonomy)
_CLIENT_CODES = frozenset({"NONFINITE", "DEADLINE"})
#: codes that mean "this replica can't take it right now, another can":
#: reroute without charging
_REROUTE_CODES = frozenset({"SHED", "DRAINING", "BREAKER_OPEN"})
#: legacy single-string error prefixes that are client-input failures
#: (bad op, bad shapes, bad file paths) — the replica processed the
#: request and returned a verdict, so nothing is charged or retried
_CLIENT_LEGACY = ("ValueError", "KeyError", "TypeError",
                  "JSONDecodeError", "FileNotFoundError",
                  "IsADirectoryError", "NotADirectoryError",
                  "PermissionError")


def _classify(resp: dict) -> str:
    """'client' | 'reroute' | 'replica' for a replica's error
    response."""
    code = str(resp.get("error", ""))
    if code in _CLIENT_CODES:
        return "client"
    if code in _REROUTE_CODES:
        return "reroute"
    if "message" in resp:
        # a structured code we don't know: surface it untouched rather
        # than guess-retry a verdict the replica already made
        return "client"
    if code.split(":", 1)[0] in _CLIENT_LEGACY:
        return "client"
    return "replica"


class _ForwardFailure(Exception):
    """Internal: a forward attempt failed with replica attribution."""

    def __init__(self, rep: "_Replica", cause: BaseException,
                 dead_connection: bool):
        super().__init__(str(cause))
        self.rep = rep
        self.cause = cause
        self.dead_connection = dead_connection


class _Replica:
    """Router-side record of one fleet member. ``inflight`` is the
    router's own dispatch count (guarded by the router lock);
    ``queued`` / ``ttft_p99_ms`` are the last readyz-polled values."""

    __slots__ = ("rank", "host", "port", "breaker", "inflight",
                 "queued", "ttft_p99_ms")

    def __init__(self, rank: int, host: str, port: int,
                 breaker: CircuitBreaker):
        self.rank = rank
        self.host = host
        self.port = port
        self.breaker = breaker
        self.inflight = 0
        self.queued = 0
        self.ttft_p99_ms = 0.0


class FleetReplica:
    """One fleet member in this process: a ``KerasServer`` (with
    ``replica_rank`` armed for the chaos kinds and ``preload`` for
    readiness) plus its rendezvous presence — a payload heartbeat and a
    join request in the shared fleet directory.

    ``drain()`` is the zero-drop leave: the heartbeat retires FIRST
    (file deleted — the router stops routing here within one poll; the
    raced requests that still land get ``DRAINING`` and reroute), then
    in-flight work finishes under the server's own drain. ``kill()`` is
    chaos: abrupt death, stale heartbeat left behind."""

    def __init__(self, fleet_dir: Union[str, Path], rank: int,
                 model: Optional[str] = None, host: str = "127.0.0.1",
                 port: int = 0, heartbeat_interval_s: float = 0.2,
                 **server_kw):
        self.rank = int(rank)
        self._dir = Path(fleet_dir)
        self.server = KerasServer(
            host=host, port=port, replica_rank=self.rank,
            preload=[model] if model else None, **server_kw)
        self.host, self.port = self.server.host, self.server.port
        self._hb = HostHeartbeat(
            self._dir, self.rank, interval_s=heartbeat_interval_s,
            payload={"host": self.host, "port": self.port})
        # a hard kill (chaos or real) must take liveness with it: stop
        # beating, LEAVE the stale file — that is how peers see death
        self.server.on_hard_kill = self._hb.stop
        request_join(self._dir, self.rank)
        self._hb.start()
        # flap_replica chaos: this incarnation dies shortly after the
        # router admits it — the crash-looper the flap quarantine exists
        # for. The watcher thread is joined on drain (LC005)
        self._flap_stop = threading.Event()
        self._flap_thread: Optional[threading.Thread] = None
        flap_delay = faultinject.check_flap_spawn(self.rank)
        if flap_delay is not None:
            self._flap_thread = threading.Thread(
                target=self._flap_loop, args=(float(flap_delay),),
                daemon=True, name=f"flap-replica-{self.rank}")
            self._flap_thread.start()
        flight_record("fleet", "replica_up", rank=self.rank,
                      port=self.port)

    def _flap_loop(self, delay_s: float) -> None:
        """Wait until this rank shows up in the lease world (admitted),
        then hard-kill ``delay_s`` later — join-then-die, the shape a
        crash-looping launcher produces."""
        while not self._flap_stop.is_set():
            lease = read_lease(self._dir)
            if lease and self.rank in (lease.get("world") or []):
                break
            if self._flap_stop.wait(0.05):
                return
        if self._flap_stop.wait(delay_s):
            return
        flight_record("faultinject", "flap_kill", rank=self.rank)
        self.kill()

    @property
    def draining(self) -> bool:
        return self.server.draining

    @property
    def alive(self) -> bool:
        """False once the server was hard-killed (chaos drivers use
        this to decide when to respawn a flapping incarnation)."""
        return not self.server.killed

    def readyz(self) -> dict:
        return self.server._readyz()

    def drain(self, grace_s: float = 10.0) -> bool:
        self._flap_stop.set()
        if self._flap_thread is not None:
            self._flap_thread.join(timeout=5.0)
            self._flap_thread = None
        self._hb.retire()
        clear_join_requests(self._dir, [self.rank])
        drained = self.server.drain(grace_s)
        flight_record("fleet", "replica_drained", rank=self.rank,
                      emptied=drained)
        return drained

    def kill(self) -> None:
        """Chaos: die the way ``kill_replica`` dies — connections
        severed, heartbeat stopped cold (stale file stays)."""
        self.server.hard_kill()


class FleetRouter:
    """The fleet front-end: speaks the KerasServer newline-JSON
    protocol (a ``KerasClient`` pointed at the router works unchanged),
    admits through its own ``ServiceGuard``, and dispatches ``predict``
    / ``generate`` across the lease's current membership."""

    def __init__(self, fleet_dir: Union[str, Path],
                 host: str = "127.0.0.1", port: int = 0,
                 max_concurrency: int = 16, queue_depth: int = 64,
                 default_deadline_ms: Optional[float] = 300_000.0,
                 max_queue_wait_s: float = 5.0,
                 heartbeat_timeout_s: float = 2.0,
                 poll_s: float = 0.25,
                 breaker_failures: int = 3,
                 breaker_cooldown_base: float = 0.5,
                 breaker_cooldown_max: float = 30.0,
                 retries: int = 4,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 0.5,
                 hedge_ms: Optional[float] = None,
                 empty_pool_wait_s: float = 15.0,
                 connect_timeout_s: float = 2.0,
                 io_timeout_s: float = 120.0,
                 retry_budget_capacity: float = 10.0,
                 retry_budget_ratio: float = 0.1,
                 flap_window_s: float = 5.0,
                 flap_strikes: int = 2,
                 flap_quarantine_base_s: float = 2.0,
                 flap_quarantine_max_s: float = 60.0,
                 metrics_port: Optional[int] = 0):
        self._dir = Path(fleet_dir)
        self._dir.mkdir(parents=True, exist_ok=True)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.poll_s = float(poll_s)
        self.retries = int(retries)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.hedge_ms = hedge_ms
        self.empty_pool_wait_s = float(empty_pool_wait_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.io_timeout_s = float(io_timeout_s)
        self._breaker_kw = dict(failures=breaker_failures,
                                cooldown_base=breaker_cooldown_base,
                                cooldown_max=breaker_cooldown_max)
        # one budget gates EVERY amplifier (failover retries + hedges):
        # retries stay a bounded fraction of successful traffic
        self._retry_budget = RetryBudget(capacity=retry_budget_capacity,
                                         refill_ratio=retry_budget_ratio)
        self._flaps = FlapTracker(window_s=flap_window_s,
                                  strikes_to_quarantine=flap_strikes,
                                  base_s=flap_quarantine_base_s,
                                  max_s=flap_quarantine_max_s)
        # flipped by the autoscaler's brownout state machine; read
        # lock-free on the hot path (a bool write is atomic under the
        # GIL and a one-request stale read is harmless)
        self._brownout = False
        self._rng = random.Random()
        self._lock = threading.Lock()
        self._replicas: Dict[int, _Replica] = {}
        lease = read_lease(self._dir)
        self._epoch = int(lease["epoch"]) if lease else 0
        self._closed = False
        # lease writes serialize here, and an epoch never regresses on
        # disk even when a dispatch-path removal races the monitor
        self._lease_lock = threading.Lock()
        self._lease_epoch_written = self._epoch
        self._stop_evt = threading.Event()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            timeout = io_timeout_s

            def _stream_writer(self):
                """Client-facing partial re-streaming for generate
                (same wire shape the replicas emit). All writes happen
                on THIS handler thread — forwarding is synchronous — so
                no write lock is needed."""
                def on_token(tok):
                    self.wfile.write((json.dumps(
                        {"partial": True, "t": int(tok)}) + "\n").encode())
                    self.wfile.flush()
                return on_token

            def handle(self):
                try:
                    for line in self.rfile:
                        try:
                            req = json.loads(line)
                            on_token = None
                            if req.get("op") == "generate" \
                                    and req.get("stream"):
                                on_token = self._stream_writer()
                            resp = outer._handle(req, on_token)
                        except ServiceError as e:
                            resp = e.to_response()
                        except Exception as e:  # report, keep serving
                            resp = {"error": f"{type(e).__name__}: {e}"}
                        self.wfile.write((json.dumps(resp) + "\n").encode())
                        self.wfile.flush()
                        if isinstance(resp, dict) and resp.get("shutdown"):
                            threading.Thread(target=outer.close,
                                             daemon=True).start()
                            return
                except (TimeoutError, OSError):
                    return  # client vanished / idle timeout

        self._server = socketserver.ThreadingTCPServer((host, port),
                                                       Handler)
        self._server.daemon_threads = True
        self.host, self.port = host, self._server.server_address[1]
        self._guard = register_guard(ServiceGuard(
            f"fleet_router_{self.port}",
            max_concurrency=max_concurrency, queue_depth=queue_depth,
            default_deadline_ms=default_deadline_ms,
            max_queue_wait_s=max_queue_wait_s))
        self._guard.add_ready_check("replicas",
                                    lambda: bool(self._replicas))
        # metrics exist (at zero) from birth: an empty /api/metrics
        # scrape must still show the fleet_* family
        reg = get_registry()
        self._m_dispatches = reg.counter(
            "fleet_dispatches_total",
            help="requests forwarded to a replica (attempts, not "
                 "client requests)")
        self._m_retries = reg.counter(
            "fleet_retries_total",
            help="forward attempts re-dispatched to another replica "
                 "(any cause)")
        self._m_failovers = reg.counter(
            "fleet_failovers_total",
            help="retries caused by a replica-attributable failure "
                 "(dead connection, timeout, server fault)")
        self._m_hedges = reg.counter(
            "fleet_hedges_total",
            help="predicts duplicated to a second replica after the "
                 "hedge delay")
        self._m_hedge_wins = reg.counter(
            "fleet_hedge_wins_total",
            help="hedged duplicates that answered before the primary")
        self._m_admissions = reg.counter(
            "fleet_admissions_total",
            help="replicas admitted to the fleet (readyz-gated, "
                 "each at an epoch bump)")
        self._m_removals = reg.counter(
            "fleet_removals_total",
            help="replicas removed from the fleet (stale heartbeat or "
                 "dead connection, each at an epoch bump)")
        self._m_resumes = reg.counter(
            "fleet_generate_resumes_total",
            help="mid-stream generations resumed on a survivor via "
                 "re-prefill from prompt + tokens-so-far")
        self._m_budget_exhausted = reg.counter(
            "fleet_retry_budget_exhausted_total",
            help="retries/hedges suppressed because the retry budget "
                 "was dry")
        self._m_brownout_sheds = reg.counter(
            "fleet_brownout_sheds_total",
            help="bulk-class requests shed (structured SHED) while the "
                 "router was in brownout")
        self._m_quarantines = reg.counter(
            "fleet_quarantines_total",
            help="flap-quarantine episodes (a crash-looping replica "
                 "put on probation)")
        self._g_replicas = reg.gauge(
            "fleet_replicas", help="current fleet membership size")
        self._g_epoch = reg.gauge(
            "fleet_epoch", help="current membership lease epoch")
        self._g_brownout = reg.gauge(
            "fleet_brownout",
            help="1 while the router sheds bulk-class requests")
        self._g_budget = reg.gauge(
            "fleet_retry_budget_tokens",
            help="retry-budget tokens currently available")
        self._g_score = reg.labeled_gauge(
            "fleet_replica_score",
            help="per-replica dispatch score (lower routes sooner): "
                 "2*inflight + queued + min(ttft_p99,1000)/1000")
        self._g_replicas.set(0)
        self._g_epoch.set(self._epoch)
        self._g_brownout.set(0)
        self._g_budget.set(self._retry_budget.tokens)
        # optional Prometheus sidecar: GET /api/metrics[.json], /readyz
        self._http = None
        self._http_thread: Optional[threading.Thread] = None
        if metrics_port is not None:
            self._http = _MetricsHTTP(self, host, int(metrics_port))
            self.metrics_port = self._http.server_address[1]
            self._http_thread = threading.Thread(
                target=self._http.serve_forever, daemon=True,
                name="fleet-metrics-http")
            self._http_thread.start()
        else:
            self.metrics_port = None
        self._acceptor = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="fleet-acceptor")
        self._acceptor.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True, name="fleet-monitor")
        self._monitor.start()

    # ----------------------------------------------------------- membership
    def _monitor_loop(self) -> None:
        while not self._stop_evt.wait(self.poll_s):
            try:
                self._membership_scan()
            except Exception:  # noqa: BLE001 — the fleet outlives a scan
                logger.exception("fleet membership scan failed")

    def _membership_scan(self) -> None:
        """One rendezvous pass: remove stale members, admit ready
        joiners (join request OR a returning fresh heartbeat), refresh
        per-member load stats."""
        hbs = read_heartbeats(self._dir)
        with self._lock:
            members = {rank: (r.host, r.port)
                       for rank, r in self._replicas.items()}
        for rank in list(members):
            hb = hbs.get(rank)
            if hb is None:
                self._remove_replica(rank, "heartbeat_gone")
            elif float(hb["age"]) > self.heartbeat_timeout_s:
                self._remove_replica(rank, "stale_heartbeat")
        # candidates: announced joiners plus any returning rank with a
        # fresh payload heartbeat (a healed partition re-admits itself
        # through the same readyz gate, at a fresh epoch)
        candidates = set(pending_join_ranks(self._dir)) | set(hbs)
        for rank in sorted(candidates - set(members)):
            if self._flaps.blocked(rank):
                continue  # on probation: re-admission delay still runs
            hb = hbs.get(rank)
            if hb is None or float(hb["age"]) > self.heartbeat_timeout_s:
                continue
            host, port = hb.get("host"), hb.get("port")
            if host is None or port is None:
                continue  # a training-host beat, not a serving replica
            rz = self._probe_readyz(str(host), int(port))
            if rz is not None and rz.get("ready"):
                self._admit_replica(rank, str(host), int(port))
        self._poll_stats()

    def _probe_readyz(self, host: str, port: int) -> Optional[dict]:
        try:
            with socket.create_connection(
                    (host, port), timeout=self.connect_timeout_s) as s:
                s.settimeout(self.connect_timeout_s)
                f = s.makefile("rwb")
                f.write(b'{"op": "readyz"}\n')
                f.flush()
                line = f.readline()
                f.close()
            if not line:
                return None
            return json.loads(line)
        except (OSError, ValueError):
            return None

    def _poll_stats(self) -> None:
        """Refresh queue-depth / TTFT-p99 dispatch signals from each
        member's readyz op; a member whose probe fails outright is
        removed (dead connection)."""
        with self._lock:
            members = [(r.rank, r.host, r.port)
                       for r in self._replicas.values()]
        for rank, host, port in members:
            rz = self._probe_readyz(host, port)
            if rz is None:
                self._remove_replica(rank, "dead_connection")
                continue
            with self._lock:
                rep = self._replicas.get(rank)
                if rep is not None:
                    rep.queued = int(rz.get("queued") or 0)
                    rep.ttft_p99_ms = float(rz.get("ttft_p99_ms") or 0.0)
                    score = self._score_locked(rep)
            if rep is not None:
                # the dispatch score itself, per replica, so autoscaler
                # decisions are explainable from /api/metrics alone
                self._g_score.labels(rank=str(rank)).set(score)

    def _admit_replica(self, rank: int, host: str, port: int) -> None:
        with self._lock:
            if self._closed or rank in self._replicas:
                return
            self._replicas[rank] = _Replica(
                rank, host, port,
                CircuitBreaker(key=f"replica:{rank}", **self._breaker_kw))
            self._epoch += 1
            epoch, world = self._epoch, sorted(self._replicas)
        clear_join_requests(self._dir, [rank])
        self._publish_lease(epoch, world)
        self._flaps.on_admit(rank)
        self._m_admissions.inc()
        self._g_replicas.set(len(world))
        self._g_epoch.set(epoch)
        self._g_score.labels(rank=str(rank)).set(0.0)
        get_tracer().instant("fleet_admit", rank=rank, epoch=epoch)
        flight_record("fleet", "replica_admitted", rank=rank,
                      epoch=epoch, world=world)

    def _remove_replica(self, rank: int, reason: str) -> None:
        with self._lock:
            if self._replicas.pop(rank, None) is None:
                return
            self._epoch += 1
            epoch, world = self._epoch, sorted(self._replicas)
        self._publish_lease(epoch, world)
        self._m_removals.inc()
        self._g_replicas.set(len(world))
        self._g_epoch.set(epoch)
        self._g_score.remove(rank=str(rank))
        get_tracer().instant("fleet_remove", rank=rank, epoch=epoch,
                             reason=reason)
        flight_record("fleet", "replica_removed", rank=rank,
                      epoch=epoch, reason=reason, world=world)
        quarantine_s = self._flaps.on_remove(rank, reason)
        if quarantine_s is not None:
            self._m_quarantines.inc()
            get_tracer().instant("fleet_quarantine", rank=rank,
                                 delay_s=round(quarantine_s, 3))
            flight_record("fleet", "replica_quarantined", rank=rank,
                          delay_s=round(quarantine_s, 3),
                          strikes=self._flaps.strikes(rank))

    def _publish_lease(self, epoch: int, world: List[int]) -> None:
        """Serialized, monotonic lease writes: a racing older epoch
        never lands on disk after a newer one."""
        with self._lease_lock:
            if epoch <= self._lease_epoch_written:
                return
            self._lease_epoch_written = epoch
            write_lease(self._dir, epoch, world, ROUTER_COORDINATOR)

    # ------------------------------------------------------------- dispatch
    def _score_locked(self, r: _Replica) -> float:
        # router-side in-flight is the freshest signal; polled queue
        # depth and TTFT p99 (bounded so a slow outlier can't dominate
        # forever) break ties toward the snappier replica
        return (2.0 * r.inflight + float(r.queued)
                + min(r.ttft_p99_ms, 1000.0) / 1000.0)

    def _pick(self, exclude: Set[int]) -> Optional[_Replica]:
        """Power-of-two-choices among members outside ``exclude``
        (falling back to all members when exclusion empties the pool —
        a last retry against a previously-failed replica beats a
        refusal)."""
        with self._lock:
            cands = [r for k, r in self._replicas.items()
                     if k not in exclude]
            if not cands:
                cands = list(self._replicas.values())
            if not cands:
                return None
            if len(cands) > 2:
                cands = self._rng.sample(cands, 2)
            return min(cands, key=self._score_locked)

    def _try_pick(self, exclude: Set[int]) -> Optional[_Replica]:
        seen = set(exclude)
        while True:
            rep = self._pick(seen)
            if rep is None:
                return None
            if rep.breaker.allow():
                return rep
            if rep.rank in seen:
                return None  # exclusion already exhausted the pool
            seen.add(rep.rank)

    def _pick_for_dispatch(self, exclude: Set[int],
                           deadline: Deadline) -> Optional[_Replica]:
        """``_try_pick``, but riding out a briefly-empty pool: during a
        rolling restart the last old replica can leave moments before
        its replacement admits, and a mid-stream failover's survivor
        may still be in its readyz gate. Waiting (bounded by
        ``empty_pool_wait_s`` and the deadline) is what turns those
        windows into latency instead of client-visible failures."""
        t_end = time.monotonic() + self.empty_pool_wait_s
        while True:
            rep = self._try_pick(exclude)
            if rep is not None:
                return rep
            deadline.check("fleet replica wait")
            if time.monotonic() >= t_end or self._stop_evt.is_set():
                return None
            time.sleep(0.05)

    def _no_replica(self, what: str) -> NoReplicaAvailable:
        with self._lock:
            n = len(self._replicas)
            ras = [r.breaker.retry_after_ms()
                   for r in self._replicas.values()]
        return NoReplicaAvailable(
            f"{what}: no dispatchable replica ({n} member(s))",
            retry_after_ms=min(ras) if ras else None)

    def _note_inflight(self, rep: _Replica, delta: int) -> None:
        with self._lock:
            rep.inflight += delta

    def _io_budget(self, deadline: Deadline) -> float:
        rem = deadline.remaining()
        if rem is None:
            return self.io_timeout_s
        return max(0.05, min(self.io_timeout_s, rem + 0.25))

    def _forward(self, rep: _Replica, fwd: dict, deadline: Deadline,
                 on_partial=None, sock_slot: Optional[list] = None
                 ) -> Tuple[dict, int]:
        """One request to one replica over a fresh connection. Streams
        partial tokens to ``on_partial``; returns ``(final response,
        partial count)``. Raises ``_ForwardFailure`` on connection
        failure / timeout / garbage, with the replica attributed."""
        self._m_dispatches.inc()
        partials = 0
        try:
            rem = deadline.remaining()
            if rem is not None and rem <= 0:
                deadline.check("fleet forward")
            with socket.create_connection(
                    (rep.host, rep.port),
                    timeout=self.connect_timeout_s) as s:
                s.settimeout(self._io_budget(deadline))
                f = s.makefile("rwb")
                if sock_slot is not None:
                    sock_slot.append(s)
                try:
                    f.write((json.dumps(fwd) + "\n").encode())
                    f.flush()
                    while True:
                        line = f.readline()
                        if not line:
                            raise ConnectionError(
                                f"replica {rep.rank} closed the "
                                f"connection mid-response")
                        resp = json.loads(line)
                        if isinstance(resp, dict) and resp.get("partial"):
                            partials += 1
                            if on_partial is not None:
                                on_partial(int(resp["t"]))
                            continue
                        return resp, partials
                finally:
                    try:
                        f.close()
                    except OSError:
                        pass
        except socket.timeout as e:
            # slow, maybe alive: charge-worthy, but not removal-worthy
            raise _ForwardFailure(rep, e, dead_connection=False) from e
        except (ConnectionError, OSError, ValueError) as e:
            # refused / reset / EOF / garbage bytes: a dead connection
            raise _ForwardFailure(rep, e, dead_connection=True) from e

    def _absorb_failure(self, failure: _ForwardFailure) -> None:
        """Charge and (for dead connections) remove the failed
        replica — the shared accounting for primary and hedge paths."""
        failure.rep.breaker.record_failure()
        if failure.dead_connection:
            self._remove_replica(failure.rep.rank, "dead_connection")

    # --------------------------------------------------------- retry budget
    def _budget_success(self) -> None:
        """A replica answered: earn back a fraction of a retry token."""
        self._retry_budget.on_success()
        self._g_budget.set(self._retry_budget.tokens)

    def _spend_retry(self, what: str) -> bool:
        """Spend one budget token for a retry/hedge; False = dry (the
        caller must stop amplifying)."""
        if self._retry_budget.try_spend():
            self._g_budget.set(self._retry_budget.tokens)
            return True
        self._m_budget_exhausted.inc()
        flight_record("fleet", "retry_budget_exhausted", what=what)
        return False

    # ------------------------------------------------------------- predict
    def _dispatch_predict(self, req: dict, deadline: Deadline) -> dict:
        attempt = 0
        tried: Set[int] = set()
        last_resp: Optional[dict] = None
        # with a dry budget a failed dispatch still gets ONE reroute
        # (a single replica death must not fail clients outright), but
        # never a storm
        free_reroute_used = False
        while True:
            deadline.check("fleet predict")
            rep = self._pick_for_dispatch(tried, deadline)
            if rep is None:
                if last_resp is not None:
                    return last_resp  # honest: the fleet's own verdict
                raise self._no_replica("predict")
            fwd = dict(req)
            rem = deadline.remaining()
            if rem is not None:
                fwd["deadline_ms"] = max(1.0, rem * 1000.0)
            try:
                used, resp = self._forward_hedged(rep, fwd, deadline,
                                                  tried)
            except _ForwardFailure as failure:
                self._absorb_failure(failure)
                tried.add(failure.rep.rank)
                attempt += 1
                if attempt > self.retries:
                    raise NoReplicaAvailable(
                        f"predict: {attempt} attempts exhausted; last "
                        f"failure on replica {failure.rep.rank}: "
                        f"{failure.cause}") from failure.cause
                if not self._spend_retry("predict"):
                    if free_reroute_used:
                        raise NoReplicaAvailable(
                            f"predict: retry budget exhausted after "
                            f"{attempt} attempt(s); last failure on "
                            f"replica {failure.rep.rank}: "
                            f"{failure.cause}") from failure.cause
                    free_reroute_used = True
                self._m_retries.inc()
                self._m_failovers.inc()
                flight_record("fleet", "failover", op="predict",
                              frm=failure.rep.rank, attempt=attempt)
                self._backoff(attempt, deadline)
                continue
            if resp.get("error") is None:
                used.breaker.record_success()
                self._budget_success()
                return resp
            verdict = _classify(resp)
            if verdict == "client":
                used.breaker.record_success()
                self._budget_success()
                return resp
            if verdict == "replica":
                used.breaker.record_failure()
            last_resp = resp
            tried.add(used.rank)
            attempt += 1
            if attempt > self.retries:
                return resp
            if not self._spend_retry("predict"):
                if free_reroute_used:
                    return resp  # surface the fleet's structured verdict
                free_reroute_used = True
            self._m_retries.inc()
            if verdict == "replica":
                self._m_failovers.inc()
                flight_record("fleet", "failover", op="predict",
                              frm=used.rank, attempt=attempt)
            self._backoff(attempt, deadline)

    def _forward_hedged(self, rep: _Replica, fwd: dict,
                        deadline: Deadline, tried: Set[int]
                        ) -> Tuple[_Replica, dict]:
        """Forward with an optional hedged duplicate. Hedging defends
        the TAIL (a slow-but-alive primary), not errors: when the
        primary fails outright the outer retry loop is the failover
        path. Returns ``(replica answered, response)`` or raises the
        primary's ``_ForwardFailure``."""
        if self.hedge_ms is None:
            self._note_inflight(rep, +1)
            try:
                resp, _ = self._forward(rep, fwd, deadline)
            finally:
                self._note_inflight(rep, -1)
            return rep, resp
        outcomes: "queue.Queue" = queue.Queue()
        slots: Dict[int, list] = {}

        def run(r: _Replica) -> None:
            slot: list = []
            slots[r.rank] = slot
            self._note_inflight(r, +1)
            try:
                resp, _ = self._forward(r, fwd, deadline,
                                        sock_slot=slot)
                outcomes.put((r, resp, None))
            except _ForwardFailure as failure:
                outcomes.put((r, None, failure))
            except Exception as e:  # noqa: BLE001 — never strand the q
                outcomes.put((r, None, _ForwardFailure(r, e, False)))
            finally:
                self._note_inflight(r, -1)

        threading.Thread(target=run, args=(rep,), daemon=True,
                         name="fleet-forward").start()
        launched = [rep]
        try:
            first = outcomes.get(timeout=self.hedge_ms / 1000.0)
        except queue.Empty:
            first = None
        if first is None:
            # opportunistic: a hedge with nowhere to go just waits for
            # the primary (never block on an empty pool here). A hedge
            # is a duplicate — pure amplification — so it spends a
            # retry-budget token; dry budget = no hedge, period
            hedge = self._try_pick(tried | {rep.rank})
            if (hedge is not None and hedge.rank != rep.rank
                    and self._spend_retry("hedge")):
                self._m_hedges.inc()
                flight_record("fleet", "hedge", primary=rep.rank,
                              hedge=hedge.rank)
                threading.Thread(target=run, args=(hedge,), daemon=True,
                                 name="fleet-forward-hedge").start()
                launched.append(hedge)
            first = outcomes.get(timeout=self._io_budget(deadline)
                                 + self.connect_timeout_s + 1.0)
        collected = [first]
        r0, resp0, fail0 = first
        winner = None
        if fail0 is None and (resp0.get("error") is None
                              or _classify(resp0) == "client"):
            winner = (r0, resp0)
        elif len(launched) > 1:
            # first outcome is bad: account for it, take the other
            if fail0 is not None:
                self._absorb_failure(fail0)
            else:
                if _classify(resp0) == "replica":
                    r0.breaker.record_failure()
            second = outcomes.get(timeout=self._io_budget(deadline)
                                  + self.connect_timeout_s + 1.0)
            collected.append(second)
            r1, resp1, fail1 = second
            if fail1 is not None:
                raise fail1
            winner = (r1, resp1)
        else:
            if fail0 is not None:
                raise fail0
            winner = (r0, resp0)
        # cut the loser loose: close its socket so its thread unblocks
        # and errors out (its failure is discarded, not charged — the
        # race was OUR doing)
        for r in launched:
            if r.rank != winner[0].rank:
                for s in slots.get(r.rank, ()):
                    try:
                        s.close()
                    except OSError:
                        pass
        if len(launched) > 1 and winner[0].rank != rep.rank:
            self._m_hedge_wins.inc()
        return winner

    # ------------------------------------------------------------- generate
    def _dispatch_generate(self, req: dict, deadline: Deadline,
                           on_token) -> dict:
        prompt = [int(t) for t in (req.get("tokens") or [])]
        if not prompt:
            raise ValueError("generate needs 'tokens': [ids...]")
        max_new = int(req.get("max_new_tokens", 16))
        sofar: List[int] = []
        failovers = 0
        attempt = 0
        tried: Set[int] = set()
        free_reroute_used = False
        t0 = time.monotonic()
        first_token_s: Optional[float] = None
        final: Optional[dict] = None
        while True:
            deadline.check("fleet generate")
            remaining = max_new - len(sofar)
            if remaining <= 0:
                break  # the replica died BETWEEN its last token and
                # the final envelope: the stream is already complete
            rep = self._pick_for_dispatch(tried, deadline)
            if rep is None:
                raise self._no_replica(
                    f"generate ({len(sofar)} tokens streamed)")
            # the re-prefill continuation: survivors see prompt +
            # generated-so-far as THE prompt and the leftover budget as
            # THE budget — bitwise the PR-14 eviction discipline
            fwd = dict(req)
            fwd["tokens"] = prompt + sofar
            fwd["max_new_tokens"] = remaining
            fwd["stream"] = True
            rem = deadline.remaining()
            if rem is not None:
                fwd["deadline_ms"] = max(1.0, rem * 1000.0)
            wave: List[int] = []

            def on_partial(tok: int) -> None:
                nonlocal first_token_s
                if first_token_s is None:
                    first_token_s = time.monotonic()
                wave.append(tok)
                sofar.append(tok)
                if on_token is not None:
                    on_token(tok)

            self._note_inflight(rep, +1)
            try:
                resp, _ = self._forward(rep, fwd, deadline,
                                        on_partial=on_partial)
            except _ForwardFailure as failure:
                self._absorb_failure(failure)
                tried.add(rep.rank)
                attempt += 1
                if attempt > self.retries:
                    raise NoReplicaAvailable(
                        f"generate: {attempt} attempts exhausted with "
                        f"{len(sofar)} tokens streamed; last failure "
                        f"on replica {rep.rank}: {failure.cause}"
                    ) from failure.cause
                if not self._spend_retry("generate"):
                    if free_reroute_used:
                        raise NoReplicaAvailable(
                            f"generate: retry budget exhausted after "
                            f"{attempt} attempt(s) with {len(sofar)} "
                            f"tokens streamed; last failure on replica "
                            f"{rep.rank}: {failure.cause}"
                        ) from failure.cause
                    free_reroute_used = True
                self._m_retries.inc()
                self._m_failovers.inc()
                if sofar:
                    self._m_resumes.inc()
                    get_tracer().instant("fleet_generate_resume",
                                         frm=rep.rank,
                                         tokens=len(sofar))
                flight_record("fleet", "failover", op="generate",
                              frm=rep.rank, attempt=attempt,
                              tokens_so_far=len(sofar))
                failovers += 1
                self._backoff(attempt, deadline)
                continue
            finally:
                self._note_inflight(rep, -1)
            if resp.get("error") is None:
                rep.breaker.record_success()
                self._budget_success()
                # reconcile: the final envelope carries this attempt's
                # complete token list; partials lost to a transient
                # stream-write failure on the replica still count
                full = [int(t) for t in resp.get("tokens", [])]
                for tok in full[len(wave):]:
                    if first_token_s is None:
                        first_token_s = time.monotonic()
                    sofar.append(tok)
                    if on_token is not None:
                        on_token(tok)
                final = resp
                break
            verdict = _classify(resp)
            if verdict == "client":
                rep.breaker.record_success()
                self._budget_success()
                return resp
            if verdict == "replica":
                rep.breaker.record_failure()
            tried.add(rep.rank)
            attempt += 1
            if attempt > self.retries:
                return resp
            if not self._spend_retry("generate"):
                if free_reroute_used:
                    return resp  # surface the fleet's structured verdict
                free_reroute_used = True
            self._m_retries.inc()
            if verdict == "replica":
                self._m_failovers.inc()
                if sofar:
                    self._m_resumes.inc()
                failovers += 1
            self._backoff(attempt, deadline)
        ttft_ms = (None if first_token_s is None
                   else round((first_token_s - t0) * 1000.0, 3))
        return {"ok": True, "tokens": sofar, "ttft_ms": ttft_ms,
                "reprefills": int((final or {}).get("reprefills") or 0),
                "failovers": failovers}

    def _backoff(self, attempt: int, deadline: Deadline) -> None:
        delay = backoff_delay(attempt, self.backoff_base_s,
                              self.backoff_max_s, self._rng)
        rem = deadline.remaining()
        if rem is not None:
            delay = min(delay, max(0.0, rem - 0.05))
        if delay > 0:
            time.sleep(delay)

    # ---------------------------------------------------------------- serve
    def _handle(self, req: dict, on_token=None) -> dict:
        op = req.get("op")
        if op == "health":
            ready, reasons = self._guard.ready()
            return {"ok": True, "live": True, "ready": ready,
                    "reasons": reasons,
                    "draining": self._guard.draining}
        if op == "readyz":
            return self._readyz()
        if op == "shutdown":
            return {"ok": True, "shutdown": True}
        if op in ("fit", "evaluate"):
            raise UnroutableOp(
                f"{op} mutates or scans ONE replica's state; connect "
                f"to a replica directly — the fleet serves stateless "
                f"inference (predict/generate)")
        if op not in ("predict", "generate"):
            raise ValueError(f"unknown op {op!r}")
        if self._brownout and priority_rank(
                str(req.get("priority", "interactive"))) > 0:
            # brownout: degrade by priority class, not for everyone —
            # bulk sheds (structured, connection stays up) so
            # interactive keeps its SLO
            self._m_brownout_sheds.inc()
            flight_record("fleet", "brownout_shed", op=op)
            raise ShedError(
                "fleet brownout: shedding bulk-class requests",
                retry_after_ms=int(self._guard.max_queue_wait_s * 1000))
        deadline = self._guard.deadline(req)
        with self._guard.admit(deadline):
            flight_record("fleet", "dispatch", op=op)
            with get_tracer().span(f"fleet:{op}"):
                if op == "predict":
                    return self._dispatch_predict(req, deadline)
                return self._dispatch_generate(req, deadline, on_token)

    def _readyz(self) -> dict:
        ready, reasons = self._guard.ready()
        with self._lock:
            epoch = self._epoch
            brownout = self._brownout
            replicas = {
                str(r.rank): {"host": r.host, "port": r.port,
                              "inflight": r.inflight,
                              "queued": r.queued,
                              "ttft_p99_ms": r.ttft_p99_ms,
                              "breaker": r.breaker.state,
                              "score": self._score_locked(r)}
                for r in self._replicas.values()}
        if brownout:
            # honest readiness: ready (interactive still serves), but
            # the degradation is visible to anything that probes
            reasons = list(reasons) + ["brownout: shedding bulk"]
        return {"ok": True, "ready": ready, "reasons": reasons,
                "draining": self._guard.draining, "epoch": epoch,
                "brownout": brownout,
                "retry_budget_tokens": self._retry_budget.tokens,
                "replicas": replicas}

    # ------------------------------------------------------------- overload
    @property
    def brownout(self) -> bool:
        return self._brownout

    def set_brownout(self, active: bool, reason: str = "") -> None:
        """Flip brownout shedding (the autoscaler's state machine owns
        the transitions; operators can force it too). Idempotent."""
        active = bool(active)
        with self._lock:
            if self._brownout == active:
                return
            self._brownout = active
        self._g_brownout.set(1 if active else 0)
        kind = "brownout_enter" if active else "brownout_exit"
        get_tracer().instant(f"fleet_{kind}", reason=reason)
        flight_record("fleet", kind, reason=reason)

    def load_snapshot(self) -> dict:
        """One coherent view of the load signals the autoscaler ticks
        on: router queue/inflight, lease epoch, and per-member polled
        stats (queued, TTFT p99, breaker state, dispatch score)."""
        with self._lock:
            replicas = {
                r.rank: {"inflight": r.inflight, "queued": r.queued,
                         "ttft_p99_ms": r.ttft_p99_ms,
                         "breaker": r.breaker.state,
                         "score": self._score_locked(r)}
                for r in self._replicas.values()}
            epoch, brownout = self._epoch, self._brownout
        return {"queued": self._guard.queued,
                "inflight": self._guard.inflight,
                "max_concurrency": self._guard.max_concurrency,
                "epoch": epoch, "brownout": brownout,
                "replicas": replicas}

    def quarantined(self, rank: int) -> bool:
        """True while a flapping rank's re-admission delay is running
        (drivers/tests observe probation without reaching into the
        tracker)."""
        return self._flaps.blocked(rank)

    def replicas(self) -> List[int]:
        with self._lock:
            return sorted(self._replicas)

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def wait_for_replicas(self, n: int, timeout_s: float = 30.0) -> bool:
        """Block until the membership reaches ``n`` (test/driver
        convenience — admission itself stays readyz-gated)."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            with self._lock:
                if len(self._replicas) >= n:
                    return True
            time.sleep(min(0.05, self.poll_s))
        with self._lock:
            return len(self._replicas) >= n

    # ------------------------------------------------------------ lifecycle
    @property
    def draining(self) -> bool:
        return self._guard.draining

    def drain(self, grace_s: float = 10.0) -> bool:
        """Stop admitting (DRAINING), let in-flight dispatches finish,
        then close every thread the router owns."""
        self._guard.start_drain()
        drained = self._guard.wait_idle(grace_s)
        self.close()
        return drained

    def close(self) -> None:
        """Teardown: monitor, acceptor, and metrics threads are all
        JOINED — enumerate() returns to baseline (the LC005/thread-
        hygiene contract)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._guard.start_drain()
        self._stop_evt.set()
        self._monitor.join(timeout=2 * self.poll_s
                           + 4 * self.connect_timeout_s + 5.0)
        self._server.shutdown()
        self._server.server_close()
        self._acceptor.join(timeout=5.0)
        if self._http is not None:
            self._http.shutdown()
            self._http.server_close()
            self._http_thread.join(timeout=5.0)
        unregister_guard(self._guard)
        flight_record("fleet", "router_closed", epoch=self.epoch)


class _MetricsHTTP(ThreadingHTTPServer):
    """Tiny observability sidecar for the router: Prometheus text at
    ``/api/metrics``, the JSON mirror at ``/api/metrics.json``, and the
    fleet ``/readyz`` (200 when ready, 503 while not)."""

    daemon_threads = True

    def __init__(self, router: FleetRouter, host: str, port: int):
        outer_router = router

        class H(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet: metrics scrapes
                pass

            def _send(self, status: int, body: bytes,
                      ctype: str) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    if self.path.startswith("/api/metrics.json"):
                        body = json.dumps(
                            get_registry().to_dict()).encode()
                        self._send(200, body, "application/json")
                    elif self.path.startswith("/api/metrics"):
                        body = get_registry().to_prometheus().encode()
                        self._send(200, body,
                                   "text/plain; version=0.0.4")
                    elif self.path.startswith("/readyz"):
                        rz = outer_router._readyz()
                        self._send(200 if rz["ready"] else 503,
                                   json.dumps(rz).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except (BrokenPipeError, ConnectionResetError):
                    pass

        super().__init__((host, port), H)
