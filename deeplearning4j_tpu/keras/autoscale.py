"""Fleet autoscaling + overload degradation controller.

ROADMAP item 2's elasticity half: the PR-18 fleet publishes everything
a control loop needs (per-replica ``readyz`` stats from the membership
poller, router queue depth, TTFT p99, breaker states) and its zero-drop
drain seam makes scale-down free — this module closes the loop. Three
pieces, one controller thread:

- **FleetAutoscaler** — a tick-based controller that reads one
  ``router.load_snapshot()`` per tick and drives ``spawn_fn`` /
  ``drain_fn`` callbacks toward a target SLO. Scale-up when queue depth
  or TTFT p99 breaches for ``up_ticks`` consecutive ticks; scale-down
  when the pool idles for ``down_ticks`` ticks — always through the
  replica's own drain seam (retire beat → clear join → ServiceGuard
  drain), never by killing. Hysteresis (consecutive-tick streaks) plus
  per-direction cooldowns keep oscillating load from flapping the pool.
  Every decision lands as ``fleet_autoscale_*`` counters, the
  ``fleet_target_replicas`` gauge, and a flight-recorder event, so a
  postmortem bundle explains *why* the pool was the size it was.
- **Brownout state machine** — when the breach persists while the pool
  is already at ``max_replicas`` there is nothing left to spawn; the
  controller flips the router into brownout (``router.set_brownout``)
  and the router sheds bulk-class requests with a structured ``SHED``
  while interactive traffic keeps its SLO. Exit needs ``exit_ticks``
  calm ticks (wider than entry, so the machine can't chatter).
- **FlapTracker** — probation for crash-looping replicas. A member that
  dies or partitions within ``window_s`` of admission takes a strike;
  ``strikes_to_quarantine`` strikes inside the window quarantine the
  rank with an exponentially growing, bounded, equal-jitter re-admission
  delay (``service.backoff_delay`` — the same policy every other retry
  path in the repo uses). Clean leaves (retired heartbeat) never
  strike, and a tenure longer than the window resets the count.

The controller is a real thread with a real teardown: ``drain()`` stops
the loop, JOINS it (the lockcheck LC005 invariant), and optionally
drains every replica the controller itself spawned. ``spawn_fn``/
``drain_fn`` run *outside* the controller's lock — they block on model
load and drain grace respectively, and nothing unbounded ever runs
under a lock here.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set

from deeplearning4j_tpu.profiling.flightrec import record as flight_record
from deeplearning4j_tpu.profiling.metrics import get_registry
from deeplearning4j_tpu.profiling.tracer import get_tracer
from deeplearning4j_tpu.resilience.service import backoff_delay

logger = logging.getLogger(__name__)

#: removal reasons that count as a flap strike: the replica *vanished*
#: (kill, crash, partition). A clean leave retires its heartbeat first
#: and surfaces as "heartbeat_gone" — draining is not flapping.
STRIKE_REASONS = frozenset({"stale_heartbeat", "dead_connection"})


class FlapTracker:
    """Per-rank probation for replicas that join and die repeatedly.

    The router calls ``on_admit(rank)`` when it admits a member and
    ``on_remove(rank, reason)`` when it removes one; the membership scan
    consults ``blocked(rank)`` before probing a candidate. All methods
    take only the tracker's own leaf lock (nothing else is called under
    it), so it composes with the router's locks in any order."""

    def __init__(self, window_s: float = 5.0,
                 strikes_to_quarantine: int = 2,
                 base_s: float = 2.0, max_s: float = 60.0,
                 rng: Optional[random.Random] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self.strikes_to_quarantine = max(1, int(strikes_to_quarantine))
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self._rng = rng if rng is not None else random.Random()
        self._now = now_fn
        self._lock = threading.Lock()
        # rank -> {"admitted_at": float|None, "strikes": int,
        #          "blocked_until": float}
        self._records: Dict[int, Dict[str, Any]] = {}

    def _rec(self, rank: int) -> Dict[str, Any]:
        return self._records.setdefault(
            int(rank), {"admitted_at": None, "strikes": 0,
                        "blocked_until": 0.0})

    def on_admit(self, rank: int) -> None:
        with self._lock:
            self._rec(rank)["admitted_at"] = self._now()

    def on_remove(self, rank: int, reason: str) -> Optional[float]:
        """Record a removal; returns the quarantine delay (seconds) when
        this removal tipped the rank into (deeper) probation, else
        None."""
        with self._lock:
            rec = self._rec(rank)
            admitted, rec["admitted_at"] = rec["admitted_at"], None
            if reason not in STRIKE_REASONS or admitted is None:
                return None
            now = self._now()
            if now - admitted > self.window_s:
                # it served long enough: that is a failure, not a flap
                rec["strikes"] = 0
                return None
            rec["strikes"] += 1
            if rec["strikes"] < self.strikes_to_quarantine:
                return None
            episode = rec["strikes"] - self.strikes_to_quarantine + 1
            delay = backoff_delay(episode, self.base_s, self.max_s,
                                  self._rng)
            rec["blocked_until"] = now + delay
            return delay

    def blocked(self, rank: int) -> bool:
        with self._lock:
            rec = self._records.get(int(rank))
            return (rec is not None
                    and self._now() < rec["blocked_until"])

    def strikes(self, rank: int) -> int:
        with self._lock:
            rec = self._records.get(int(rank))
            return 0 if rec is None else int(rec["strikes"])

    def forget(self, rank: int) -> None:
        """Drop a rank's history (operator override)."""
        with self._lock:
            self._records.pop(int(rank), None)


class FleetAutoscaler:
    """SLO-driven controller for a FleetRouter's replica pool.

    ``spawn_fn(rank) -> handle`` must bring up a replica that joins the
    router's rendezvous directory (an in-process ``FleetReplica``
    factory in tests/smoke; a process/VM launcher in production) and
    return a handle; ``drain_fn(rank, handle) -> bool`` retires it
    (default: ``handle.drain(drain_grace_s)`` — the zero-drop seam).
    The controller only ever drains replicas *it* spawned; pre-existing
    members are the operator's.

    ``tick()`` is the whole policy and is safe to call manually
    (``start=False`` + an injected ``now_fn`` make the tests
    deterministic); the controller thread just calls it every
    ``tick_s``. ``drain()`` stops and joins the thread."""

    def __init__(self, router: Any,
                 spawn_fn: Callable[[int], Any],
                 drain_fn: Optional[Callable[[int, Any], bool]] = None,
                 *,
                 min_replicas: int = 1, max_replicas: int = 3,
                 queue_high: int = 4,
                 slo_ttft_p99_ms: Optional[float] = None,
                 breach_on_open_breaker: bool = True,
                 up_ticks: int = 3, down_ticks: int = 10,
                 up_cooldown_s: float = 5.0,
                 down_cooldown_s: float = 10.0,
                 brownout: bool = True,
                 brownout_enter_ticks: int = 6,
                 brownout_exit_ticks: int = 4,
                 tick_s: float = 0.5,
                 drain_grace_s: float = 15.0,
                 spawn_grace_s: float = 30.0,
                 start: bool = True,
                 now_fn: Callable[[], float] = time.monotonic):
        self.router = router
        self.spawn_fn = spawn_fn
        self.drain_fn = drain_fn
        self.min_replicas = max(0, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.queue_high = max(1, int(queue_high))
        self.slo_ttft_p99_ms = (None if slo_ttft_p99_ms is None
                                else float(slo_ttft_p99_ms))
        self.breach_on_open_breaker = bool(breach_on_open_breaker)
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.brownout_enabled = bool(brownout)
        self.brownout_enter_ticks = max(1, int(brownout_enter_ticks))
        self.brownout_exit_ticks = max(1, int(brownout_exit_ticks))
        self.tick_s = float(tick_s)
        self.drain_grace_s = float(drain_grace_s)
        self.spawn_grace_s = float(spawn_grace_s)
        self._now = now_fn

        # controller state: mutated only on the tick caller (the
        # controller thread, or the test driving tick() by hand) —
        # _lock guards the handle map, which drain()/handles() read
        # from other threads
        self._lock = threading.Lock()
        self._owned: Dict[int, Any] = {}
        self._spawned_at: Dict[int, float] = {}
        self._was_member: Set[int] = set()
        self._next_rank = 0
        self._breach_streak = 0
        self._calm_streak = 0
        self._idle_streak = 0
        self._next_up_at = 0.0
        self._next_down_at = 0.0
        self._brownout = False

        reg = get_registry()
        self._m_up = reg.counter(
            "fleet_autoscale_up_total", help="autoscaler scale-up spawns")
        self._m_down = reg.counter(
            "fleet_autoscale_down_total",
            help="autoscaler scale-down drains")
        self._m_decisions = reg.labeled_counter(
            "fleet_autoscale_decisions_total",
            help="autoscaler tick decisions by action/reason")
        self._m_spawn_failures = reg.counter(
            "fleet_autoscale_spawn_failures_total",
            help="spawn_fn raised or the spawn never joined")
        self._m_brownout_entries = reg.counter(
            "fleet_brownout_entries_total",
            help="brownout episodes entered")
        self._g_target = reg.gauge(
            "fleet_target_replicas",
            help="autoscaler's current target pool size")
        self._g_target.set(max(self.min_replicas, 1))

        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._drained = False
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="fleet-autoscaler", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------ signals
    def _read_breach(self, snap: dict) -> List[str]:
        """Reasons the current snapshot violates the SLO (empty = no
        breach)."""
        reasons: List[str] = []
        queued = int(snap.get("queued", 0))
        if queued >= self.queue_high:
            reasons.append(f"queue_depth={queued}>={self.queue_high}")
        worst_ttft = max(
            (float(r.get("ttft_p99_ms") or 0.0)
             for r in snap.get("replicas", {}).values()), default=0.0)
        if (self.slo_ttft_p99_ms is not None
                and worst_ttft > self.slo_ttft_p99_ms):
            reasons.append(
                f"ttft_p99={worst_ttft:.0f}ms>{self.slo_ttft_p99_ms:.0f}ms")
        if self.breach_on_open_breaker:
            opened = [r for r, st in snap.get("replicas", {}).items()
                      if st.get("breaker") == 2]
            if opened:
                reasons.append(f"breakers_open={sorted(opened)}")
        return reasons

    def _is_idle(self, snap: dict) -> bool:
        """Quiet enough to consider shrinking: nothing queued, no open
        breaker, TTFT comfortably under the SLO."""
        if int(snap.get("queued", 0)) > 0:
            return False
        for st in snap.get("replicas", {}).values():
            if st.get("breaker") == 2:
                return False
            ttft = float(st.get("ttft_p99_ms") or 0.0)
            if (self.slo_ttft_p99_ms is not None
                    and ttft > 0.5 * self.slo_ttft_p99_ms):
                return False
        return True

    # ----------------------------------------------------------- ownership
    def _reconcile_owned(self, members: Set[int], now: float) -> None:
        """Forget handles for owned replicas that are gone: a spawn
        that never joined inside ``spawn_grace_s`` failed; a member
        that vanished died (the flap tracker, not us, judges it)."""
        with self._lock:
            for rank in list(self._owned):
                if rank in members:
                    self._was_member.add(rank)
                    continue
                if rank in self._was_member:
                    self._owned.pop(rank, None)
                    self._spawned_at.pop(rank, None)
                elif now - self._spawned_at.get(rank, now) \
                        > self.spawn_grace_s:
                    self._owned.pop(rank, None)
                    self._spawned_at.pop(rank, None)
                    self._m_spawn_failures.inc()
                    flight_record("autoscale", "spawn_abandoned",
                                  rank=rank)

    def _pending_spawn(self, members: Set[int]) -> bool:
        with self._lock:
            return any(r not in members for r in self._owned)

    def _fresh_rank(self, members: Set[int]) -> int:
        with self._lock:
            used = members | set(self._owned) | self._was_member
        rank = max([self._next_rank] + [r + 1 for r in used])
        self._next_rank = rank + 1
        return rank

    def handles(self) -> Dict[int, Any]:
        """The replicas this controller spawned and still tracks."""
        with self._lock:
            return dict(self._owned)

    # ---------------------------------------------------------------- tick
    def tick(self) -> dict:
        """One control decision. Returns the decision record (what the
        flight event carries) for tests and callers."""
        snap = self.router.load_snapshot()
        members: Set[int] = set(snap.get("replicas", {}))
        now = self._now()
        self._reconcile_owned(members, now)
        n = len(members)

        reasons = self._read_breach(snap)
        breach = bool(reasons)
        if breach:
            self._breach_streak += 1
            self._calm_streak = 0
            self._idle_streak = 0
        else:
            self._breach_streak = 0
            self._calm_streak += 1
            self._idle_streak = (self._idle_streak + 1
                                 if self._is_idle(snap) else 0)

        decision = {"action": "hold", "reason": "steady", "members": n,
                    "epoch": snap.get("epoch"), "breach": breach}
        if breach and self._breach_streak >= self.up_ticks:
            decision.update(self._try_scale_up(members, now, reasons))
        elif (not breach and self._idle_streak >= self.down_ticks
              and n > self.min_replicas):
            decision.update(self._try_scale_down(snap, members, now))

        self._update_brownout(breach, n, reasons)
        self._m_decisions.labels(action=decision["action"]).inc()
        return decision

    def _try_scale_up(self, members: Set[int], now: float,
                      reasons: List[str]) -> dict:
        if len(members) >= self.max_replicas:
            return {"action": "hold", "reason": "at_max"}
        if self._pending_spawn(members):
            return {"action": "hold", "reason": "spawn_pending"}
        if now < self._next_up_at:
            return {"action": "hold", "reason": "up_cooldown"}
        rank = self._fresh_rank(members)
        try:
            handle = self.spawn_fn(rank)
        except Exception as exc:  # the pool must survive a bad launcher
            logger.exception("autoscale: spawn_fn(%d) failed", rank)
            self._m_spawn_failures.inc()
            flight_record("autoscale", "spawn_failed", rank=rank,
                          error=repr(exc))
            return {"action": "hold", "reason": "spawn_failed"}
        with self._lock:
            self._owned[rank] = handle
            self._spawned_at[rank] = now
        self._next_up_at = now + self.up_cooldown_s
        target = min(self.max_replicas, len(members) + 1)
        self._g_target.set(target)
        self._m_up.inc()
        why = ";".join(reasons)
        get_tracer().instant("autoscale_up", rank=rank, reason=why)
        flight_record("autoscale", "scale_up", rank=rank, reason=why,
                      members=len(members), target=target)
        return {"action": "up", "reason": why, "rank": rank}

    def _try_scale_down(self, snap: dict, members: Set[int],
                        now: float) -> dict:
        if now < self._next_down_at:
            return {"action": "hold", "reason": "down_cooldown"}
        with self._lock:
            candidates = [r for r in self._owned if r in members]
        if not candidates:
            return {"action": "hold", "reason": "no_owned_member"}
        # retire the least-loaded owned member (ties: highest rank, so
        # repeated downs peel the newest spawns first)
        stats = snap.get("replicas", {})
        victim = min(candidates,
                     key=lambda r: (float(stats.get(r, {}).get("score",
                                                               0.0)), -r))
        with self._lock:
            handle = self._owned.pop(victim, None)
            self._spawned_at.pop(victim, None)
        self._next_down_at = now + self.down_cooldown_s
        target = max(self.min_replicas, len(members) - 1)
        self._g_target.set(target)
        self._m_down.inc()
        get_tracer().instant("autoscale_down", rank=victim)
        flight_record("autoscale", "scale_down", rank=victim,
                      members=len(members), target=target)
        # the drain itself runs on the tick caller, outside every lock:
        # it blocks for up to drain_grace_s by design (zero-drop seam)
        emptied = self._drain_handle(victim, handle)
        flight_record("autoscale", "scale_down_drained", rank=victim,
                      emptied=bool(emptied))
        return {"action": "down", "reason": "idle", "rank": victim,
                "emptied": bool(emptied)}

    def _drain_handle(self, rank: int, handle: Any) -> bool:
        try:
            if self.drain_fn is not None:
                return bool(self.drain_fn(rank, handle))
            return bool(handle.drain(self.drain_grace_s))
        except Exception:
            logger.exception("autoscale: drain of replica %d failed", rank)
            return False

    def _update_brownout(self, breach: bool, members: int,
                         reasons: List[str]) -> None:
        if not self.brownout_enabled:
            return
        if (not self._brownout and breach
                and members >= self.max_replicas
                and self._breach_streak >= self.brownout_enter_ticks):
            self._brownout = True
            self._m_brownout_entries.inc()
            self.router.set_brownout(True, reason=";".join(reasons))
        elif (self._brownout
                and self._calm_streak >= self.brownout_exit_ticks):
            self._brownout = False
            self.router.set_brownout(False, reason="recovered")

    # ------------------------------------------------------------ lifecycle
    def _loop(self) -> None:
        while not self._stop_evt.wait(self.tick_s):
            try:
                self.tick()
            except Exception:  # a bad tick must not kill the controller
                logger.exception("autoscale tick failed")

    def drain(self, drain_owned: bool = False) -> None:
        """Stop the controller and JOIN its thread; with
        ``drain_owned=True`` also retire (zero-drop) every replica this
        controller spawned. Idempotent."""
        with self._lock:
            if self._drained:
                return
            self._drained = True
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=max(5.0, 4 * self.tick_s))
            self._thread = None
        if drain_owned:
            for rank, handle in sorted(self.handles().items()):
                self._drain_handle(rank, handle)
                with self._lock:
                    self._owned.pop(rank, None)
                    self._spawned_at.pop(rank, None)
        flight_record("autoscale", "controller_drained",
                      owned=len(self.handles()))

    def close(self) -> None:
        self.drain()
