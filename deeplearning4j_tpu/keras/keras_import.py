"""Keras model import: HDF5 -> framework configs + weights.

Ref: deeplearning4j-modelimport/.../keras/{KerasModelImport.java:48-284,
KerasModel.java, KerasSequentialModel.java, KerasLayer.java (1189 LoC of
layer mapping + dim-ordering fixups)}.

Supports Keras 1.x and 2.x saved models (``model.save`` -> model_config
attr + /model_weights, or ``save_weights`` -> weights at root):

- Sequential -> MultiLayerNetwork
- Functional Model (linear + Add/Concatenate merges) -> ComputationGraph

Weight-layout translation notes (the part KerasLayer.java spends most of
its 1189 lines on):
- Dense kernel [in, out] == our [in, out]; no transpose.
- Conv2D TF ordering [kh, kw, in, out] == our HWIO; TH ordering
  [out, in, kh, kw] is transposed to HWIO.
- LSTM: Keras gate order is (i, f, c, o); our gate blocks are (i, f, g, o)
  with g == c — the orders coincide by design (see
  nn/layers/recurrent.py docstring), so kernels copy straight through.
  Keras 1.x per-gate matrices (W_i, U_i, b_i, ...) are concatenated.
- BatchNormalization: gamma/beta -> params; moving mean/var -> layer state.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.keras.hdf5 import Hdf5Archive
from deeplearning4j_tpu.nn.conf.builder import (
    MultiLayerConfiguration, NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (
    GRU, LSTM, ActivationLayer, BatchNormalization, Convolution1DLayer,
    ConvolutionLayer, DenseLayer, DropoutLayer, EmbeddingLayer,
    GlobalPoolingLayer, LayerNormalization, OutputLayer, PermuteLayer,
    RepeatVectorLayer, ReshapeLayer, SimpleRnn, Subsampling1DLayer,
    SubsamplingLayer, TimeDistributedLayer, ZeroPaddingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_KERAS_ACTIVATIONS = {
    "linear": "identity", "relu": "relu", "sigmoid": "sigmoid",
    "tanh": "tanh", "softmax": "softmax", "softplus": "softplus",
    "softsign": "softsign", "hard_sigmoid": "hardsigmoid", "elu": "elu",
    "selu": "selu", "swish": "swish", "gelu": "gelu",
}


def _act(name: Optional[str]) -> str:
    return _KERAS_ACTIVATIONS.get(name or "linear", "identity")


def _cfg(layer_cfg: dict) -> dict:
    return layer_cfg.get("config", layer_cfg)


class KerasLayerMapper:
    """class_name -> layer conf (ref: KerasLayer.getKerasLayerFromConfig)."""

    @staticmethod
    def map(class_name: str, cfg: dict):
        if class_name == "Dense":
            units = cfg.get("units", cfg.get("output_dim"))
            return DenseLayer(n_out=int(units), activation=_act(cfg.get("activation")))
        if class_name in ("Conv2D", "Convolution2D"):
            filters = cfg.get("filters", cfg.get("nb_filter"))
            if "kernel_size" in cfg:
                kh, kw = cfg["kernel_size"]
            else:
                kh, kw = cfg.get("nb_row"), cfg.get("nb_col")
            strides = tuple(cfg.get("strides", cfg.get("subsample", (1, 1))))
            pad = cfg.get("padding", cfg.get("border_mode", "valid"))
            mode = "same" if pad == "same" else "truncate"
            dil = tuple(cfg.get("dilation_rate", (1, 1)))
            return ConvolutionLayer(n_out=int(filters), kernel_size=(kh, kw),
                                    stride=strides, dilation=dil,
                                    convolution_mode=mode,
                                    activation=_act(cfg.get("activation")))
        if class_name in ("Conv1D", "Convolution1D"):
            # ref: the reference's convolution translator handles 1-D too
            # (modelimport/.../layers/KerasConvolution.java); Keras 1.x
            # spells the hyperparams filter_length/subsample_length
            filters = cfg.get("filters", cfg.get("nb_filter"))
            k = (cfg["kernel_size"][0] if "kernel_size" in cfg
                 else cfg.get("filter_length"))
            strides = cfg.get("strides", cfg.get("subsample_length", 1))
            s = strides[0] if isinstance(strides, (list, tuple)) else strides
            pad = cfg.get("padding", cfg.get("border_mode", "valid"))
            if pad == "causal":
                raise ValueError("Conv1D padding='causal' is not supported")
            dil = cfg.get("dilation_rate", 1)
            dil = dil[0] if isinstance(dil, (list, tuple)) else dil
            return Convolution1DLayer(
                n_out=int(filters), kernel_size=(int(k), 1),
                stride=(int(s), 1), dilation=(int(dil), 1),
                convolution_mode="same" if pad == "same" else "truncate",
                activation=_act(cfg.get("activation")))
        if class_name in ("MaxPooling1D", "AveragePooling1D"):
            pool = cfg.get("pool_size", cfg.get("pool_length", 2))
            p0 = pool[0] if isinstance(pool, (list, tuple)) else pool
            strides = cfg.get("strides", cfg.get("stride")) or p0
            s = strides[0] if isinstance(strides, (list, tuple)) else strides
            pad = cfg.get("padding", cfg.get("border_mode", "valid"))
            return Subsampling1DLayer(
                pooling_type="max" if class_name.startswith("Max") else "avg",
                kernel_size=(int(p0), 1), stride=(int(s), 1),
                convolution_mode="same" if pad == "same" else "truncate")
        if class_name in ("MaxPooling2D", "AveragePooling2D"):
            pool = tuple(cfg.get("pool_size", (2, 2)))
            strides = tuple(cfg.get("strides") or pool)
            pad = cfg.get("padding", cfg.get("border_mode", "valid"))
            return SubsamplingLayer(
                pooling_type="max" if class_name.startswith("Max") else "avg",
                kernel_size=pool, stride=strides,
                convolution_mode="same" if pad == "same" else "truncate")
        if class_name in ("GlobalMaxPooling2D", "GlobalAveragePooling2D",
                          "GlobalMaxPooling1D", "GlobalAveragePooling1D"):
            return GlobalPoolingLayer(
                pooling_type="max" if "Max" in class_name else "avg")
        if class_name == "Flatten":
            return "flatten"
        if class_name == "Dropout":
            # Keras stores drop prob; our conf stores retain prob (DL4J-style)
            rate = cfg.get("rate", cfg.get("p", 0.5))
            return DropoutLayer(dropout=1.0 - float(rate))
        if class_name == "Activation":
            return ActivationLayer(activation=_act(cfg.get("activation")))
        if class_name == "LayerNormalization":
            axis = cfg.get("axis", -1)
            if isinstance(axis, (list, tuple)):
                axis = axis[0] if len(axis) == 1 else axis
            if axis not in (-1,):
                raise ValueError(
                    f"LayerNormalization axis={axis} unsupported (only the "
                    "last/feature axis)")
            if not cfg.get("scale", True) or not cfg.get("center", True):
                raise ValueError("LayerNormalization with scale=False or "
                                 "center=False is unsupported")
            return LayerNormalization(eps=float(cfg.get("epsilon", 1e-5)))
        if class_name == "BatchNormalization":
            return BatchNormalization(eps=float(cfg.get("epsilon", 1e-5)),
                                      decay=float(cfg.get("momentum", 0.99)))
        if class_name == "ZeroPadding2D":
            p = cfg.get("padding", (1, 1))
            if isinstance(p, (list, tuple)) and len(p) == 2 \
                    and isinstance(p[0], (list, tuple)):
                (t, b), (l, r) = p
            elif isinstance(p, (list, tuple)):
                t, b, l, r = p[0], p[0], p[1], p[1]
            else:
                t = b = l = r = int(p)
            return ZeroPaddingLayer(pad=(t, b, l, r))
        if class_name == "LSTM":
            units = cfg.get("units", cfg.get("output_dim"))
            return LSTM(n_out=int(units),
                        activation=_act(cfg.get("activation", "tanh")),
                        gate_activation=_act(cfg.get("recurrent_activation",
                                                     cfg.get("inner_activation",
                                                             "sigmoid"))),
                        forget_gate_bias_init=0.0)
        if class_name == "GRU":
            units = cfg.get("units", cfg.get("output_dim"))
            # Keras >= 2.1 always writes reset_after; its absence means a
            # legacy (Keras 1.x) config whose math is reset-BEFORE
            return GRU(n_out=int(units),
                       activation=_act(cfg.get("activation", "tanh")),
                       gate_activation=_act(cfg.get("recurrent_activation",
                                                    cfg.get("inner_activation",
                                                            "sigmoid"))),
                       reset_after=bool(cfg.get("reset_after", False)))
        if class_name == "SimpleRNN":
            units = cfg.get("units", cfg.get("output_dim"))
            return SimpleRnn(n_out=int(units),
                             activation=_act(cfg.get("activation", "tanh")))
        if class_name == "Reshape":
            return ReshapeLayer(target_shape=tuple(cfg["target_shape"]))
        if class_name == "Permute":
            return PermuteLayer(dims=tuple(cfg["dims"]))
        if class_name == "RepeatVector":
            return RepeatVectorLayer(n=int(cfg["n"]))
        if class_name == "ZeroPadding1D":
            p = cfg.get("padding", 1)
            if isinstance(p, (list, tuple)):
                l, r = (p[0], p[1]) if len(p) == 2 else (p[0], p[0])
            else:
                l = r = int(p)
            from deeplearning4j_tpu.nn.layers import ZeroPadding1DLayer
            return ZeroPadding1DLayer(padding=(int(l), int(r)))
        if class_name == "TimeDistributedDense":
            # Keras 1.x spelling of TimeDistributed(Dense); reuse the
            # Dense mapping so future Dense fixes cover this path too
            return TimeDistributedLayer(
                inner=KerasLayerMapper.map("Dense", cfg))
        if class_name == "TimeDistributed":
            inner_cfg = cfg["layer"]
            inner = KerasLayerMapper.map(inner_cfg["class_name"],
                                         _cfg(inner_cfg))
            if isinstance(inner, str) or not hasattr(inner, "apply"):
                raise ValueError(
                    f"TimeDistributed({inner_cfg['class_name']}) unsupported")
            return TimeDistributedLayer(inner=inner)
        if class_name == "Embedding":
            return EmbeddingLayer(n_out=int(cfg.get("output_dim")),
                                  n_in=int(cfg.get("input_dim")),
                                  activation="identity")
        if class_name == "InputLayer":
            return "input"
        raise ValueError(f"Unsupported Keras layer type {class_name!r}")


def _input_type_from_config(cfg: dict) -> Optional[InputType]:
    shape = cfg.get("batch_input_shape") or cfg.get("batch_shape")
    if shape is None:
        return None
    dims = [d for d in shape[1:]]
    if len(dims) == 1 and dims[0] is not None:
        return InputType.feed_forward(dims[0])
    if len(dims) == 2:
        return InputType.recurrent(dims[1], dims[0])
    if len(dims) == 3:
        # Keras TF ordering: (h, w, c)
        return InputType.convolutional(dims[0], dims[1], dims[2])
    return None


# Keras merge-layer class -> vertex factory. Keras 1.x used a single
# "Merge" layer with a mode string; Keras 2.x has one class per op
# (ref: KerasMerge.java mapping to DL4J MergeVertex/ElementWiseVertex).
def _concat_vertex(cfg: dict) -> MergeVertex:
    axis = cfg.get("axis", cfg.get("concat_axis", -1))
    if axis not in (-1, 3):
        # MergeVertex concatenates along the feature (last) axis; Keras
        # channels-last models use axis=-1 (default) or axis=3 (NHWC
        # channel axis, e.g. keras.applications Inception/ResNet). Anything
        # else (channels_first retrain, time-axis concat) has no mapping.
        raise ValueError(
            f"Concatenate axis={axis} unsupported (only the last/feature "
            "axis maps to MergeVertex)")
    return MergeVertex()


_MERGE_CLASSES = {
    "Add": lambda cfg: ElementWiseVertex(op="add"),
    "Subtract": lambda cfg: ElementWiseVertex(op="subtract"),
    "Multiply": lambda cfg: ElementWiseVertex(op="product"),
    "Average": lambda cfg: ElementWiseVertex(op="average"),
    "Maximum": lambda cfg: ElementWiseVertex(op="max"),
    "Concatenate": _concat_vertex,
}

_KERAS1_MERGE_MODES = {
    "sum": lambda: ElementWiseVertex(op="add"),
    "mul": lambda: ElementWiseVertex(op="product"),
    "ave": lambda: ElementWiseVertex(op="average"),
    "max": lambda: ElementWiseVertex(op="max"),
    "concat": lambda: MergeVertex(),
}


def _inbound_names(inbound_nodes) -> List[str]:
    """Source-layer names of a layer's first inbound node.

    Handles the nested-list format (Keras 1.x/2.x:
    ``[[["src", 0, 0, {}], ...]]``) and the dict format (TF-Keras 2.13+ /
    Keras 3: ``[{"args": [<keras tensors with keras_history>], ...}]``).
    Ref: KerasModel.java inbound-node graph walk.
    """
    if not inbound_nodes:
        return []
    node0 = inbound_nodes[0]
    names: List[str] = []
    if isinstance(node0, dict):
        def walk(obj):
            if isinstance(obj, dict):
                if obj.get("class_name") == "__keras_tensor__":
                    hist = obj.get("config", {}).get("keras_history")
                    if hist:
                        names.append(hist[0])
                    return
                for v in obj.values():
                    walk(v)
            elif isinstance(obj, (list, tuple)):
                for v in obj:
                    walk(v)
        walk(node0)
    else:
        for entry in node0:
            if isinstance(entry, (list, tuple)) and entry:
                names.append(entry[0])
            elif isinstance(entry, str):
                names.append(entry)
    return names


def _layer_ref_name(ref) -> str:
    """'fc1000' from an input_layers/output_layers entry (list or str)."""
    if isinstance(ref, (list, tuple)):
        return ref[0]
    return ref


def _layer_refs(val) -> List[str]:
    """Normalize input_layers/output_layers: either a list of refs
    (``[["a",0,0], ["b",0,0]]`` or ``["a","b"]``) or ONE flat ref
    (``["a", 0, 0]`` — Keras 3 single-input form)."""
    if not val:
        return []
    if (isinstance(val, (list, tuple)) and isinstance(val[0], str)
            and len(val) == 3 and isinstance(val[1], int)):
        return [val[0]]
    return [_layer_ref_name(r) for r in val]


def _snake(name: str) -> str:
    """CamelCase -> snake_case, matching Keras's auto object naming
    ('Conv2D' -> 'conv2d', 'SimpleRNN' -> 'simple_rnn')."""
    import re
    s = re.sub(r"\W+", "", name)
    s = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", s)
    s = re.sub(r"([a-z])([A-Z])", r"\1_\2", s)
    return s.lower()


class KerasModelImport:
    """Static entry points (ref: KerasModelImport.java:101
    importKerasSequentialModelAndWeights / importKerasModelAndWeights).

    Accepts legacy HDF5 files (the format the reference supports) AND the
    modern Keras-3 ``.keras`` zip format (config.json +
    model.weights.h5) — an extension beyond the reference's importer.
    """

    @staticmethod
    def import_keras_sequential_model_and_weights(path: str,
                                                  enforce_training_config: bool = False
                                                  ) -> MultiLayerNetwork:
        import zipfile
        if zipfile.is_zipfile(path):
            net = KerasModelImport._import_keras_v3(
                path, require="Sequential")
            return net
        with Hdf5Archive(path) as h5:
            cfg_json = h5.read_attribute_as_string("model_config")
            if cfg_json is None:
                raise ValueError(f"{path!r} has no model_config attribute")
            model_cfg = json.loads(cfg_json)
            if model_cfg.get("class_name") != "Sequential":
                raise ValueError("Not a Sequential model; use "
                                 "import_keras_model_and_weights")
            layer_cfgs = model_cfg["config"]
            if isinstance(layer_cfgs, dict):  # Keras 2.2+: {'layers': [...]}
                layer_cfgs = layer_cfgs["layers"]
            net = KerasModelImport._build_sequential(layer_cfgs)
            KerasModelImport._load_sequential_weights(h5, net, layer_cfgs)
        return net

    # alias with the reference's naming
    importKerasSequentialModelAndWeights = import_keras_sequential_model_and_weights

    @staticmethod
    def import_keras_model_and_weights(path: str,
                                       enforce_training_config: bool = False
                                       ):
        """Functional ``Model`` -> ComputationGraph; Sequential models are
        delegated to the sequential path (ref: KerasModelImport.java:101,
        KerasModel.java getComputationGraphConfiguration/getComputationGraph).
        """
        import zipfile
        if zipfile.is_zipfile(path):
            return KerasModelImport._import_keras_v3(path)
        with Hdf5Archive(path) as h5:
            cfg_json = h5.read_attribute_as_string("model_config")
            if cfg_json is None:
                raise ValueError(f"{path!r} has no model_config attribute")
            model_cfg = json.loads(cfg_json)
            cls = model_cfg.get("class_name")
            if cls == "Sequential":
                net = None  # delegate below (reopens the archive once)
            elif cls in ("Model", "Functional"):
                net = KerasModelImport._build_graph(model_cfg["config"])
                KerasModelImport._load_graph_weights(h5, net)
            else:
                raise ValueError(f"Unsupported Keras model class {cls!r}")
        if net is None:
            return KerasModelImport.import_keras_sequential_model_and_weights(
                path, enforce_training_config)
        return net

    # alias with the reference's naming
    importKerasModelAndWeights = import_keras_model_and_weights

    @staticmethod
    def _build_graph(cfg: dict) -> ComputationGraph:
        """Functional-config DAG -> ComputationGraphConfiguration.

        InputLayer nodes become network inputs; merge layers become
        Merge/ElementWise vertices; Flatten collapses into the auto
        CnnToFeedForward preprocessor (alias to its upstream node); the
        Dense feeding each network output becomes an OutputLayer so the
        imported net is trainable (ref: KerasModel.java:1-647).
        """
        layer_cfgs: List[dict] = cfg["layers"]
        input_names = _layer_refs(cfg.get("input_layers", []))
        output_names = _layer_refs(cfg.get("output_layers", []))

        b = NeuralNetConfiguration.builder().seed(12345)
        gb = b.graph_builder()

        # alias: keras layer name -> graph node name that produces its output
        alias: Dict[str, str] = {}
        input_types: Dict[str, InputType] = {}
        # pre-scan: which keras names feed a network output (for OutputLayer
        # conversion) — a Dense is a loss head only if it IS an output
        out_set = set(output_names)
        kept_names: List[str] = []  # layer nodes that own weights, in order

        # Network inputs MUST follow cfg["input_layers"] order, not the
        # layers-list encounter order (Keras stores layers in traversal
        # order) — callers zip positional inputs against this order.
        by_name = {(_cfg(lc).get("name", lc.get("name"))): lc
                   for lc in layer_cfgs}
        if not input_names:  # older configs: fall back to encounter order
            input_names = [_cfg(lc).get("name", lc.get("name"))
                           for lc in layer_cfgs
                           if lc["class_name"] == "InputLayer"]
        for iname in input_names:
            kcfg = _cfg(by_name[iname])
            it = _input_type_from_config(kcfg)
            if it is None:
                raise ValueError(f"InputLayer {iname!r} has no "
                                 "batch_input_shape")
            gb.add_inputs(iname)
            input_types[iname] = it
            alias[iname] = iname

        groups: Dict[str, str] = {}  # node name -> h5 group path rel. root
        for lc in layer_cfgs:
            cls = lc["class_name"]
            kcfg = _cfg(lc)
            name = kcfg.get("name", lc.get("name"))
            inbound = lc.get("inbound_nodes", [])
            if len(inbound) > 1:
                raise ValueError(
                    f"Layer {name!r} is shared (called {len(inbound)} "
                    "times); shared-layer import is unsupported")
            srcs = [alias[s] for s in _inbound_names(inbound)]
            if cls == "InputLayer":
                continue  # added above, in input_layers order
            alias[name] = KerasModelImport._emit_layer(
                gb, kept_names, groups, name, cls, kcfg, srcs, out_set,
                name)

        gb.set_outputs(*[alias[o] for o in output_names])
        gb.set_input_types(*[input_types[i] for i in input_types])
        conf = gb.build()
        net = ComputationGraph(conf)
        net.init()
        net._keras_names = kept_names  # node name == keras layer name
        net._keras_groups = groups
        return net

    @staticmethod
    def _emit_layer(gb, kept, groups, node_name, cls, kcfg, srcs, out_set,
                    h5_path, nested_ctx=None):
        """Add one Keras layer (or merge vertex, or nested submodel) to the
        graph builder; returns the node name producing its output.
        ``h5_path`` is the weight-group path (or list of candidate paths)
        relative to the weights root — the keras name at top level;
        nested layers live at ``<outer>/<outer>/<inner>`` (Sequential
        submodels) or ``<outer>/<inner>`` (functional submodels) in the
        legacy HDF5 layout, so nested nodes carry both candidates.
        ``nested_ctx``: (top outer name, relative prefix) when emitting
        inside a submodel."""
        if cls in _MERGE_CLASSES:
            gb.add_vertex(node_name, _MERGE_CLASSES[cls](kcfg), *srcs)
            return node_name
        if cls == "Merge":  # Keras 1.x
            mode = kcfg.get("mode", "sum")
            if mode not in _KERAS1_MERGE_MODES:
                raise ValueError(f"Unsupported Merge mode {mode!r}")
            gb.add_vertex(node_name, _KERAS1_MERGE_MODES[mode](), *srcs)
            return node_name
        if cls in ("Sequential", "Functional", "Model"):
            top, rel = nested_ctx or (node_name, "")
            return KerasModelImport._inline_submodel(
                gb, kept, groups, node_name, cls, kcfg, srcs, out_set,
                top, rel)
        mapped = KerasLayerMapper.map(cls, kcfg)
        if mapped in ("flatten", "input"):
            # collapses into the auto preprocessor of the consumer
            return srcs[0]
        if node_name in out_set and isinstance(mapped, DenseLayer) \
                and not isinstance(mapped, OutputLayer):
            loss = "mcxent" if mapped.activation == "softmax" else "mse"
            mapped = OutputLayer(n_out=mapped.n_out,
                                 activation=mapped.activation, loss=loss)
        gb.add_layer(node_name, mapped, *srcs)
        kept.append(node_name)
        groups[node_name] = h5_path
        if isinstance(mapped, (LSTM, GRU, SimpleRnn)) \
                and not kcfg.get("return_sequences", False):
            # Keras LSTM default emits only the final step; ours emits
            # the sequence — append a LastTimeStepVertex
            from deeplearning4j_tpu.nn.conf.graph import LastTimeStepVertex
            gb.add_vertex(node_name + "__last", LastTimeStepVertex(),
                          node_name)
            return node_name + "__last"
        return node_name

    @staticmethod
    def _inline_submodel(gb, kept, groups, outer_name, cls, kcfg, srcs,
                         out_set, top, rel_prefix):
        """Inline a nested Sequential/Functional model as prefixed graph
        nodes (ref: KerasModel.java handles nested models by recursion).
        ``top`` is the top-level submodel's keras name (the h5 group);
        ``rel_prefix`` the path inside nested submodels so far."""
        layers_cfg = kcfg["layers"]

        def inner_emit(iname, icls, icfg, isrcs, inner_out_set):
            # '.'-separated node names: '/' would collide with the
            # sharded-checkpoint leaf-path join (parallel/checkpoint.py)
            node = f"{outer_name}.{iname}"
            rel = rel_prefix + iname
            return KerasModelImport._emit_layer(
                gb, kept, groups, node, icls, icfg, isrcs, inner_out_set,
                [f"{top}/{top}/{rel}", f"{top}/{rel}"],
                nested_ctx=(top, rel + "/"))

        # the submodel's output should become a loss head only when the
        # submodel itself IS a network output
        convert_out = outer_name in out_set

        if cls == "Sequential":
            if len(srcs) != 1:
                raise ValueError(
                    f"Nested Sequential {outer_name!r} needs exactly one "
                    f"input, got {len(srcs)}")
            # convert to a loss head only when the submodel's FINAL
            # emitting layer is a Dense (a mid-sequence Dense followed by
            # Dropout/Activation must stay an inner layer)
            fin = next((lc for lc in reversed(layers_cfg)
                        if lc["class_name"] not in ("InputLayer",
                                                    "Flatten")), None)
            inner_out = frozenset()
            if convert_out and fin is not None \
                    and fin["class_name"] == "Dense":
                fname = _cfg(fin).get("name", fin.get("name"))
                inner_out = {f"{outer_name}.{fname}"}
            prev = srcs[0]
            for lc in layers_cfg:
                icls = lc["class_name"]
                icfg = _cfg(lc)
                iname = icfg.get("name", lc.get("name"))
                if icls == "InputLayer":
                    continue
                prev = inner_emit(iname, icls, icfg, [prev], inner_out)
            return prev

        # nested functional Model: positional inputs map onto the outer
        # sources; single output only (multi-output submodels have no
        # single downstream node to alias)
        in_names = _layer_refs(kcfg.get("input_layers", []))
        if not in_names:
            in_names = [_cfg(lc).get("name", lc.get("name"))
                        for lc in layers_cfg
                        if lc["class_name"] == "InputLayer"]
        out_refs = _layer_refs(kcfg.get("output_layers", []))
        if len(out_refs) != 1:
            raise ValueError(
                f"Nested model {outer_name!r} has {len(out_refs)} "
                "outputs; only single-output submodels import")
        if len(in_names) != len(srcs):
            raise ValueError(
                f"Nested model {outer_name!r} takes {len(in_names)} "
                f"inputs, got {len(srcs)}")
        sub_alias = dict(zip(in_names, srcs))
        inner_out = ({f"{outer_name}.{out_refs[0]}"} if convert_out
                     else frozenset())
        for lc in layers_cfg:
            icls = lc["class_name"]
            icfg = _cfg(lc)
            iname = icfg.get("name", lc.get("name"))
            if icls == "InputLayer":
                continue
            inbound = lc.get("inbound_nodes", [])
            if len(inbound) > 1:
                raise ValueError(
                    f"Layer {iname!r} in nested model {outer_name!r} is "
                    "shared; shared-layer import is unsupported")
            isrcs = [sub_alias[s] for s in _inbound_names(inbound)]
            sub_alias[iname] = inner_emit(iname, icls, icfg, isrcs,
                                          inner_out)
        return sub_alias[out_refs[0]]

    # ---------------------------------------------------------- keras-3 zip
    @staticmethod
    def _import_keras_v3(path: str, require: Optional[str] = None):
        """Import the Keras-3 native ``.keras`` zip: config.json carries
        the same polymorphic model config; model.weights.h5 stores each
        layer's variables under ``layers/<class-counter-path>/vars/<i>``
        (paths use per-class counters in model-build order — 'conv2d',
        'conv2d_1', ... — NOT the user layer names)."""
        import io
        import zipfile

        import h5py

        with zipfile.ZipFile(path) as z:
            model_cfg = json.loads(z.read("config.json"))
            cls = model_cfg.get("class_name")
            if require is not None and cls != require:
                # fail BEFORE building the graph / copying weights
                raise ValueError(
                    f"Not a {require} model; use "
                    "import_keras_model_and_weights")
            wbytes = z.read("model.weights.h5")
        layer_cfgs = model_cfg["config"]
        if isinstance(layer_cfgs, dict):
            inner_layers = layer_cfgs.get("layers", [])
        else:
            inner_layers = layer_cfgs
        if any(lc["class_name"] in ("Sequential", "Functional", "Model")
               for lc in inner_layers):
            raise ValueError(
                ".keras files with nested submodels are unsupported; "
                "re-save as legacy HDF5 (model.save('m.h5'))")
        if cls == "Sequential":
            net = KerasModelImport._build_sequential(inner_layers)
        elif cls in ("Model", "Functional"):
            net = KerasModelImport._build_graph(model_cfg["config"])
        else:
            raise ValueError(f"Unsupported Keras model class {cls!r}")

        # keras layer name -> class-counter weight path, in config order
        # (== build order)
        wpaths: Dict[str, str] = {}
        counters: Dict[str, int] = {}
        for lc in inner_layers:
            snake = _snake(lc["class_name"])
            idx = counters.get(snake, 0)
            counters[snake] = idx + 1
            name = _cfg(lc).get("name", lc.get("name"))
            wpaths[name] = snake if idx == 0 else f"{snake}_{idx}"

        is_graph = isinstance(net, ComputationGraph)
        targets = (net._keras_names if is_graph
                   else list(zip(range(len(net.layers)), net._keras_names)))
        with h5py.File(io.BytesIO(wbytes), "r") as h:
            layers_grp = h["layers"]
            for entry in targets:
                li, kname = (entry, entry) if is_graph else entry
                wp = wpaths.get(kname)
                if wp is None or wp not in layers_grp:
                    continue
                grp = layers_grp[wp]
                for nested in ("cell", "layer"):  # RNNs nest vars in the
                    # cell; TimeDistributed wraps them under 'layer'
                    if ("vars" not in grp or not len(grp["vars"])) \
                            and nested in grp:
                        grp = grp[nested]
                if "vars" not in grp or not len(grp["vars"]):
                    continue
                arrs = [np.asarray(grp["vars"][str(i)])
                        for i in range(len(grp["vars"]))]
                layer = (net.conf.nodes[li].layer if is_graph
                         else net.layers[li])
                ds = KerasModelImport._name_v3_vars(layer, arrs)
                KerasModelImport._set_layer_weights(net, li, layer, ds,
                                                    tf_kernels=True)
        return net

    @staticmethod
    def _name_v3_vars(layer, arrs) -> Dict[str, np.ndarray]:
        """Assign Keras variable names to the ordered vars list (the v3
        format stores variables positionally, in layer.weights order)."""
        if isinstance(layer, BatchNormalization):
            if len(arrs) != 4:
                # scale=False / center=False drop gamma/beta from the
                # positional vars list; assigning by position would
                # silently write beta into gamma
                raise ValueError(
                    ".keras BatchNormalization with scale=False or "
                    "center=False is unsupported (positional weight "
                    f"list has {len(arrs)} entries, expected 4)")
            names = ["gamma", "beta", "moving_mean", "moving_variance"]
        elif isinstance(layer, LayerNormalization):
            names = ["gamma", "beta"]
        elif isinstance(layer, (LSTM, GRU, SimpleRnn)):
            names = ["kernel", "recurrent_kernel", "bias"]
        elif isinstance(layer, EmbeddingLayer):
            names = ["embeddings"]
        else:  # Dense / Conv / TimeDistributed-wrapped Dense
            names = ["kernel", "bias"]
        return dict(zip(names, arrs))

    @staticmethod
    def _layer_datasets(h5: Hdf5Archive, group: str) -> Dict[str, np.ndarray]:
        """{param name: array} for one layer's weight group, via the
        ``weight_names`` attr (Keras save_weights layout) or, absent that,
        the group's direct dataset children."""
        wnames = h5.read_attribute_as_string_list("weight_names", group)
        if wnames is None:
            children = h5.list_children(group)
            wnames = [n for k, n in children if k == "d"]
        return {
            wn.split("/")[-1].split(":")[0]:
                h5.read_dataset(f"{group}/{wn}".replace("//", "/"))
            for wn in wnames}

    @staticmethod
    def _load_graph_weights(h5: Hdf5Archive, net: ComputationGraph) -> None:
        root = KerasModelImport._weights_root(h5)
        groups = getattr(net, "_keras_groups", {})
        for name in net._keras_names:
            layer = net.conf.nodes[name].layer
            cand = groups.get(name, name)
            datasets = {}
            for c in ([cand] if isinstance(cand, str) else cand):
                datasets = KerasModelImport._layer_datasets(
                    h5, f"{root}/{c}".replace("//", "/"))
                if datasets:
                    break
            if not datasets:
                continue
            KerasModelImport._set_layer_weights(net, name, layer, datasets)

    @staticmethod
    def _build_sequential(layer_cfgs: List[dict]) -> MultiLayerNetwork:
        b = NeuralNetConfiguration.builder().seed(12345)
        lb = b.list()
        input_type = None
        kept: List[Tuple[dict, object]] = []  # (keras cfg, our layer)
        for lc in layer_cfgs:
            cls = lc["class_name"]
            cfg = _cfg(lc)
            if input_type is None:
                it = _input_type_from_config(cfg)
                if it is not None:
                    input_type = it
            mapped = KerasLayerMapper.map(cls, cfg)
            if mapped in ("flatten", "input"):
                continue  # flatten == our auto CnnToFeedForward preprocessor
            kept.append((lc, mapped))
            if isinstance(mapped, (LSTM, GRU, SimpleRnn)) \
                    and not cfg.get("return_sequences", False):
                # Keras LSTM default emits only the final step; ours emits
                # the sequence — append a param-free LastTimeStepLayer whose
                # synthetic name has no weight group in the h5 (skipped by
                # the weight loader)
                from deeplearning4j_tpu.nn.layers import LastTimeStepLayer
                synth = {"config": {"name": (cfg.get("name", "lstm")
                                             + "__last")}}
                kept.append((synth, LastTimeStepLayer()))
        if input_type is None:
            raise ValueError("Cannot infer input shape (no batch_input_shape)")
        # final Dense becomes an OutputLayer so the net is trainable
        for i, (lc, layer) in enumerate(kept):
            if i == len(kept) - 1 and isinstance(layer, DenseLayer) \
                    and not isinstance(layer, OutputLayer):
                loss = ("mcxent" if layer.activation == "softmax" else "mse")
                layer = OutputLayer(n_out=layer.n_out,
                                    activation=layer.activation, loss=loss)
                kept[i] = (lc, layer)
            lb.layer(layer)
        conf = lb.set_input_type(input_type).build()
        net = MultiLayerNetwork(conf)
        net.init()
        net._keras_names = [  # layer name alignment for weight loading
            _cfg(lc).get("name", lc.get("name", f"layer_{i}"))
            for i, (lc, _) in enumerate(kept)]
        return net

    @staticmethod
    def _weights_root(h5: Hdf5Archive) -> str:
        children = dict((name, kind) for kind, name in h5.list_children("/"))
        return "/model_weights" if "model_weights" in children else "/"

    @staticmethod
    def _load_sequential_weights(h5: Hdf5Archive, net: MultiLayerNetwork,
                                 layer_cfgs: List[dict]) -> None:
        root = KerasModelImport._weights_root(h5)
        for li, (layer, name) in enumerate(zip(net.layers, net._keras_names)):
            group = f"{root}/{name}".replace("//", "/")
            datasets = KerasModelImport._layer_datasets(h5, group)
            if not datasets:
                continue
            KerasModelImport._set_layer_weights(net, li, layer, datasets)

    @staticmethod
    def _set_layer_weights(net, li: int, layer, ds: Dict[str, np.ndarray],
                           tf_kernels: bool = False):
        """``tf_kernels=True`` (the .keras v3 path) asserts kernels are
        already HWIO, suppressing the legacy Theano-ordering heuristic —
        which would mis-fire on HWIO kernels whose height happens to
        equal n_out (e.g. a 3-filter 3x3 conv)."""
        p = dict(net.params[li])

        def put(name, arr):
            ref = p[name]
            arr = jnp.asarray(arr, ref.dtype)
            if arr.shape != ref.shape:
                raise ValueError(
                    f"Layer {li} ({type(layer).__name__}) param {name}: "
                    f"shape {arr.shape} != expected {ref.shape}")
            p[name] = arr

        if isinstance(layer, ConvolutionLayer):
            kernel = ds.get("kernel", ds.get("W"))
            if (not tf_kernels and kernel.ndim == 4
                    and kernel.shape[0] == layer.n_out):
                # TH ordering [out, in, kh, kw] -> HWIO
                kernel = kernel.transpose(2, 3, 1, 0)
            put("W", kernel)
            if "bias" in ds or "b" in ds:
                put("b", ds.get("bias", ds.get("b")))
        elif isinstance(layer, LayerNormalization):
            put("gamma", ds.get("gamma"))
            put("beta", ds.get("beta"))
        elif isinstance(layer, BatchNormalization):
            put("gamma", ds.get("gamma"))
            put("beta", ds.get("beta"))
            mean = ds.get("moving_mean", ds.get("running_mean"))
            var = ds.get("moving_variance", ds.get("running_std",
                                                   ds.get("running_var")))
            net.states[li] = {"mean": jnp.asarray(mean),
                              "var": jnp.asarray(var)}
        elif isinstance(layer, LSTM):
            if "kernel" in ds:  # Keras 2: fused (i, f, c, o) == our order
                put("W", ds["kernel"])
                put("RW", ds["recurrent_kernel"])
                put("b", ds.get("bias", np.zeros(p["b"].shape)))
            else:  # Keras 1: per-gate W_i/U_i/b_i...
                W = np.concatenate([ds["W_i"], ds["W_f"], ds["W_c"], ds["W_o"]],
                                   axis=-1)
                U = np.concatenate([ds["U_i"], ds["U_f"], ds["U_c"], ds["U_o"]],
                                   axis=-1)
                bvec = np.concatenate([ds["b_i"], ds["b_f"], ds["b_c"], ds["b_o"]])
                put("W", W)
                put("RW", U)
                put("b", bvec)
        elif isinstance(layer, GRU):
            if "kernel" in ds:  # Keras 2+: fused (z, r, h) == our order
                put("W", ds["kernel"])
                put("RW", ds["recurrent_kernel"])
                bias = ds.get("bias")
                if bias is not None:
                    if bias.ndim == 2:  # reset_after: [input; recurrent]
                        put("b", bias[0])
                        put("b2", bias[1])
                    else:
                        put("b", bias)
            else:  # Keras 1: per-gate W_z/U_z/b_z...
                put("W", np.concatenate([ds["W_z"], ds["W_r"], ds["W_h"]],
                                        axis=-1))
                put("RW", np.concatenate([ds["U_z"], ds["U_r"], ds["U_h"]],
                                         axis=-1))
                put("b", np.concatenate([ds["b_z"], ds["b_r"], ds["b_h"]]))
        elif isinstance(layer, SimpleRnn):
            put("W", ds.get("kernel", ds.get("W")))
            put("RW", ds.get("recurrent_kernel", ds.get("U")))
            if "bias" in ds or "b" in ds:
                put("b", ds.get("bias", ds.get("b")))
        elif isinstance(layer, TimeDistributedLayer):
            # Keras nests the wrapped layer's weights directly under the
            # TimeDistributed group; our param dict IS the inner layer's
            KerasModelImport._set_layer_weights(net, li, layer.inner, ds,
                                                tf_kernels=tf_kernels)
            return
        elif isinstance(layer, EmbeddingLayer):
            put("W", ds.get("embeddings", ds.get("W")))
            # Keras embeddings have no bias; ours stays zero
        elif isinstance(layer, DenseLayer):  # incl. OutputLayer
            put("W", ds.get("kernel", ds.get("W")))
            if "bias" in ds or "b" in ds:
                put("b", ds.get("bias", ds.get("b")))
        net.params[li] = p
