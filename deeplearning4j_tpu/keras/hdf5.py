"""HDF5 archive reader over the native C++ shim.

Ref: deeplearning4j-modelimport/.../keras/Hdf5Archive.java:22-51 — the
reference's JavaCPP->libhdf5 reader with readAttributeAsJson /
readDataSet / getDataSets. Same surface here, backed by
native/hdf5_reader.cc through ctypes.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.native_loader import load_native


class Hdf5Archive:
    def __init__(self, path: str):
        self._lib = load_native("h5reader")
        if self._lib is None:
            raise RuntimeError(
                "Native HDF5 reader unavailable (libhdf5 or toolchain "
                "missing); cannot read Keras .h5 files")
        lib = self._lib
        lib.h5r_open.restype = ctypes.c_int64
        lib.h5r_open.argtypes = [ctypes.c_char_p]
        lib.h5r_close.argtypes = [ctypes.c_int64]
        lib.h5r_read_attr_str.restype = ctypes.c_int64
        lib.h5r_read_attr_str.argtypes = [
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_char_p, ctypes.c_int64]
        lib.h5r_read_attr_strlist.restype = ctypes.c_int64
        lib.h5r_read_attr_strlist.argtypes = lib.h5r_read_attr_str.argtypes
        lib.h5r_list_children.restype = ctypes.c_int64
        lib.h5r_list_children.argtypes = [
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64]
        lib.h5r_dataset_ndims.restype = ctypes.c_int
        lib.h5r_dataset_ndims.argtypes = [ctypes.c_int64, ctypes.c_char_p]
        lib.h5r_dataset_shape.restype = ctypes.c_int
        lib.h5r_dataset_shape.argtypes = [
            ctypes.c_int64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
        lib.h5r_read_dataset_float.restype = ctypes.c_int
        lib.h5r_read_dataset_float.argtypes = [
            ctypes.c_int64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        self._file = lib.h5r_open(path.encode())
        if self._file < 0:
            raise FileNotFoundError(f"Cannot open HDF5 file {path!r}")

    def close(self):
        if self._file >= 0:
            self._lib.h5r_close(self._file)
            self._file = -1

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    # ------------------------------------------------------------- attributes
    def read_attribute_as_string(self, attr: str, obj_path: str = "/") -> Optional[str]:
        """(ref: Hdf5Archive.readAttributeAsJson / readAttributeAsString)"""
        for cap in (1 << 16, 1 << 22, 1 << 26):
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.h5r_read_attr_str(self._file, obj_path.encode(),
                                            attr.encode(), buf, cap)
            if n == -1:
                return None
            if n == -2:
                raise IOError(f"Failed reading attribute {attr!r} at {obj_path!r}")
            if n < cap:
                return buf.value.decode("utf-8", "replace")
        raise IOError(f"Attribute {attr!r} too large")

    def read_attribute_as_string_list(self, attr: str,
                                      obj_path: str = "/") -> Optional[List[str]]:
        for cap in (1 << 16, 1 << 22):
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.h5r_read_attr_strlist(self._file, obj_path.encode(),
                                                attr.encode(), buf, cap)
            if n == -1:
                return None
            if n == -2:
                raise IOError(f"Failed reading attribute {attr!r} at {obj_path!r}")
            if n < cap:
                s = buf.value.decode("utf-8", "replace")
                return s.split("\n") if s else []
        raise IOError(f"Attribute {attr!r} too large")

    # ---------------------------------------------------------------- listing
    def list_children(self, path: str = "/") -> List[Tuple[str, str]]:
        """[(kind 'g'|'d', name)] (ref: Hdf5Archive.getDataSets/getGroups)"""
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        n = self._lib.h5r_list_children(self._file, path.encode(), buf, cap)
        if n < 0:
            return []
        s = buf.value.decode("utf-8", "replace")
        out = []
        for item in s.split("\n"):
            if item:
                out.append((item[0], item[1:]))
        return out

    # -------------------------------------------------------------- writing
    @staticmethod
    def create(path: str) -> "Hdf5Writer":
        return Hdf5Writer(path)

    # --------------------------------------------------------------- datasets
    def read_dataset(self, path: str) -> np.ndarray:
        """(ref: Hdf5Archive.readDataSet)"""
        dims = (ctypes.c_int64 * 32)()
        nd = self._lib.h5r_dataset_shape(self._file, path.encode(), dims, 32)
        if nd < 0:
            raise IOError(f"Cannot read dataset {path!r}")
        shape = tuple(int(dims[i]) for i in range(nd))
        n = int(np.prod(shape)) if shape else 1
        out = np.empty(n, dtype=np.float32)
        rc = self._lib.h5r_read_dataset_float(
            self._file, path.encode(),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), n)
        if rc != 0:
            raise IOError(f"Failed reading dataset {path!r} (rc={rc})")
        return out.reshape(shape)


class Hdf5Writer:
    """Write-side companion (fixtures + Keras-compatible weight export)."""

    def __init__(self, path: str):
        self._lib = load_native("h5reader")
        if self._lib is None:
            raise RuntimeError("Native HDF5 library unavailable")
        lib = self._lib
        lib.h5w_create.restype = ctypes.c_int64
        lib.h5w_create.argtypes = [ctypes.c_char_p]
        lib.h5w_create_group.argtypes = [ctypes.c_int64, ctypes.c_char_p]
        lib.h5w_write_attr_str.argtypes = [
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p]
        lib.h5w_write_attr_strlist.argtypes = lib.h5w_write_attr_str.argtypes
        lib.h5w_write_dataset_float.argtypes = [
            ctypes.c_int64, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float)]
        self._file = lib.h5w_create(path.encode())
        if self._file < 0:
            raise IOError(f"Cannot create HDF5 file {path!r}")

    def create_group(self, path: str):
        rc = self._lib.h5w_create_group(self._file, path.encode())
        if rc != 0:
            raise IOError(f"Cannot create group {path!r}")

    def write_attr_str(self, obj_path: str, attr: str, value: str):
        rc = self._lib.h5w_write_attr_str(self._file, obj_path.encode(),
                                          attr.encode(), value.encode())
        if rc != 0:
            raise IOError(f"Cannot write attr {attr!r}")

    def write_attr_strlist(self, obj_path: str, attr: str, values: List[str]):
        rc = self._lib.h5w_write_attr_strlist(
            self._file, obj_path.encode(), attr.encode(),
            "\n".join(values).encode())
        if rc != 0:
            raise IOError(f"Cannot write attr {attr!r}")

    def write_dataset(self, path: str, data: np.ndarray):
        data = np.ascontiguousarray(data, dtype=np.float32)
        dims = (ctypes.c_int64 * 32)(*data.shape)
        rc = self._lib.h5w_write_dataset_float(
            self._file, path.encode(), dims, data.ndim,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if rc != 0:
            raise IOError(f"Cannot write dataset {path!r}")

    def close(self):
        if self._file >= 0:
            lib = self._lib
            lib.h5r_close.argtypes = [ctypes.c_int64]
            lib.h5r_close(self._file)
            self._file = -1

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
