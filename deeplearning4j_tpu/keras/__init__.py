"""Keras model import (ref: deeplearning4j-modelimport)."""

from deeplearning4j_tpu.keras.batching import BatchScheduler  # noqa: F401
from deeplearning4j_tpu.keras.hdf5 import Hdf5Archive  # noqa: F401
from deeplearning4j_tpu.keras.keras_import import KerasModelImport  # noqa: F401
from deeplearning4j_tpu.keras.server import (  # noqa: F401
    HDF5MiniBatchDataSetIterator,
    KerasClient,
    KerasServer,
)
