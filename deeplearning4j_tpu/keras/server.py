"""Keras-backend gateway server.

Ref: deeplearning4j-keras/.../Server.java:15-22 (py4j GatewayServer
exposing DeepLearning4jEntryPoint to a Python Keras client),
DeepLearning4jEntryPoint.java (fit(model, train dirs, epochs)), and
HDF5MiniBatchDataSetIterator.java (one .h5 file per minibatch in a
directory). The capability bar (SURVEY §2.2): "usable as a Keras-style
backend" — an external process drives training/inference over a socket.

This framework is already Python, so the py4j JVM gateway collapses to a
newline-delimited JSON-over-TCP protocol:

    {"op": "fit", "model": <keras .h5 path>, "features_dir": ...,
     "labels_dir": ..., "nb_epoch": N}
    {"op": "predict", "features": <.npy path>}  -> {"predictions": [...]}
    {"op": "evaluate", "features_dir": ..., "labels_dir": ...}
    {"op": "shutdown"}

Batch files: ``.npy`` or ``.h5`` (one array per file, sorted order), the
HDF5MiniBatchDataSetIterator layout.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from pathlib import Path
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator


def _load_array(path: Path) -> np.ndarray:
    if path.suffix == ".npy":
        return np.load(path)
    from deeplearning4j_tpu.keras.hdf5 import Hdf5Archive
    h5 = Hdf5Archive(str(path))
    names = h5.dataset_names()
    if not names:
        raise ValueError(f"{path}: no datasets")
    return np.asarray(h5.read_dataset(names[0]))


class HDF5MiniBatchDataSetIterator(DataSetIterator):
    """One file per minibatch, features/labels in parallel directories,
    loaded lazily per next() — the dataset need not fit in RAM
    (ref: HDF5MiniBatchDataSetIterator.java)."""

    def __init__(self, features_dir: str, labels_dir: str):
        self._f_files = sorted(p for p in Path(features_dir).iterdir()
                               if p.suffix in (".npy", ".h5"))
        self._l_files = sorted(p for p in Path(labels_dir).iterdir()
                               if p.suffix in (".npy", ".h5"))
        if len(self._f_files) != len(self._l_files):
            raise ValueError(f"{len(self._f_files)} feature files vs "
                             f"{len(self._l_files)} label files")
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._f_files)

    def next(self) -> DataSet:
        f, l = self._f_files[self._pos], self._l_files[self._pos]
        self._pos += 1
        return DataSet(_load_array(f).astype(np.float32),
                       _load_array(l).astype(np.float32))

    def batch_size(self):
        if not self._f_files:
            return 0
        return int(_load_array(self._f_files[0]).shape[0])


class KerasServer:
    """The gateway. A loaded model is cached per model path; ``fit`` /
    ``predict`` / ``evaluate`` operate on it. Runs in a daemon thread."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._models = {}
        # handler threads (ThreadingTCPServer) share _models/_last; without
        # the lock a predict that omits 'model' could resolve _last mid-swap
        # from another connection and run against the wrong model
        self._state_lock = threading.Lock()
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    try:
                        req = json.loads(line)
                        resp = outer._dispatch(req)
                    except Exception as e:  # report, keep serving
                        resp = {"error": f"{type(e).__name__}: {e}"}
                    self.wfile.write((json.dumps(resp) + "\n").encode())
                    self.wfile.flush()
                    if isinstance(resp, dict) and resp.get("shutdown"):
                        threading.Thread(target=outer.stop,
                                         daemon=True).start()
                        return

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = host, self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    # -- ops ----------------------------------------------------------
    def _get_model(self, path: Optional[str]):
        with self._state_lock:
            if path is not None:
                if path not in self._models:
                    if path.endswith(".zip"):
                        from deeplearning4j_tpu.util.serializer import (
                            ModelSerializer)
                        # container-agnostic: MLN or ComputationGraph
                        self._models[path] = \
                            ModelSerializer.restore_model(path)
                    else:
                        from deeplearning4j_tpu.keras.keras_import import (
                            KerasModelImport)
                        self._models[path] = (KerasModelImport
                                              .import_keras_model_and_weights(path))
                self._last = path
                return self._models[path]
            if not self._models:
                raise ValueError("no model loaded; pass 'model'")
            return self._models[self._last]

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "shutdown":
            return {"ok": True, "shutdown": True}
        if op not in ("fit", "predict", "evaluate"):
            raise ValueError(f"unknown op {op!r}")
        model = self._get_model(req.get("model"))
        if op == "fit":
            it = HDF5MiniBatchDataSetIterator(req["features_dir"],
                                              req["labels_dir"])
            for _ in range(int(req.get("nb_epoch", 1))):
                model.fit(it)
            return {"ok": True, "score": float(model.score())}
        if op == "predict":
            x = _load_array(Path(req["features"])).astype(np.float32)
            return {"ok": True,
                    "predictions": np.asarray(model.output(x)).tolist()}
        if op == "evaluate":
            it = HDF5MiniBatchDataSetIterator(req["features_dir"],
                                              req["labels_dir"])
            ev = model.evaluate(it)
            return {"ok": True, "accuracy": ev.accuracy(), "f1": ev.f1()}
        raise AssertionError("unreachable")  # ops validated above

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class KerasClient:
    """Convenience client for the gateway (what the Python Keras side of
    the reference's py4j bridge would use)."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._file = self._sock.makefile("rwb")

    def request(self, **req) -> dict:
        self._file.write((json.dumps(req) + "\n").encode())
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise ConnectionError("server closed")
        resp = json.loads(line)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    def fit(self, model: str, features_dir: str, labels_dir: str,
            nb_epoch: int = 1) -> dict:
        return self.request(op="fit", model=model, features_dir=features_dir,
                            labels_dir=labels_dir, nb_epoch=nb_epoch)

    def predict(self, features: str, model: Optional[str] = None) -> np.ndarray:
        resp = self.request(op="predict", features=features,
                            **({"model": model} if model else {}))
        return np.asarray(resp["predictions"])

    def close(self) -> None:
        self._sock.close()
