"""Keras-backend gateway server.

Ref: deeplearning4j-keras/.../Server.java:15-22 (py4j GatewayServer
exposing DeepLearning4jEntryPoint to a Python Keras client),
DeepLearning4jEntryPoint.java (fit(model, train dirs, epochs)), and
HDF5MiniBatchDataSetIterator.java (one .h5 file per minibatch in a
directory). The capability bar (SURVEY §2.2): "usable as a Keras-style
backend" — an external process drives training/inference over a socket.

This framework is already Python, so the py4j JVM gateway collapses to a
newline-delimited JSON-over-TCP protocol:

    {"op": "fit", "model": <keras .h5 path>, "features_dir": ...,
     "labels_dir": ..., "nb_epoch": N}
    {"op": "predict", "features": <.npy path>}  -> {"predictions": [...]}
    {"op": "evaluate", "features_dir": ..., "labels_dir": ...}
    {"op": "health"}  -> {"live": true, "ready": ..., "reasons": [...]}
    {"op": "readyz"}  -> structured readiness: guard state, model_loaded
                         / prewarm_done checks, open breakers, inflight/
                         queued depth, TTFT p99 (the fleet router's
                         admission gate and load signal — ISSUE 18)
    {"op": "shutdown"}

A ``generate`` request may add ``"stream": true``: each generated token
is written as its own ``{"partial": true, "t": tok}`` line the moment
the decode loop produces it, before the normal final envelope — the
seam the fleet router uses to resume a generation mid-stream on a
survivor when a replica dies (re-prefill from prompt + tokens-so-far).

Every request may carry ``deadline_ms`` — its deadline budget (the
server default applies otherwise; <= 0 disables). Requests admit
through a ``resilience/service.py`` ServiceGuard: past the bounded
queue they are shed with ``{"error": "SHED", ...}`` instead of queueing
unboundedly, blown budgets return ``{"error": "DEADLINE", ...}``, and a
per-model circuit breaker fails fast with ``{"error": "BREAKER_OPEN",
"retry_after_ms": ...}`` after consecutive failures/timeouts. A
nonfinite prediction is refused (``{"error": "NONFINITE"}``) — the
serving analog of the PR 3 divergence sentinel, applied PER ROW under
batching so one poisoned request never fails its batchmates.

Predicts are served by a continuous-batching scheduler
(``keras/batching.py``): admitted requests for the same model coalesce
into padded power-of-two row buckets, each bucket executes one
AOT-compiled step (compile once per (model, bucket) — no per-request
recompiles), and batch formation is deadline-aware. ``max_batch`` /
``max_wait_ms`` tune it; ``batching=False`` restores the one-request =
one-dispatch path.

Autoregressive decoders are served TOKEN-level (ISSUE 15)::

    {"op": "generate", "model": <gpt .zip>, "tokens": [ids...],
     "max_new_tokens": N, "priority": "interactive"|"bulk",
     "sampling": {"temperature": 0.8, "seed": 7}}   # optional
    -> {"ok": true, "tokens": [...], "ttft_ms": ...}

``keras/generation.py`` schedules these iteration-level: requests join
and leave the running decode batch every step, per-request KV state
lives in a block-paged page pool (ISSUE 20) that rides the compiled
step as donated carry state, prompt prefixes are content-hash deduped
across requests (repeat prompts skip prefill entirely), prefill/decode
compile as separate pow2 AOT buckets, and batched greedy decode is
bitwise identical to singleton decode. ``sampling`` switches greedy
argmax to seeded temperature sampling (bitwise reproducible for a
fixed seed). Every request (predict AND generate)
may carry ``priority`` — ``interactive`` (default) jumps every queued
``bulk`` request in the batch queues.

Batch files: ``.npy`` or ``.h5`` (one array per file, sorted order), the
HDF5MiniBatchDataSetIterator layout.
"""

from __future__ import annotations

import collections
import json
import socket
import socketserver
import threading
import time
from pathlib import Path
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.profiling.flightrec import record as flight_record
from deeplearning4j_tpu.profiling.metrics import get_registry
from deeplearning4j_tpu.profiling.tracer import get_tracer
from deeplearning4j_tpu.profiling.watchdog import beat as watchdog_beat
from deeplearning4j_tpu.resilience import faultinject
from deeplearning4j_tpu.resilience.service import (BreakerOpen, Deadline,
                                                   DeadlineExceeded,
                                                   NonFiniteOutput,
                                                   ServiceError,
                                                   ServiceGuard,
                                                   register_guard,
                                                   unregister_guard)


def _load_array(path: Path) -> np.ndarray:
    if path.suffix == ".npy":
        return np.load(path)
    from deeplearning4j_tpu.keras.hdf5 import Hdf5Archive
    h5 = Hdf5Archive(str(path))
    names = h5.dataset_names()
    if not names:
        raise ValueError(f"{path}: no datasets")
    return np.asarray(h5.read_dataset(names[0]))


class HDF5MiniBatchDataSetIterator(DataSetIterator):
    """One file per minibatch, features/labels in parallel directories,
    loaded lazily per next() — the dataset need not fit in RAM
    (ref: HDF5MiniBatchDataSetIterator.java)."""

    def __init__(self, features_dir: str, labels_dir: str):
        self._f_files = sorted(p for p in Path(features_dir).iterdir()
                               if p.suffix in (".npy", ".h5"))
        self._l_files = sorted(p for p in Path(labels_dir).iterdir()
                               if p.suffix in (".npy", ".h5"))
        if len(self._f_files) != len(self._l_files):
            raise ValueError(f"{len(self._f_files)} feature files vs "
                             f"{len(self._l_files)} label files")
        self._pos = 0

    def reset(self):
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._f_files)

    def next(self) -> DataSet:
        f, l = self._f_files[self._pos], self._l_files[self._pos]
        self._pos += 1
        return DataSet(_load_array(f).astype(np.float32),
                       _load_array(l).astype(np.float32))

    def batch_size(self):
        if not self._f_files:
            return 0
        return int(_load_array(self._f_files[0]).shape[0])


class _DeadlineGatedIterator(DataSetIterator):
    """Wraps a DataSetIterator so a fit/evaluate checks its deadline
    budget before every batch — the "next safe seam": the model's
    parameters are only ever abandoned at a batch boundary, never
    mid-update."""

    def __init__(self, it: DataSetIterator, deadline: Deadline,
                 what: str):
        self._it = it
        self._deadline = deadline
        self._what = what

    def async_supported(self):
        # NEVER let fit() wrap this in AsyncDataSetIterator: the
        # prefetch thread would drain next() (and every deadline
        # check) ahead of training, turning the per-batch seam into a
        # no-op for any dataset smaller than the prefetch queue
        return False

    def reset(self):
        self._it.reset()

    def has_next(self):
        return self._it.has_next()

    def next(self):
        self._deadline.check(self._what)
        return self._it.next()

    def batch_size(self):
        return self._it.batch_size()


class KerasServer:
    """The gateway. A loaded model is cached per model path (bounded
    LRU, ``keep_models``); ``fit`` / ``predict`` / ``evaluate`` operate
    on it under a per-model lock (a concurrent fit and predict on the
    same model must never interleave a half-updated parameter tree).
    Runs in a daemon thread.

    Hardened edge (PR 4): every op admits through a ``ServiceGuard``
    (bounded concurrency + queue, load shedding, per-model circuit
    breaker, deadline budgets, graceful ``drain``); the handler socket
    carries an idle/slow-loris timeout so a dribbling client cannot
    park a thread forever."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_concurrency: int = 4, queue_depth: int = 8,
                 default_deadline_ms: Optional[float] = 300_000.0,
                 max_queue_wait_s: float = 5.0, keep_models: int = 4,
                 breaker_failures: int = 5,
                 breaker_cooldown_base: float = 0.5,
                 breaker_cooldown_max: float = 30.0,
                 breaker_slow_call_s: float = 30.0,
                 io_timeout: float = 60.0, batching: bool = True,
                 max_batch: int = 32, max_wait_ms: float = 5.0,
                 batch_deadline_margin_ms: float = 50.0,
                 kv_cache_budget_bytes: Optional[int] = None,
                 kv_page_len: Optional[int] = None,
                 prewarm: bool = True,
                 tuned=None,
                 preload: Optional[List[str]] = None,
                 replica_rank: Optional[int] = None):
        from deeplearning4j_tpu.keras.batching import BatchScheduler
        from deeplearning4j_tpu.keras.generation import (
            GenerationScheduler)
        # tuned= (a TunedConfig from deeplearning4j_tpu.autotune): the
        # batching scheduler adopts the tuned serving bucket set — its
        # top bucket becomes max_batch, so the gateway's compiled-bucket
        # ladder is exactly the pow2 set the autotuner budgeted for.
        # An explicit non-default max_batch wins.
        if tuned is not None and max_batch == 32:
            max_batch = tuned.serve_max_batch
        self._batcher = (BatchScheduler(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            deadline_margin_ms=batch_deadline_margin_ms)
            if batching and max_batch > 0 else None)
        # token-level generation engine (ISSUE 15/20): decode row
        # buckets cap at the same max_batch; kv_cache_budget_bytes now
        # bounds the block-paged KV POOL (page-granular eviction past
        # it), kv_page_len overrides the per-model page size
        self._gen = GenerationScheduler(
            max_rows=max(1, max_batch),
            cache_budget_bytes=kv_cache_budget_bytes,
            kv_page_len=kv_page_len,
            prewarm_decode_ladder=prewarm)
        self._prewarm = prewarm
        self._models = collections.OrderedDict()  # path -> model (LRU)
        self._model_locks = {}  # path -> per-model op lock
        self._model_pins = {}  # path -> in-flight ops (pinned != evictable)
        self._keep_models = max(1, int(keep_models))
        # handler threads (ThreadingTCPServer) share _models/_last; without
        # the lock a predict that omits 'model' could resolve _last mid-swap
        # from another connection and run against the wrong model
        self._state_lock = threading.Lock()
        # fleet-replica identity (ISSUE 18): when set, admitted requests
        # consult the kill/partition/slow_replica chaos kinds, and
        # hard_kill() becomes reachable. None = standalone server.
        self._replica_rank = (None if replica_rank is None
                              else int(replica_rank))
        #: optional hook invoked FIRST by hard_kill (the FleetReplica
        #: wires its heartbeat stop here so liveness dies with the
        #: listener, exactly as process death would take both)
        self.on_hard_kill = None
        self._kill_lock = threading.Lock()
        self._killed = False
        # established handler sockets — hard_kill() severs them so
        # clients mid-request see a dead connection, not a late answer
        self._conns_lock = threading.Lock()
        self._conns: set = set()
        # in-flight speculative prewarm threads; readiness ("prewarm"
        # check) requires this back at zero, so a fleet router admits a
        # joiner only after its buckets compiled
        self._prewarm_inflight = 0
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            timeout = io_timeout  # reclaims slow-loris/idle threads

            def setup(self):
                super().setup()
                with outer._conns_lock:
                    outer._conns.add(self.connection)

            def finish(self):
                with outer._conns_lock:
                    outer._conns.discard(self.connection)
                super().finish()

            def _stream_writer(self):
                """A per-request partial-line writer for streaming
                generate: each generated token goes on the wire as
                ``{"partial": true, "t": tok}`` the moment the decode
                loop produces it. The lock serializes the decode-loop
                writes against the handler's final response; close()
                fences the stream shut (any later token raises into
                ``push_token``, which just unhooks)."""
                lock = threading.Lock()
                state = {"open": True}

                def on_token(tok):
                    if outer._replica_rank is not None and \
                            faultinject.check_kill_replica_token(
                                outer._replica_rank):
                        outer.hard_kill()  # mid-stream death, by schedule
                    with lock:
                        if not state["open"]:
                            raise RuntimeError("stream closed")
                        self.wfile.write((json.dumps(
                            {"partial": True, "t": int(tok)})
                            + "\n").encode())
                        self.wfile.flush()

                def close():
                    with lock:
                        state["open"] = False

                return on_token, close

            def handle(self):
                try:
                    for line in self.rfile:
                        closer = None
                        try:
                            req = json.loads(line)
                            on_token = None
                            if req.get("op") == "generate" \
                                    and req.get("stream"):
                                on_token, closer = self._stream_writer()
                            resp = outer._dispatch(req, on_token=on_token)
                        except ServiceError as e:  # structured
                            resp = e.to_response()
                        except Exception as e:  # report, keep serving
                            resp = {"error": f"{type(e).__name__}: {e}"}
                        if closer is not None:
                            closer()  # no partial may trail the final line
                        self.wfile.write((json.dumps(resp) + "\n").encode())
                        self.wfile.flush()
                        if isinstance(resp, dict) and resp.get("shutdown"):
                            threading.Thread(target=outer.stop,
                                             daemon=True).start()
                            return
                except TimeoutError:
                    # dribbled (slow-loris) or idle connection timed
                    # out: count it, reclaim the thread cleanly. NOT
                    # serving_deadline_exceeded_total — no admitted
                    # request's budget ran out; a well-behaved client
                    # parking an idle keep-alive must not trip
                    # deadline alerts
                    get_registry().counter(
                        "serving_idle_timeouts_total",
                        help="connections closed after the handler "
                             "socket idle/slow-loris timeout").inc()
                    return
                except OSError:
                    return  # client vanished mid-line

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = host, self._server.server_address[1]
        self._guard = register_guard(ServiceGuard(
            f"keras_server_{self.port}", max_concurrency=max_concurrency,
            queue_depth=queue_depth,
            default_deadline_ms=default_deadline_ms,
            max_queue_wait_s=max_queue_wait_s,
            breaker_failures=breaker_failures,
            breaker_cooldown_base=breaker_cooldown_base,
            breaker_cooldown_max=breaker_cooldown_max,
            breaker_slow_call_s=breaker_slow_call_s))
        self._guard.add_ready_check("model_loaded",
                                    lambda: bool(self._models))
        self._guard.add_ready_check("prewarm",
                                    lambda: self._prewarm_inflight == 0)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        # preload= (fleet joiners): load + pin-warm the named models
        # synchronously, so by the time the constructor returns only the
        # background bucket prewarms separate this server from ready —
        # and the "prewarm" check holds readiness until they land
        for path in (preload or []):
            self._get_model(path)
            self._unpin(path)

    # -- ops ----------------------------------------------------------
    def _resolve_key(self, path: Optional[str]) -> str:
        """The model-cache / breaker key for a request, WITHOUT loading
        anything (the breaker must be consulted before a possibly
        expensive/failing load)."""
        with self._state_lock:
            if path is not None:
                return path
            if not self._models:
                raise ValueError("no model loaded; pass 'model'")
            if self._last not in self._models:  # evicted since last use
                self._last = next(reversed(self._models))
            return self._last

    def _get_model(self, key: str):
        """(model, per-model lock) for ``key``, loading and LRU-caching
        on miss, and PINNING the entry: the LRU never evicts a pinned
        model (an in-flight op keeps its model — and its lock identity —
        resident; checking ``lock.locked()`` instead would race the
        window between returning the lock and acquiring it). Callers
        must ``_unpin(key)`` when the op finishes."""
        with self._state_lock:
            if key not in self._models:
                if key.endswith(".zip"):
                    from deeplearning4j_tpu.util.serializer import (
                        ModelSerializer)
                    # container-agnostic: MLN or ComputationGraph
                    model = ModelSerializer.restore_model(key)
                else:
                    from deeplearning4j_tpu.keras.keras_import import (
                        KerasModelImport)
                    model = (KerasModelImport
                             .import_keras_model_and_weights(key))
                self._models[key] = model
                if self._prewarm and self._batcher is not None:
                    # speculative bucket prewarming: compile the
                    # observed-mix buckets for the fresh model in the
                    # background, so its first wave pays zero compiles
                    # (counted in-flight — readiness waits for it)
                    self._prewarm_inflight += 1
                    threading.Thread(
                        target=self._prewarm_buckets, args=(key, model),
                        daemon=True, name="bucket-prewarm").start()
            self._models.move_to_end(key)
            self._model_pins[key] = self._model_pins.get(key, 0) + 1
            while len(self._models) > self._keep_models:
                victim = next(
                    (p for p in self._models
                     if not self._model_pins.get(p)), None)
                if victim is None:
                    break  # everything older is mid-op; over-stay
                del self._models[victim]
                self._model_locks.pop(victim, None)
                if self._batcher is not None:  # AOT cache dies with LRU
                    self._batcher.evict_model(victim)
                self._gen.evict_model(victim)
                get_registry().counter(
                    "serving_models_evicted_total",
                    help="models evicted from the KerasServer LRU "
                         "cache").inc()
            self._last = key
            lock = self._model_locks.setdefault(key, threading.Lock())
            return self._models[key], lock

    def _prewarm_buckets(self, key: str, model) -> None:
        try:
            self._batcher.prewarm(key, model)
        finally:
            with self._state_lock:
                self._prewarm_inflight -= 1

    def _unpin(self, key: str) -> None:
        with self._state_lock:
            n = self._model_pins.get(key, 0) - 1
            if n <= 0:
                self._model_pins.pop(key, None)
            else:
                self._model_pins[key] = n

    def _dispatch(self, req: dict, on_token=None) -> dict:
        op = req.get("op")
        if op == "health":
            # never admitted/queued: a health probe must answer even
            # (especially) when the server is saturated or draining
            ready, reasons = self._guard.ready()
            return {"ok": True, "live": True, "ready": ready,
                    "reasons": reasons, "draining": self._guard.draining}
        if op == "readyz":
            # the structured readiness surface (ISSUE 18): everything a
            # fleet router needs to gate admission and score dispatch —
            # never admitted, so it answers while saturated or draining
            return self._readyz()
        if op == "debug":
            # the live diagnostic bundle — like health, never admitted:
            # the whole point is answering while the server is wedged
            from deeplearning4j_tpu.profiling.watchdog import \
                assemble_bundle
            return {"ok": True, "bundle": json.loads(json.dumps(
                assemble_bundle(reason="live"), default=repr))}
        if op == "shutdown":
            return {"ok": True, "shutdown": True}
        if op not in ("fit", "predict", "evaluate", "generate"):
            raise ValueError(f"unknown op {op!r}")
        if self._replica_rank is not None:
            # fleet chaos seams: slow_replica stalls this request,
            # partition_replica opens this rank's heartbeat-suppression
            # window, kill_replica hard-kills the whole server (probes
            # above never reach here, so at_call stays predictable
            # under router readyz polling)
            stall, kill = faultinject.on_replica_request(
                self._replica_rank)
            if stall > 0:
                time.sleep(stall)
            if kill:
                self.hard_kill()
                raise OSError("replica hard-killed by fault schedule")
        # resolve the model name ONCE, at admission — a predict without
        # 'model' must not re-read _last after queueing (an LRU swap or
        # eviction mid-queue could silently retarget the request); the
        # resolved key travels with the request from here on
        key = self._resolve_key(req.get("model"))
        deadline = self._guard.deadline(req)
        t_req = time.perf_counter()
        with self._guard.admit(deadline):
            watchdog_beat("keras_server")
            flight_record("keras_server", "dispatch", op=op, model=key)
            with get_tracer().span(f"serve:{op}"):
                resp = self._serve(op, req, deadline, key,
                                   on_token=on_token)
        if op == "predict" and self._batcher is not None:
            # p50/p99 over served predictions (admission queue included
            # — this is the latency a client actually observes)
            self._batcher.latency.observe(time.perf_counter() - t_req)
        return resp

    def _readyz(self) -> dict:
        """Aggregate ServiceGuard + model/prewarm state into one
        machine-readable readiness record — the router's admission gate
        AND its per-replica load signal (inflight/queued/TTFT), which
        matters because in-process replicas share the global metrics
        registry: per-replica numbers must come from HERE, not from
        shared gauges."""
        ready, reasons = self._guard.ready()
        with self._state_lock:
            models = list(self._models)
            prewarm_done = self._prewarm_inflight == 0
        stats = self._gen.stats()
        return {"ok": True, "ready": ready, "reasons": reasons,
                "draining": self._guard.draining,
                "checks": {"model_loaded": bool(models),
                           "prewarm_done": prewarm_done},
                "open_breakers": self._guard.open_breakers(),
                "inflight": self._guard.inflight,
                "queued": self._guard.queued,
                "ttft_p99_ms": stats.get("ttft_p99_ms"),
                "models": models}

    def _serve(self, op: str, req: dict, deadline: Deadline,
               key: str, on_token=None) -> dict:
        # a budget already blown in the admission queue says nothing
        # about the backend — and checking BEFORE _prepare avoids
        # loading the whole input from disk for a doomed request
        deadline.check(f"{op} before dispatch")
        # client-side input validation/loading happens BEFORE the
        # breaker scope: a typo'd features path or mismatched batch
        # dirs is the CLIENT's failure and must not open the circuit
        # for a healthy model
        payload = self._prepare(op, req, deadline)
        breaker = self._guard.breaker(key)
        if not breaker.allow():
            raise BreakerOpen(f"model {key!r}: circuit open",
                              retry_after_ms=breaker.retry_after_ms())
        pinned = False
        t0 = time.monotonic()
        try:
            # model load IS backend scope: an unloadable model path
            # should trip its breaker
            model, lock = self._get_model(key)
            pinned = True
            faultinject.on_backend_dispatch(op)
            priority = str(req.get("priority", "interactive"))
            if op == "generate":
                # token-level continuous batching: this request joins
                # the model's running decode batch and leaves when its
                # generation completes; its verdict is its OWN (a
                # poisoned row fails alone mid-stream)
                out = self._gen.submit(
                    key, model, lock, payload,
                    int(req.get("max_new_tokens", 16)), deadline,
                    priority=priority, on_token=on_token,
                    sampling=req.get("sampling"))
                resp = {"ok": True, **out}
            elif op == "predict" and self._batcher is not None:
                # continuous batching: coalesce with concurrent
                # predicts on this model; the scheduler runs one
                # AOT-compiled step per bucket under the model lock
                # and raises this request's OWN verdict (a batch-level
                # failure is re-tried singleton first)
                y = self._batcher.submit(key, model, lock, payload,
                                         deadline, priority=priority)
                resp = {"ok": True, "predictions": y.tolist()}
            else:
                with lock:
                    resp = self._run_op(op, req, payload, model,
                                        deadline)
            # post-hoc budget check: the op itself cannot be cancelled
            # mid-kernel, so a blown budget is detected at this seam
            # and the (late) result withheld
            deadline.check(f"{op} after dispatch")
        except DeadlineExceeded:
            # a blown CLIENT budget opens the shared breaker only when
            # the backend was genuinely slow (dispatch ran at least the
            # guard's slow-call threshold) — an impatient deadline_ms
            # must not fail-fast everyone else's healthy model
            if (time.monotonic() - t0
                    >= self._guard.breaker_slow_call_s):
                breaker.record_failure()
            raise
        except NonFiniteOutput:
            # a NaN/Inf prediction is a CLIENT-INPUT failure (poisoned
            # features on a healthy model): refuse the row, never open
            # the shared circuit for its batchmates or anyone else
            raise
        except Exception:
            breaker.record_failure()
            raise
        finally:
            if pinned:
                self._unpin(key)
        breaker.record_success()
        return resp

    def _prepare(self, op: str, req: dict, deadline: Deadline):
        """Load/validate the request's inputs (not the model)."""
        if op == "generate":
            # prompt token ids, inline in the request envelope (a
            # prompt is tiny next to a feature batch)
            tokens = req.get("tokens")
            if not tokens or not isinstance(tokens, (list, tuple)):
                raise ValueError("generate needs 'tokens': [ids...]")
            return np.asarray(tokens, np.int32)
        if op == "predict":
            x = _load_array(Path(req["features"])).astype(np.float32)
            # poison_row chaos seam: NaN-poison ONE request's features
            # so the per-row sentinel's batchmate isolation is provable
            return faultinject.poison_predict(x)
        return _DeadlineGatedIterator(
            HDF5MiniBatchDataSetIterator(req["features_dir"],
                                         req["labels_dir"]),
            deadline, f"{op} batch")

    def _run_op(self, op: str, req: dict, payload, model,
                deadline: Deadline) -> dict:
        if op == "fit":
            for _ in range(int(req.get("nb_epoch", 1))):
                deadline.check("fit epoch")
                model.fit(payload)
            return {"ok": True, "score": float(model.score())}
        if op == "predict":
            y = np.asarray(model.output(payload))
            from deeplearning4j_tpu.resilience.sentinel import \
                host_nonfinite
            if host_nonfinite(y):
                get_registry().counter(
                    "serving_nonfinite_outputs_total",
                    help="predictions refused because the model "
                         "output carried NaN/Inf").inc()
                raise NonFiniteOutput("prediction contains NaN/Inf")
            return {"ok": True, "predictions": y.tolist()}
        if op == "evaluate":
            ev = model.evaluate(payload)
            return {"ok": True, "accuracy": ev.accuracy(), "f1": ev.f1()}
        raise AssertionError("unreachable")  # ops validated above

    # -- lifecycle ----------------------------------------------------
    @property
    def draining(self) -> bool:
        return self._guard.draining

    @property
    def killed(self) -> bool:
        """True once ``hard_kill`` ran (chaos drivers poll this to
        respawn a flapping replica's next incarnation)."""
        return self._killed

    def hard_kill(self) -> None:
        """Chaos-only abrupt death (``kill_replica``): the in-process
        analog of SIGKILL. Every established connection is severed
        FIRST (clients mid-request see a dead connection, never a late
        answer), then the listener closes and a reaper thread retires
        the schedulers so the zombie's threads wind down — nothing in
        flight is finished, flushed, or answered. Callable from any
        thread, including a handler or decode loop, and idempotent."""
        with self._kill_lock:
            if self._killed:
                return
            self._killed = True
        flight_record("keras_server", "hard_killed", port=self.port)
        cb = self.on_hard_kill
        if cb is not None:
            try:
                cb()   # liveness (heartbeat) dies with the process
            except Exception:  # noqa: BLE001 — death must not fail
                pass
        self._guard.start_drain()   # nothing new admits into the corpse
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._server.shutdown()
        self._server.server_close()
        # scheduler teardown joins decode loops — a decode loop may be
        # the very thread that called us (mid-stream kill), so the
        # reaping happens on a fresh thread; it is transient and exits
        # as soon as the joins land
        threading.Thread(target=self._reap_after_kill, daemon=True,
                         name="replica-reap").start()

    def _reap_after_kill(self) -> None:
        if self._batcher is not None:
            self._batcher.stop(2.0)
        self._gen.stop(2.0)
        self._thread.join(timeout=5.0)
        unregister_guard(self._guard)

    def drain(self, grace_s: float = 10.0) -> bool:
        """Graceful shutdown: stop admitting (new ops get ``DRAINING``),
        let in-flight ops finish up to ``grace_s``, then close the
        listener. Returns True when the server emptied in time."""
        with self._kill_lock:
            if self._killed:
                # hard-killed already: the reaper owns teardown; a
                # belated drain (test finally blocks) is a no-op
                return True
        self._guard.start_drain()
        drained = self._guard.wait_idle(grace_s)
        if self._batcher is not None:
            # after wait_idle no admitted predict is waiting on a
            # future; fail any stragglers DRAINING and join dispatchers
            self._batcher.stop(grace_s)
        self._gen.stop(grace_s)
        self._server.shutdown()
        self._server.server_close()
        # shutdown() already waited for serve_forever to exit; the join
        # reaps the acceptor thread itself (bounded for safety)
        self._thread.join(timeout=grace_s)
        unregister_guard(self._guard)
        flight_record("keras_server", "drained", emptied=drained)
        return drained

    def stop(self, grace_s: float = 2.0) -> None:
        self.drain(grace_s)


class KerasClient:
    """Convenience client for the gateway (what the Python Keras side of
    the reference's py4j bridge would use)."""

    def __init__(self, host: str, port: int):
        self._sock = socket.create_connection((host, port))
        self._file = self._sock.makefile("rwb")

    def request(self, **req) -> dict:
        self._file.write((json.dumps(req) + "\n").encode())
        self._file.flush()
        while True:
            line = self._file.readline()
            if not line:
                raise ConnectionError("server closed")
            resp = json.loads(line)
            if not (isinstance(resp, dict) and resp.get("partial")):
                break
            # streaming generate interleaves {"partial": true, "t": tok}
            # lines before the final envelope; the blocking client just
            # drains them (the fleet router is the consumer that acts on
            # each one)
        if "error" in resp:
            # structured serving errors carry a machine-readable code in
            # "error" ("SHED", "DEADLINE", "BREAKER_OPEN", ...) plus a
            # human "message"; legacy errors are a single string
            msg = resp["error"]
            if "message" in resp:
                msg = f"{msg}: {resp['message']}"
            raise RuntimeError(msg)
        return resp

    def health(self) -> dict:
        return self.request(op="health")

    def readyz(self) -> dict:
        """The structured readiness record (unadmitted): guard state,
        model_loaded / prewarm_done checks, open breakers, inflight /
        queued depth, TTFT p99 — the fleet router's admission gate and
        load signal."""
        return self.request(op="readyz")

    def debug(self) -> dict:
        """The server's live diagnostic bundle (unadmitted, like
        health — answers even while the server is wedged)."""
        return self.request(op="debug")["bundle"]

    def fit(self, model: str, features_dir: str, labels_dir: str,
            nb_epoch: int = 1) -> dict:
        return self.request(op="fit", model=model, features_dir=features_dir,
                            labels_dir=labels_dir, nb_epoch=nb_epoch)

    def predict(self, features: str, model: Optional[str] = None) -> np.ndarray:
        resp = self.request(op="predict", features=features,
                            **({"model": model} if model else {}))
        return np.asarray(resp["predictions"])

    def generate(self, tokens, max_new_tokens: int = 16,
                 model: Optional[str] = None,
                 priority: str = "interactive", **kw) -> dict:
        """Token-level generation: returns the full response dict
        (``tokens``, ``ttft_ms``, ``reprefills``)."""
        return self.request(op="generate", tokens=list(tokens),
                            max_new_tokens=max_new_tokens,
                            priority=priority,
                            **({"model": model} if model else {}), **kw)

    def close(self) -> None:
        # close the makefile wrapper FIRST: the socket's real fd close
        # is deferred until every makefile ref drops, and a live fd
        # keeps the server's handler thread parked in readline until
        # its idle timeout instead of seeing EOF now
        try:
            self._file.close()
        except OSError:
            pass
        self._sock.close()
