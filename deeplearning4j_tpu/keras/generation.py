"""Token-level continuous batching for autoregressive decoders
(ISSUE 15 — the iteration-level LM serving engine).

PR 6's scheduler batches *whole predicts*: every request occupies its
batch for the full dispatch. For an autoregressive decoder that wastes
the accelerator on every step a short request pads out a long one — the
right scheduling unit is the DECODE STEP. This module serves the GPT
decoder (``models/gpt.py``) iteration-level:

- **Requests join and leave the running batch every decode step.** A
  per-model decode loop owns a pow2-row bucket; an admitted request is
  prefilled (its own pow2 prompt-length bucket), its KV cache row is
  inserted into the bucket, and from then on each loop iteration
  decodes ONE token for every live row. A finished (or failed) row
  leaves immediately; the bucket compacts to the next power of two.
- **Prefill and decode are separate AOT buckets.** Prefill compiles per
  pow2 prompt length (``jit(prefill).lower(...).compile()`` — params
  and states stay arguments, so ``fit`` never invalidates a bucket);
  decode compiles per pow2 row count. Steady state runs with ZERO
  recompiles: a second wave of identical bucket shapes adds no traces.
- **KV state lives in a BLOCK-PAGED pool** (ISSUE 20, vLLM-style):
  one fixed ``[n_pages, H, page_len, D]`` array pair per attention
  node, all nodes sharing ONE physical page-id space (a "page group"
  = the same slot across every node's k and v). Each decode row owns
  a host-side page table mapping logical page slots to physical
  pages; the compiled paged step gathers the row's chain back into
  the exact dense ``[rows, H, max_len, D]`` cache shape, runs the
  UNCHANGED attention math, and scatters the one new K/V token back
  to the row's write page. The pool is donated every iteration
  (shardcheck SC010 statically verifies both the page-table gather
  and that donation survived the indirection; SC009 still covers the
  dense step) — which is what keeps batched greedy decode BITWISE
  equal to singleton decode on CPU: join/leave churn, page eviction,
  and prefix sharing included. Physical page 0 is reserved scratch:
  unmapped table slots alias it so a free or stalled row's scatter
  never lands in a live page.
- **Refcounted prefix sharing.** Prompt prefixes are content-hashed
  at page granularity (key = prefill bucket + exact token prefix):
  a full page whose prefix matches one already resident is MAPPED,
  not rewritten — refcount++ and the pool write is skipped; a page
  frees only at refcount zero. A shared page is read-only by
  construction (decode writes only ever land in a row's EXCLUSIVE
  write page — host validation asserts refcount==1 on it every
  step). On top rides a full-prompt registry (LRU): an identical
  prompt skips prefill entirely — retained pages are mapped, the
  partial tail page restored from host copies, and the first token
  re-selected from the cached prefill probs, so TTFT collapses for
  shared-system-prompt traffic.
- **Page-granular eviction under pool pressure.** When allocation
  fails the allocator walks a pressure ladder: registry LRU entries
  drop their retained refs first, then the oldest-admitted BULK row
  loses its COLDEST entirely-decode-written page — the victim rolls
  its position back to the lost page's first token and REPLAYS its
  own recorded tokens through the normal decode step (emission
  suppressed), re-deriving the lost K/V bitwise; only what was lost
  re-computes, never the whole row. If every live row stalls on
  allocation, the oldest row falls back to whole-ROW eviction (the
  ISSUE 15 requeue + re-prefill path), so progress is guaranteed.
  ``evict_page`` / ``corrupt_page_table`` chaos force these paths.
- **Sampling v0** rides the per-row probs seam: an op-level
  ``{"sampling": {"temperature": t, "seed": s}}`` switches a request
  from greedy argmax to seeded temperature sampling whose draw index
  is the tokens-generated count — the token stream is bitwise
  reproducible for a fixed seed, whatever churn, replay, or
  re-prefill the request lived through. Greedy stays the default.
- **Priority classes**: the admission queue orders ``interactive``
  ahead of ``bulk`` (stable FIFO within a class) — same discipline as
  the predict scheduler's queue; interactive arrivals may still evict
  a whole bulk row (ring order) when row slots run out.

Every PR 6 invariant carries over: admission only through the server's
ServiceGuard, the nonfinite sentinel runs PER ROW per step (a poisoned
request fails alone MID-STREAM — ``poison_decode`` chaos proves it; its
batchmates keep decoding), a batch-level decode failure re-runs each
row as a singleton before anything surfaces, and compiled steps live in
the budgeted cross-model :class:`~.batching.CompileCache`.

Observability: ``serving_generated_tokens_total``,
``serving_decode_steps_total``, ``serving_decode_batch_rows``
histogram, ``serving_ttft_seconds`` + ``serving_ttft_p50/p99_ms``
(time-to-first-token = admission to the prefill's first token),
``serving_kv_cache_bytes`` gauge (now the resident page-pool bytes),
``serving_kv_evictions_total`` / ``serving_reprefills_total``,
``serving_kv_page_evictions_total``, ``serving_prefill_steps_total``,
``serving_prefix_cache_lookups_total`` /
``serving_prefix_cache_hits_total``,
``serving_page_table_corruptions_total``, and ``serve:prefill`` /
``serve:decode`` tracer spans. ``stats()`` surfaces
``prefix_cache_hit_rate`` and the ``kv_pages_*`` pool occupancy the
bench's ``lm_serve`` record carries.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.keras.batching import (CompileCache, _LatencyWindow,
                                               get_compile_cache,
                                               next_cache_owner,
                                               priority_insert,
                                               priority_rank)
from deeplearning4j_tpu.profiling.flightrec import record as flight_record
from deeplearning4j_tpu.profiling.metrics import get_registry
from deeplearning4j_tpu.profiling.tracer import get_tracer
from deeplearning4j_tpu.profiling.watchdog import beat as watchdog_beat
from deeplearning4j_tpu.resilience import faultinject
from deeplearning4j_tpu.resilience.sentinel import host_nonfinite
from deeplearning4j_tpu.resilience.service import (Deadline,
                                                   DeadlineExceeded,
                                                   DrainingError,
                                                   NonFiniteOutput,
                                                   PageTableCorruption)
from deeplearning4j_tpu.util.math_utils import next_pow_of_2

#: row-count edges for the serving_decode_batch_rows histogram
DECODE_ROWS_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


def sample_token(probs, temperature: float = 0.0, seed: int = 0,
                 draw_index: int = 0) -> int:
    """Seeded temperature sampling over one probability row (sampling
    v0). ``temperature <= 0`` degrades to greedy argmax. The draw is
    ``default_rng([seed, draw_index]).random()`` — a COUNTER-KEYED
    stream: the i-th generated token of a request depends only on
    (seed, i), never on batching, page eviction, replay, or re-prefill
    history, so a fixed seed pins a bitwise-reproducible token stream.
    Inverse-CDF over the temperature-rescaled distribution, float64 on
    host: one deterministic code path, no accelerator variance.
    ``models/gpt.py``'s singleton ``sample_generate`` reference uses
    this same function, which is what makes batched sampling == the
    singleton stream provable token-for-token."""
    p = np.asarray(probs, np.float64).ravel()
    if temperature <= 0.0:
        return int(p.argmax())
    z = np.log(np.maximum(p, 1e-38)) / float(temperature)
    z = np.exp(z - z.max())
    z /= z.sum()
    u = np.random.default_rng([int(seed), int(draw_index)]).random()
    return int(min(np.searchsorted(np.cumsum(z), u), p.size - 1))


class _GenRequest:
    """One generation in flight: the prompt (plus any tokens already
    generated before a cache eviction), its budget, and the future the
    submitting handler thread blocks on."""

    __slots__ = ("prompt", "max_new", "priority", "deadline", "event",
                 "tokens", "error", "t0", "ttft_s", "index", "steps",
                 "reprefills", "admit_seq", "model_obj", "on_token",
                 "sampling")

    def __init__(self, prompt: np.ndarray, max_new: int, priority: int,
                 deadline: Deadline, index: int, on_token=None,
                 sampling: Optional[dict] = None):
        self.prompt = prompt
        self.on_token = on_token         # per-token stream hook
        self.sampling = sampling         # None = greedy argmax
        self.max_new = max_new
        self.priority = priority
        self.deadline = deadline
        self.event = threading.Event()
        self.tokens: List[int] = []      # generated so far
        self.error: Optional[BaseException] = None
        self.t0 = time.monotonic()
        self.ttft_s: Optional[float] = None
        self.index = index               # admission order (chaos seam)
        self.steps = 0                   # decode steps taken
        self.reprefills = 0
        self.admit_seq = -1              # ring position (eviction order)
        self.model_obj = None            # the weights my tokens came from

    def push_token(self, tok: int) -> None:
        """Append one generated token and stream it to the submitter's
        ``on_token`` hook (the gateway's partial-line writer). A hook
        failure — the client hung up mid-stream — unhooks streaming but
        never touches the generation itself: tokens keep accumulating
        and the final result (or the handler's own write failure)
        settles the request. Called only on the decode-loop thread, and
        always BEFORE ``finish()`` sets the event, so every partial is
        on the wire before the final response line."""
        self.tokens.append(tok)
        cb = self.on_token
        if cb is not None:
            try:
                cb(tok)
            except Exception:  # noqa: BLE001 — stream loss ≠ decode loss
                self.on_token = None

    def history(self) -> np.ndarray:
        """prompt + generated tokens — what a re-prefill rebuilds from."""
        if not self.tokens:
            return self.prompt
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.event.set()

    def finish(self) -> None:
        self.event.set()


class _Engine:
    """Per-model decode state: the pow2 row bucket, its KV caches, and
    the AOT-compiled prefill/decode executables. All mutation happens on
    the owning scheduler's decode-loop thread; the scheduler lock only
    guards the queue handoff."""

    def __init__(self, scheduler: "GenerationScheduler", key: str,
                 model, lock: threading.Lock):
        prefill, decode = model.decode_fns()   # validates decodability
        self.scheduler = scheduler
        self.key = key
        self.model = model
        self.lock = lock
        self._prefill_fn = prefill
        self._decode_fn = decode               # dense step (SC009 seam)
        self.vocab = model.decode_vocab()
        self.max_len = model.decode_max_len()
        # ---- block-paged KV pool sizing (ISSUE 20). Mirrors
        # analysis.memory.kv_pool_plan exactly so memory_report's
        # number IS this engine's gauge: usable pages = max_rows full
        # rows, capped by the byte budget; +1 physical page 0 reserved
        # as SCRATCH (unmapped table slots alias it).
        self.page_len = model.kv_page_len(scheduler.kv_page_len)
        self.pages_per_row = self.max_len // self.page_len
        self.page_group_bytes = model.kv_page_group_bytes(self.page_len)
        self._paged_decode_fn = model.paged_decode_fn(self.page_len)
        usable = scheduler.max_rows * self.pages_per_row
        budget = scheduler.cache_budget_bytes
        if budget is not None:
            usable = min(usable, budget // self.page_group_bytes)
            if usable < 1:
                raise ValueError(
                    f"cache_budget_bytes={budget} cannot hold even one "
                    f"KV page group ({self.page_group_bytes} "
                    f"bytes/page-group)")
        self.usable_pages = usable
        self.total_pages = usable + 1
        self.pool = model.init_kv_page_pool(self.total_pages,
                                            self.page_len)
        self.pool_bytes = self.total_pages * self.page_group_bytes
        # ---- page allocator state: HOST truth. ``row_pages`` is the
        # authoritative ownership map (slot -> physical page id, one
        # dict per row) mirroring every table write; validation and
        # release go through IT, never through the (derived, possibly
        # corrupted) numpy table.
        self.page_ref = [0] * self.total_pages
        self.page_ref[0] = 1               # scratch: never allocatable
        self.free_pages = list(range(1, self.total_pages))
        self.page_key: Dict[int, tuple] = {}      # pid -> prefix key
        self.prefix_pages: Dict[tuple, int] = {}  # prefix key -> pid
        #: full-prompt LRU registry: (bucket, tokens) -> retained full
        #: pages + host tail copies + prefill probs — a hit skips
        #: prefill entirely
        self.prompt_registry: "collections.OrderedDict[tuple, dict]" = \
            collections.OrderedDict()
        self.rows = 0
        self.table = np.full((0, self.pages_per_row), -1, np.int32)
        self.row_pages: List[Dict[int, int]] = []
        self.slots: List[Optional[_GenRequest]] = []
        self.tokens: List[int] = []      # next token to feed, per slot
        self.positions: List[int] = []   # next decode position, per slot
        self.prefill_lens: List[int] = []  # prefill coverage, per slot
        self.iteration = 0
        self._admit_seq = 0
        self._eye = np.eye(self.vocab, dtype=np.float32)

    # ---------------------------------------------------------- compiled
    def _compiled(self, kind: str, bucket: int):
        """The AOT executable for one (kind, bucket): ``("prefill",
        pow2 prompt len)`` or ``("decode", pow2 rows)`` — cached in the
        budgeted cross-model cache, compiled once. KV state is DONATED
        (argnums 2): the prefill consumes its fresh 1-row cache, and
        the PAGED decode step consumes the page pool — the page table
        rides the compiled step as a plain int32 gather index, so the
        pool shapes (hence the executables) are identical for every
        row bucket and the zero-recompile steady state survives the
        indirection (SC010 proves the donation landed)."""
        sched = self.scheduler
        cache_key = (sched._cache_owner, self.key, kind, bucket)
        runner = sched._compiled.get(cache_key)
        if runner is not None:
            return runner
        import jax
        t0 = time.perf_counter()
        if kind == "prefill":
            fn = self._prefill_fn
            caches = self.model.init_decode_cache(1)
            x = jax.ShapeDtypeStruct((1, bucket, self.vocab), np.float32)
            aux = jax.ShapeDtypeStruct((1,), np.int32)
            compiled = jax.jit(fn, donate_argnums=(2,)).lower(
                self.model.params, self.model.states, caches, x, aux
            ).compile()
        else:
            fn = self._paged_decode_fn
            pool = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self.pool)
            x = jax.ShapeDtypeStruct((bucket, 1, self.vocab), np.float32)
            aux = jax.ShapeDtypeStruct((bucket,), np.int32)
            tbl = jax.ShapeDtypeStruct((bucket, self.pages_per_row),
                                       np.int32)
            compiled = jax.jit(fn, donate_argnums=(2,)).lower(
                self.model.params, self.model.states, pool, x, aux, tbl
            ).compile()
        elapsed = time.perf_counter() - t0
        get_registry().counter(
            "serving_compile_seconds_total",
            help="seconds spent AOT-compiling per-bucket predict "
                 "steps").inc(elapsed)
        with sched._stats_lock:
            sched.compile_s += elapsed
            sched.compiles += 1
            sched._compiles_per_bucket[(self.key, kind, bucket)] += 1

        if kind == "prefill":
            def runner(params, states, c, xv, av, _c=compiled):
                return _c(params, states, c, xv, av)
        else:
            def runner(params, states, c, xv, av, tbl, _c=compiled):
                return _c(params, states, c, xv, av, tbl)

        with sched._cond:
            cur = sched._backends.get(self.key)
            if cur is not None and cur[0] is self.model:
                # cache only while the key still maps to THIS model
                # object — an evict (purge serializes on this cond) or
                # a swap-to-fresh-load while we compiled must not get
                # a stale executable re-landed behind it
                sched._compiled.put(
                    cache_key, runner,
                    CompileCache.compiled_nbytes(compiled))
        return runner

    def prewarm(self, mix, top: int) -> int:
        """Speculatively compile the most-observed prefill/decode
        buckets for this (fresh) engine before traffic needs them."""
        done = 0
        for (kind, bucket), _ in mix:
            if done >= top:
                break
            if self.scheduler._compiled.get(
                    (self.scheduler._cache_owner, self.key, kind,
                     bucket)) is None:
                try:
                    self._compiled(kind, bucket)
                    done += 1
                except Exception:  # noqa: BLE001 — prewarm is speculative
                    continue
        if done:
            get_registry().counter(
                "serving_prewarmed_buckets_total",
                help="AOT buckets compiled speculatively from the "
                     "observed request-size mix").inc(done)
        return done

    # ------------------------------------------------------------ prefill
    def prefill_bucket(self, n_tokens: int) -> int:
        return min(next_pow_of_2(n_tokens), self.max_len)

    def _prefill(self, req: _GenRequest):
        """Run the request's prompt (or re-prefill history) through its
        pow2 length bucket; returns (probs row ``[V]``, 1-row caches).
        Every call counts a prefill STEP — the number a prefix-cache
        hit provably keeps flat."""
        history = req.history()
        L = len(history)
        bucket = self.prefill_bucket(L)
        x = np.zeros((1, bucket, self.vocab), np.float32)
        x[0, :L] = self._eye[history]
        runner = self._compiled("prefill", bucket)
        get_registry().counter(
            "serving_prefill_steps_total",
            help="prefill steps executed (a prefix-cache hit skips "
                 "one)").inc()
        with self.scheduler._stats_lock:   # traffic mix (prewarm signal)
            self.scheduler._mix[("prefill", bucket)] += 1
            self.scheduler.prefill_steps += 1
        flight_record("serving", "prefill_dispatch", model=self.key,
                      bucket=bucket, tokens=L)
        with get_tracer().span("serve:prefill", model=self.key,
                               bucket=bucket, tokens=L):
            with self.lock:
                probs, caches = runner(
                    self.model.params, self.model.states,
                    self.model.init_decode_cache(1), x,
                    np.asarray([L], np.int32))
        return np.asarray(probs)[0], caches

    def _select(self, req: _GenRequest, probs_vec) -> int:
        """Next-token selection for one row: greedy argmax unless the
        request carries a sampling config — then seeded temperature
        sampling whose draw index is the tokens-generated-so-far
        count, so page eviction, replay, and re-prefill never shift
        the stream (the same (seed, index) always yields the same
        draw, and a replayed step consumes NO draw)."""
        s = req.sampling
        if not s:
            return int(np.asarray(probs_vec).argmax())
        return sample_token(probs_vec,
                            temperature=float(s.get("temperature", 0.0)),
                            seed=int(s.get("seed", 0)),
                            draw_index=len(req.tokens))

    # ------------------------------------------------------ page allocator
    def _map_page(self, row: int, slot: int, pid: int) -> None:
        """Map one physical page into a row's chain: host ownership
        map, device-table mirror, and refcount move together — the
        invariant ``_validate_page_table`` re-checks every step."""
        self.row_pages[row][slot] = pid
        self.table[row, slot] = pid
        self.page_ref[pid] += 1

    def _unref_page(self, pid: int) -> None:
        """Drop one reference; at zero the page returns to the free
        list and leaves the prefix index (a later identical prefix
        re-prefills — never maps a freed page)."""
        self.page_ref[pid] -= 1
        if self.page_ref[pid] == 0:
            self.free_pages.append(pid)
            key = self.page_key.pop(pid, None)
            if key is not None:
                self.prefix_pages.pop(key, None)

    def _registry_evict_one(self) -> None:
        """Drop the LRU full-prompt registry entry: its retained refs
        release (pages still mapped by live rows survive — only the
        registry's own holds go)."""
        _, entry = self.prompt_registry.popitem(last=False)
        for pid in entry["pages"]:
            self._unref_page(pid)

    def _alloc_page(self, exclude_row: Optional[int] = None
                    ) -> Optional[int]:
        """One physical page, walking the pressure ladder: free list ->
        drop LRU prefix-registry retentions -> steal the COLDEST
        droppable page from the oldest-admitted BULK row (never from
        ``exclude_row`` — stealing from the requester frees nothing
        net). ``None`` = genuinely out of pages; the caller stalls or
        falls back to whole-row eviction."""
        if self.free_pages:
            return self.free_pages.pop()
        while self.prompt_registry:
            self._registry_evict_one()
            if self.free_pages:
                return self.free_pages.pop()
        victims = sorted((s.admit_seq, i)
                         for i, s in enumerate(self.slots)
                         if s is not None and s.priority > 0
                         and i != exclude_row)
        for _, i in victims:
            j = self._coldest_droppable(i)
            if j is None:
                continue
            self._drop_page(i, j, reason="pressure")
            if self.free_pages:
                return self.free_pages.pop()
        return None

    def _coldest_droppable(self, row: int) -> Optional[int]:
        """Lowest page slot of ``row`` that is ENTIRELY decode-written
        (``slot*page_len >= prefill_len`` — replay can only re-derive
        decode content; prefill content needs the whole-row path) and
        fully behind the write position (never the page being
        written). Such pages are exclusive by construction."""
        pf, pos, pl = (self.prefill_lens[row], self.positions[row],
                       self.page_len)
        for j in sorted(self.row_pages[row]):
            if j * pl >= pf and (j + 1) * pl <= pos:
                return j
        return None

    def _drop_page(self, row: int, slot: int, reason: str) -> None:
        """Page-granular eviction: unmap + unref ONE page and roll the
        victim's position back to that page's first token. Subsequent
        normal decode steps REPLAY its recorded tokens from there —
        the identical computation re-derives the lost K/V bitwise,
        with emission suppressed until the row catches back up, so
        only what was lost re-computes."""
        req = self.slots[row]
        pid = self.row_pages[row].pop(slot)
        self.table[row, slot] = -1
        self._unref_page(pid)
        self.positions[row] = slot * self.page_len
        hist = req.history()
        self.tokens[row] = int(hist[self.positions[row]])
        get_registry().counter(
            "serving_kv_page_evictions_total",
            help="KV pages dropped under pool pressure or chaos (the "
                 "victim replays only the lost page)").inc()
        get_tracer().instant("kv_page_evicted", model=self.key, row=row,
                             slot=slot, reason=reason)
        flight_record("serving", "kv_page_evicted", model=self.key,
                      row=row, slot=slot, page=pid, reason=reason)

    def _release_row(self, row: int) -> None:
        """Free a row's slot and every page reference it holds — via
        the authoritative host ownership map, NEVER via the device
        table (a corrupted table must not steer releases)."""
        self.slots[row] = None
        for pid in self.row_pages[row].values():
            self._unref_page(pid)
        self.row_pages[row] = {}
        if self.rows:
            self.table[row, :] = -1
        self.tokens[row] = 0
        self.positions[row] = 0
        self.prefill_lens[row] = 0

    def _write_page(self, pid: int, cache1, start: int,
                    count: int) -> None:
        """Copy prefill K/V positions ``[start, start+count)`` into
        pool page ``pid`` across every attention node (one page
        group). Stale content past ``count`` is harmless: attention
        masks it to an EXACT-zero softmax contribution, and the write
        position's slot is rewritten in-step before being read."""
        for n, kv in cache1.items():
            for k, v in kv.items():
                self.pool[n][k] = self.pool[n][k].at[
                    pid, :, :count, :].set(
                        v[0, :, start:start + count, :])

    # ----------------------------------------------------- slot lifecycle
    def active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def _publish_cache_gauge(self) -> None:
        with self.scheduler._cond:   # _engines mutates under the cond
            self.scheduler._publish_kv_gauge_locked()

    def _grow_allowed(self, new_rows: int) -> bool:
        # row slots are free under paging — MEMORY admission control
        # moved to the page allocator (a request that cannot get pages
        # re-queues; the pool bytes are fixed at engine build)
        return new_rows <= self.scheduler.max_rows

    def _resize(self, new_rows: int) -> None:
        """Re-bucket the decode batch. Under paging this is PURE HOST
        bookkeeping: the pool never moves, rows keep their page
        mappings, and only the per-row table/slot arrays re-index — no
        device gather, no cache copy, so parity is trivially
        unaffected and resize costs nothing on the accelerator."""
        live = [i for i, s in enumerate(self.slots) if s is not None]
        assert len(live) <= new_rows
        new_table = np.full((new_rows, self.pages_per_row), -1, np.int32)
        new_row_pages: List[Dict[int, int]] = [
            {} for _ in range(new_rows)]
        new_slots: List[Optional[_GenRequest]] = [None] * new_rows
        new_tokens, new_positions = [0] * new_rows, [0] * new_rows
        new_prefill = [0] * new_rows
        for j, i in enumerate(live):
            new_slots[j] = self.slots[i]
            new_tokens[j] = self.tokens[i]
            new_positions[j] = self.positions[i]
            new_prefill[j] = self.prefill_lens[i]
            new_table[j] = self.table[i]
            new_row_pages[j] = self.row_pages[i]
        self.slots, self.tokens, self.positions = (new_slots, new_tokens,
                                                   new_positions)
        self.prefill_lens = new_prefill
        self.table, self.row_pages = new_table, new_row_pages
        self.rows = new_rows
        self._publish_cache_gauge()

    def try_admit(self, req: _GenRequest) -> bool:
        """JOIN: admit one request — a full-prompt prefix-registry hit
        maps the retained pages and skips prefill ENTIRELY (TTFT
        collapses to page-mapping cost); the cold path prefills, then
        maps the prompt's pages with content-addressed FULL-page dedup
        against the pool. Returns False when no row slot or no pages
        are available (caller re-queues)."""
        row = next((i for i, s in enumerate(self.slots) if s is None),
                   None)
        if row is None:
            new_rows = next_pow_of_2(self.active() + 1)
            if not self._grow_allowed(new_rows):
                if not self._preempt_for(req):
                    return False
                row = next(i for i, s in enumerate(self.slots)
                           if s is None)
            else:
                self._resize(new_rows)
                row = next(i for i, s in enumerate(self.slots)
                           if s is None)
        if req.tokens and req.model_obj is not self.model:
            # an evicted victim re-admitted after the model was
            # reloaded as a NEW object: re-prefilling its old-model
            # tokens under the new weights would blend two models in
            # one response — fail it honestly instead
            req.fail(RuntimeError(
                "model reloaded while this generation awaited "
                "re-prefill after a cache eviction; retry"))
            return True
        req.model_obj = self.model
        history = req.history()
        L = len(history)
        pl = self.page_len
        # feasibility: the request's WORST-CASE page chain must fit the
        # pool outright, else it could never finish however long it
        # waits — fail loudly now instead of queueing forever
        remaining = max(req.max_new - len(req.tokens), 0)
        highest = (L - 1 if remaining <= 1
                   else min(L + remaining - 2, self.max_len - 1))
        need = highest // pl + 1
        if need > self.usable_pages:
            req.fail(ValueError(
                f"generation needs {need} KV pages ({L} prompt tokens "
                f"+ {remaining} new at page_len {pl}) but the pool "
                f"budget cannot hold more than {self.usable_pages}"))
            return True
        bucket = self.prefill_bucket(L)
        hist_t = tuple(int(t) for t in history)
        reg_key = (bucket, hist_t)
        with self.scheduler._stats_lock:
            self.scheduler.prefix_lookups += 1
        reg = get_registry()
        reg.counter("serving_prefix_cache_lookups_total",
                    help="full-prompt prefix-registry lookups at "
                         "admission").inc()
        entry = self.prompt_registry.get(reg_key)
        n_full, tail_len = L // pl, L % pl
        if entry is not None:
            # FULL-PROMPT HIT: an identical prompt prefilled earlier —
            # map its retained pages (refcount++, read-only by
            # construction), restore the partial tail page from host
            # copies into a fresh EXCLUSIVE write page, and re-select
            # the first token from the cached prefill probs per THIS
            # request's sampling config. No prefill step runs.
            self.prompt_registry.move_to_end(reg_key)
            wp = None
            if tail_len:
                wp = self._alloc_page(exclude_row=row)
                if wp is None:
                    return False
            for j, pid in enumerate(entry["pages"]):
                self._map_page(row, j, pid)
            if wp is not None:
                for n, kv in entry["tail"].items():
                    for k, v in kv.items():
                        self.pool[n][k] = self.pool[n][k].at[
                            wp, :, :tail_len, :].set(v)
                self._map_page(row, n_full, wp)
            first = self._select(req, entry["probs"])
            with self.scheduler._stats_lock:
                self.scheduler.prefix_hits += 1
            reg.counter("serving_prefix_cache_hits_total",
                        help="admissions that skipped prefill via the "
                             "full-prompt prefix registry").inc()
            get_tracer().instant("prefix_cache_hit", model=self.key,
                                 tokens=L)
            flight_record("serving", "prefix_cache_hit", model=self.key,
                          tokens=L, row=row)
        else:
            try:
                probs_vec, cache1 = self._prefill(req)
            except Exception as e:  # noqa: BLE001 — fail THIS alone
                req.fail(e)
                return True
            # map + fill the prompt's page chain, deduping FULL pages
            # content-addressed: same prefill bucket + same exact token
            # prefix => bitwise-identical K/V (row-independent matmuls;
            # suffix tokens contribute EXACTLY zero through the causal
            # mask), so the page is shared and the pool write skipped
            new_refs = []
            ok = True
            for j in range(n_full):
                pkey = (bucket, hist_t[:(j + 1) * pl])
                pid = self.prefix_pages.get(pkey)
                if pid is not None:
                    self._map_page(row, j, pid)     # dedup: no write
                    new_refs.append((j, pid))
                    continue
                pid = self._alloc_page(exclude_row=row)
                if pid is None:
                    ok = False
                    break
                self._write_page(pid, cache1, j * pl, pl)
                self._map_page(row, j, pid)
                self.prefix_pages[pkey] = pid
                self.page_key[pid] = pkey
                new_refs.append((j, pid))
            if ok and tail_len:
                wp = self._alloc_page(exclude_row=row)
                if wp is None:
                    ok = False
                else:
                    self._write_page(wp, cache1, n_full * pl, tail_len)
                    self._map_page(row, n_full, wp)
                    new_refs.append((n_full, wp))
            if not ok:
                # pages ran out mid-mapping: undo the refs taken and
                # re-queue (the wasted prefill is the price of not
                # holding pages hostage across the queue)
                for j, pid in new_refs:
                    del self.row_pages[row][j]
                    self.table[row, j] = -1
                    self._unref_page(pid)
                return False
            first = self._select(req, probs_vec)
            self._registry_insert(reg_key, row, n_full, cache1, L,
                                  tail_len, probs_vec)
        if req.ttft_s is None:  # a re-prefilled victim keeps its first
            req.ttft_s = time.monotonic() - req.t0
            self.scheduler.ttft.observe(req.ttft_s)
        req.push_token(first)
        req.admit_seq = self._admit_seq
        self._admit_seq += 1
        self.slots[row] = req
        self.tokens[row] = first
        # next decode writes `first`'s K/V at position = history length
        self.positions[row] = L
        self.prefill_lens[row] = L
        if len(req.tokens) >= req.max_new \
                or self.positions[row] >= self.max_len:
            self._complete(row)      # prompt-only TTFT request
        return True

    def _registry_insert(self, reg_key, row: int, n_full: int, cache1,
                         L: int, tail_len: int, probs_vec) -> None:
        """Retain this prompt's prefill for later identical prompts:
        refcount++ on its FULL pages (they outlive the row), host
        copies of the partial tail page (a hit restores them into a
        fresh exclusive write page — shared pages stay read-only), and
        the prefill probs row (a hit re-selects its first token per
        request). LRU-capped; eviction only drops the registry's own
        refs, so pages still mapped by live rows survive it."""
        if reg_key in self.prompt_registry:
            self.prompt_registry.move_to_end(reg_key)
            return
        pages = [self.row_pages[row][j] for j in range(n_full)]
        for pid in pages:
            self.page_ref[pid] += 1
        pl = self.page_len
        tail = {}
        if tail_len:
            tail = {n: {k: np.asarray(v[0, :, n_full * pl:L, :])
                        for k, v in kv.items()}
                    for n, kv in cache1.items()}
        self.prompt_registry[reg_key] = {
            "pages": pages, "tail": tail, "tail_len": tail_len,
            "probs": np.asarray(probs_vec, np.float32).copy(),
            "prefill_len": L}
        while len(self.prompt_registry) > \
                self.scheduler.prefix_registry_cap:
            self._registry_evict_one()

    def _preempt_for(self, req: _GenRequest) -> bool:
        """Ring-buffer eviction under pressure: an INTERACTIVE arrival
        evicts the oldest-admitted BULK row rather than waiting behind
        it. Bulk arrivals never preempt."""
        if req.priority != 0:
            return False
        victims = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                   if s is not None and s.priority > 0]
        if not victims:
            return False
        self.evict_row(min(victims)[1], reason="preempt")
        return True

    def evict_row(self, row: int, reason: str = "pressure") -> None:
        """LEAVE (involuntary): push the victim back onto the queue;
        its history re-prefills when capacity returns — its pages free
        immediately through the host ownership map, never salvaged."""
        victim = self.slots[row]
        if victim is None:
            return
        victim.reprefills += 1
        self._release_row(row)
        reg = get_registry()
        reg.counter("serving_kv_evictions_total",
                    help="KV-cache rows evicted (ring-buffer pressure "
                         "or chaos)").inc()
        reg.counter("serving_reprefills_total",
                    help="evicted generations re-queued for "
                         "re-prefill").inc()
        get_tracer().instant("kv_evicted", model=self.key, row=row,
                             reason=reason)
        flight_record("serving", "kv_evicted", model=self.key, row=row,
                      reason=reason)
        self.scheduler._requeue(self.key, victim)

    def ring_victim(self) -> Optional[int]:
        """Oldest-admitted live row — the ring-buffer eviction order."""
        live = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                if s is not None]
        return min(live)[1] if live else None

    def _complete(self, row: int) -> None:
        req = self.slots[row]
        self._release_row(row)
        get_registry().counter(
            "serving_generated_tokens_total",
            help="tokens generated by the decode engine").inc(
                len(req.tokens))
        with self.scheduler._stats_lock:
            self.scheduler.tokens_out += len(req.tokens)
        req.finish()

    # ------------------------------------------------------------- decode
    def _nth_oldest(self, live, rank: int) -> Optional[int]:
        """The ``rank``-th oldest-admitted live row (chaos targeting);
        clamps to the oldest available."""
        if not live:
            return None
        ordered = sorted((self.slots[i].admit_seq, i) for i in live)
        return ordered[min(max(rank, 0), len(ordered) - 1)][1]

    def _validate_page_table(self, live):
        """Host-side page-table validation, every iteration BEFORE the
        table reaches a compiled step: each live row's device table
        must mirror the authoritative ``row_pages`` ownership map
        (in-pool, un-freed pages only), and the row's WRITE page must
        be exclusive (refcount 1) — the 'shared prefix pages are
        read-only by construction' assert. A corrupt row fails ALONE
        with a structured PAGE_TABLE error; its pages release via the
        ownership map, never via the corrupted table — so cross-row
        cache garbage is structurally impossible."""
        ok = []
        for i in live:
            req = self.slots[i]
            mapped = self.row_pages[i]
            bad = None
            for j in range(self.pages_per_row):
                want = mapped.get(j, -1)
                got = int(self.table[i, j])
                if got != want:
                    bad = (f"slot {j} maps page {got}, host ownership "
                           f"says {want}")
                    break
                if want >= 0 and not 0 < want < self.total_pages:
                    bad = f"slot {j} maps out-of-pool page {want}"
                    break
                if want >= 0 and self.page_ref[want] < 1:
                    bad = f"slot {j} maps freed page {want}"
                    break
            if bad is None:
                wslot = self.positions[i] // self.page_len
                wpid = mapped.get(wslot)
                if wpid is not None and self.page_ref[wpid] != 1:
                    bad = (f"write page {wpid} (slot {wslot}) is "
                           f"SHARED (refcount {self.page_ref[wpid]}) — "
                           f"shared prefix pages are read-only by "
                           f"construction")
            if bad is None:
                ok.append(i)
                continue
            get_registry().counter(
                "serving_page_table_corruptions_total",
                help="decode rows failed by host-side page-table "
                     "validation before any compiled step ran").inc()
            get_tracer().instant("page_table_corrupt", model=self.key,
                                 row=i)
            flight_record("serving", "page_table_corrupt",
                          model=self.key, row=i, detail=bad)
            req.fail(PageTableCorruption(
                f"decode row {i}: {bad}; failing this row alone (its "
                f"pages release via the host ownership map — the "
                f"corrupt table never reached a compiled step)"))
            self._release_row(i)
        return ok

    def decode_iteration(self) -> None:
        """One engine step: decode ONE token for every live row."""
        self.iteration += 1
        live = [i for i, s in enumerate(self.slots) if s is not None]
        if not live:
            return
        # deadline-blown rows leave before paying for the step
        for i in list(live):
            req = self.slots[i]
            if req.deadline.expired():
                req.fail(DeadlineExceeded(
                    "generate: budget exhausted mid-stream at "
                    f"token {len(req.tokens)}"))
                self._release_row(i)
                live.remove(i)
        if not live:
            return
        # corrupt_page_table chaos scribbles BEFORE validation — the
        # validator must provably catch it
        rank = faultinject.check_corrupt_page_table()
        if rank is not None:
            t = self._nth_oldest(live, rank)
            if t is not None:
                self.table[t, self.positions[t] // self.page_len] = \
                    self.total_pages + 7
        live = self._validate_page_table(live)
        if not live:
            return
        # evict_page chaos: drop the target's coldest droppable page —
        # the exact path pool pressure takes (no droppable page =>
        # whole-row fallback, same as the real pressure ladder)
        rank = faultinject.check_evict_page()
        if rank is not None:
            t = self._nth_oldest(live, rank)
            if t is not None:
                j = self._coldest_droppable(t)
                if j is not None:
                    self._drop_page(t, j, reason="chaos")
                else:
                    self.evict_row(t, reason="chaos")
                    live.remove(t)
        if not live:
            return
        # every live row needs its WRITE page mapped before dispatch;
        # a row that cannot get one STALLS this step (its scatter
        # would otherwise land on scratch and lose the token)
        stalled = []
        for i in list(live):
            wslot = self.positions[i] // self.page_len
            if wslot not in self.row_pages[i]:
                pid = self._alloc_page(exclude_row=i)
                if pid is None:
                    stalled.append(i)
                    live.remove(i)
                else:
                    self._map_page(i, wslot, pid)
        if not live:
            if stalled:
                # EVERY live row is stalled on allocation: page-level
                # pressure has nothing left to give, so fall back to
                # whole-ROW eviction of the oldest — the pool drains
                # and the rest make progress (eventual serialization,
                # never deadlock)
                victim = self.ring_victim()
                if victim is not None:
                    self.evict_row(victim, reason="page-pressure")
            return
        x = np.zeros((self.rows, 1, self.vocab), np.float32)
        for i in live:
            x[i, 0] = self._eye[self.tokens[i]]
        positions = np.asarray(self.positions, np.int32)
        # derived DEVICE table: unmapped slots alias scratch page 0,
        # so free/stalled rows' scatters never touch a live page
        table = np.where(self.table < 0, 0, self.table).astype(np.int32)
        runner = self._compiled("decode", self.rows)
        tracer = get_tracer()
        watchdog_beat("serving_decode")
        flight_record("serving", "decode_dispatch", model=self.key,
                      rows=self.rows, live=len(live),
                      iteration=self.iteration)
        with tracer.span("serve:decode", model=self.key, rows=self.rows,
                         live=len(live), iteration=self.iteration):
            try:
                with self.lock:
                    probs, self.pool = runner(
                        self.model.params, self.model.states,
                        self.pool, x, positions, table)
                probs = np.asarray(probs)
            except Exception:  # noqa: BLE001 — isolate batchmates
                # batch-level decode failure: re-run each live row ALONE
                # before surfacing anything (PR 6 singleton-fallback
                # discipline, per decode step)
                get_registry().counter(
                    "serving_decode_fallbacks_total",
                    help="decode steps re-run as singletons after a "
                         "batch-level failure").inc()
                if self._caches_deleted():
                    # the failed call had already CONSUMED the donated
                    # page pool (a runtime fault after dispatch): the
                    # singleton fallback has nothing to read — and the
                    # shared prefix pages died with the pool. Rebuild
                    # the allocator from zero instead of failing
                    # everyone: every live row re-queues for RE-PREFILL
                    # from its tokens, the same never-garbage path
                    # eviction uses.
                    for i in list(live):
                        self.evict_row(i, reason="donated-cache-lost")
                    self._rebuild_pool()
                    return
                probs = self._singleton_fallback(live, x, positions,
                                                 table)
        reg = get_registry()
        reg.counter("serving_decode_steps_total",
                    help="batched decode steps executed").inc()
        reg.histogram("serving_decode_batch_rows",
                      help="live generations per decode step",
                      buckets=DECODE_ROWS_BUCKETS).observe(len(live))
        with self.scheduler._stats_lock:   # traffic mix (prewarm signal)
            self.scheduler._mix[("decode", self.rows)] += 1
        for i in live:
            req = self.slots[i]
            if req is None:
                continue
            if probs is None:
                continue  # fallback rebuilt the pool; rows re-queued
            row_probs = probs[i]
            # a row is REPLAYING (rebuilding a dropped page) while its
            # position has not caught back up to its recorded history:
            # the step's K/V write is the point, the probs re-derive
            # tokens the request already holds
            hist_len = len(req.prompt) + len(req.tokens)
            replaying = self.positions[i] + 1 < hist_len
            if not replaying:
                if faultinject.poison_decode_row(req.index,
                                                 req.steps + 1):
                    row_probs = np.full_like(row_probs, np.nan)
                if host_nonfinite(row_probs):
                    reg.counter(
                        "serving_nonfinite_outputs_total",
                        help="predictions refused because the model "
                             "output carried NaN/Inf").inc()
                    req.fail(NonFiniteOutput(
                        f"generation row turned NaN/Inf at token "
                        f"{len(req.tokens) + 1}"))
                    self._release_row(i)  # fails ALONE, mid-stream
                    continue
            req.steps += 1
            self.positions[i] += 1
            if replaying:
                # emission suppressed: feed the NEXT recorded token —
                # identical computation re-derives the lost K/V bitwise
                hist = req.history()
                self.tokens[i] = int(hist[self.positions[i]])
                continue
            tok = self._select(req, row_probs)
            req.push_token(tok)
            self.tokens[i] = tok
            if len(req.tokens) >= req.max_new \
                    or self.positions[i] >= self.max_len:
                self._complete(i)
        # evict_cache chaos: force one ring eviction, exactly what HBM
        # pressure would do — the victim must re-prefill, never garbage
        if faultinject.check_evict_cache():
            victim = self.ring_victim()
            if victim is not None:
                self.evict_row(victim, reason="chaos")
        # compact: a half-empty bucket shrinks to its pow2
        target = max(1, next_pow_of_2(max(1, self.active())))
        if target < self.rows:
            self._resize(target)

    def _caches_deleted(self) -> bool:
        """True when the page pool's buffers were invalidated by a
        donation that dispatched before the step failed."""
        for kv in self.pool.values():
            for v in kv.values():
                deleted = getattr(v, "is_deleted", None)
                if deleted is not None and deleted():
                    return True
        return False

    def _rebuild_pool(self) -> None:
        """The donated pool was consumed by a step that then died:
        every device page is gone — including shared prefix pages and
        registry-retained ones, so the whole allocator resets with it
        (host metadata pointing at dead device pages would serve
        garbage on the next prefix hit). Callers evict live rows to
        the re-prefill path FIRST."""
        self.pool = self.model.init_kv_page_pool(  # lockcheck: disable=LC004 -- the pool is only touched from the engine's single scheduler thread; decode_iteration's lock guards the model op during dispatch, not this field
            self.total_pages, self.page_len)
        self.page_ref = [0] * self.total_pages
        self.page_ref[0] = 1
        self.free_pages = list(range(1, self.total_pages))
        self.prefix_pages.clear()
        self.page_key.clear()
        self.prompt_registry.clear()
        self.table = np.full((self.rows, self.pages_per_row), -1,
                             np.int32)
        self.row_pages = [{} for _ in range(self.rows)]

    def _singleton_fallback(self, live, x, positions, table):
        """Re-run each live row in the 1-row decode bucket; rows that
        fail alone surface their own error (and only those may charge
        the caller's breaker). The POOL threads through every 1-row
        call (donated each time), so successful rows' page writes land
        exactly where the batched step would have put them — no
        write-back pass. Returns None when a singleton call consumed
        the pool and then died (callers see rows already re-queued)."""
        probs = np.zeros((self.rows, self.vocab), np.float32)
        for i in list(live):
            req = self.slots[i]
            try:
                runner = self._compiled("decode", 1)
                with self.lock:
                    p1, self.pool = runner(
                        self.model.params, self.model.states,
                        self.pool, x[i:i + 1], positions[i:i + 1],
                        table[i:i + 1])
                probs[i] = np.asarray(p1)[0]
            except Exception as e:  # noqa: BLE001 — per-row verdict
                if self._caches_deleted():
                    # the 1-row step consumed the pool then died:
                    # nothing left for the remaining rows either —
                    # evict them all to the re-prefill path and rebuild
                    for j in list(live):
                        if self.slots[j] is not None:
                            self.evict_row(j, reason="donated-cache-"
                                                     "lost")
                    self._rebuild_pool()
                    return None
                req.fail(e)
                self._release_row(i)
        return probs

    def fail_all(self, error: BaseException) -> None:
        for i, req in enumerate(self.slots):
            if req is not None:
                req.fail(error)
                self._release_row(i)


class GenerationScheduler:
    """Per-server token-level scheduler. ``submit()`` is called by an
    admitted handler thread (holding its ServiceGuard slot) and blocks
    until the generation completes; a per-model decode-loop thread owns
    the engine. The caller resolves the model key ONCE at admission —
    eviction or an LRU swap can never retarget a queued request."""

    def __init__(self, max_rows: int = 8, max_wait_ms: float = 0.0,
                 cache_budget_bytes: Optional[int] = None,
                 idle_thread_s: float = 30.0,
                 compile_cache: Optional[CompileCache] = None,
                 prewarm_top: int = 3,
                 prewarm_decode_ladder: bool = False,
                 kv_page_len: Optional[int] = None,
                 prefix_registry_cap: int = 32):
        if max_rows < 1:
            raise ValueError("max_rows must be >= 1")
        self.max_rows = next_pow_of_2(int(max_rows))
        if self.max_rows > max_rows:
            self.max_rows >>= 1
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.cache_budget_bytes = cache_budget_bytes
        # None = per-model default (analysis.memory.default_kv_page_len)
        self.kv_page_len = kv_page_len
        self.prefix_registry_cap = max(0, int(prefix_registry_cap))
        self.idle_thread_s = idle_thread_s
        self.prewarm_top = prewarm_top
        # compile the whole pow2 decode-rows ladder at engine build:
        # log2(max_rows)+1 small programs buy DETERMINISTIC zero-
        # recompile steady state whatever row counts churn produces
        self.prewarm_decode_ladder = prewarm_decode_ladder
        self._cond = threading.Condition()
        self._queues: Dict[str, collections.deque] = {}
        self._backends: Dict[str, tuple] = {}
        self._engines: Dict[str, _Engine] = {}
        self._loops: Dict[str, threading.Thread] = {}
        self._compiled = (compile_cache if compile_cache is not None
                          else get_compile_cache())
        self._cache_owner = next_cache_owner()
        self._stopping = False
        self._stats_lock = threading.Lock()
        self.compile_s = 0.0
        self.compiles = 0
        self.tokens_out = 0
        self.prefill_steps = 0
        self.prefix_lookups = 0
        self.prefix_hits = 0
        # _mix = OBSERVED traffic per (kind, bucket) — the speculative-
        # prewarm ranking signal; _compiles_per_bucket = compiles per
        # bucket — the zero-recompile gate surface (a value > 1 means a
        # shape was re-traced, whatever the traffic was)
        self._mix: collections.Counter = collections.Counter()
        self._compiles_per_bucket: collections.Counter = \
            collections.Counter()
        self._submits = 0
        self.ttft = _LatencyWindow(
            hist_name="serving_ttft_seconds",
            hist_help="time to first token (admission to the "
                      "prefill's first greedy token)",
            gauge_prefix="serving_ttft", gauge_what="time to first "
                                                    "token")

    # -------------------------------------------------------------- submit
    def submit(self, key: str, model, lock: threading.Lock,
               prompt, max_new_tokens: int, deadline: Deadline,
               priority: str = "interactive", on_token=None,
               sampling: Optional[dict] = None) -> dict:
        """Queue one generation and block until it completes. Returns
        ``{"tokens": [...], "ttft_ms": ..., "reprefills": n}``; raises
        the request's own structured error. ``on_token`` (optional) is
        invoked on the decode-loop thread with each token the moment it
        is generated — the streaming-gateway seam; exceptions it raises
        only stop the streaming, never the generation. ``sampling``
        (optional) is ``{"temperature": t, "seed": s}`` — seeded
        temperature sampling instead of the default greedy argmax;
        ``temperature`` 0 stays greedy, and a fixed seed pins a
        bitwise-reproducible token stream."""
        prompt = np.asarray(prompt, np.int32).ravel()
        vocab = model.decode_vocab()
        max_len = model.decode_max_len()
        if prompt.size < 1:
            raise ValueError("generate needs a non-empty prompt")
        if prompt.min() < 0 or prompt.max() >= vocab:
            raise ValueError(f"prompt token out of range [0, {vocab})")
        if prompt.size >= max_len:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate (max sequence length {max_len})")
        max_new = min(int(max_new_tokens), max_len - prompt.size)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if sampling is not None:
            if not isinstance(sampling, dict):
                raise ValueError(
                    'sampling must be an object like '
                    '{"temperature": t, "seed": s}')
            try:
                t = float(sampling.get("temperature", 0.0))
                s = int(sampling.get("seed", 0))
            except (TypeError, ValueError):
                raise ValueError(
                    "sampling.temperature must be a number and "
                    "sampling.seed an integer") from None
            if t < 0:
                raise ValueError("sampling.temperature must be >= 0")
            sampling = {"temperature": t, "seed": s}
        deadline.check("generate enqueue")
        with self._cond:
            if self._stopping:
                raise DrainingError("generation scheduler stopped")
            self._submits += 1
            req = _GenRequest(prompt, max_new, priority_rank(priority),
                              deadline, faultinject.on_generate_submit(),
                              on_token=on_token, sampling=sampling)
            self._backends[key] = (model, lock)
            self._enqueue_locked(key, req)
            loop = self._loops.get(key)
            if loop is None or not loop.is_alive():
                loop = threading.Thread(
                    target=self._decode_loop, args=(key,), daemon=True,
                    name=f"gen-decode-{len(self._loops)}")
                self._loops[key] = loop
                loop.start()
            self._cond.notify_all()
        while not req.event.is_set():
            remaining = deadline.remaining()
            timeout = 5.0 if remaining is None else max(0.0,
                                                        remaining) + 0.05
            if req.event.wait(timeout):
                break
            deadline.check("generate in flight")
        if req.error is not None:
            raise req.error
        if not req.event.is_set() or (req.error is None
                                      and not req.tokens):
            raise DrainingError("generation scheduler stopped")
        return {"tokens": list(req.tokens),
                "ttft_ms": (None if req.ttft_s is None
                            else round(req.ttft_s * 1000.0, 3)),
                "reprefills": req.reprefills}

    def _enqueue_locked(self, key: str, req: _GenRequest) -> None:
        priority_insert(
            self._queues.setdefault(key, collections.deque()), req)

    def _requeue(self, key: str, req: _GenRequest) -> None:
        """An evicted victim goes back FIRST within its priority class:
        it already waited its turn once."""
        with self._cond:
            priority_insert(
                self._queues.setdefault(key, collections.deque()), req,
                front_of_class=True)
            self._cond.notify_all()

    def _abandon_loop(self, key: str, error: BaseException) -> None:
        """Abnormal decode-loop exit: fail the queue AND deregister the
        loop in ONE cond hold — a submit that lands after this hold
        sees no (still-alive) loop entry and spawns a fresh one, so a
        request can never be stranded behind a thread that is merely
        unwinding."""
        with self._cond:
            for r in (self._queues.get(key) or ()):
                r.fail(error)
            self._queues.pop(key, None)
            if self._loops.get(key) is threading.current_thread():
                del self._loops[key]
            if self._engines.pop(key, None) is not None:
                self._publish_kv_gauge_locked()

    def _publish_kv_gauge_locked(self) -> None:
        """Publish resident KV bytes across live engines — callers hold
        ``self._cond`` (every resize, retire, and swap republishes, so
        freed pools never linger on the gauge). Under paging the pool
        is FIXED at engine build: the gauge is the page-granular
        eviction budget surface, and prefix sharing dedups occupancy
        BELOW it (see ``kv_pages_*`` in ``stats()``)."""
        get_registry().gauge(
            "serving_kv_cache_bytes",
            help="resident KV page-pool bytes across decode engines"
        ).set(sum(e.pool_bytes for e in self._engines.values()))

    # --------------------------------------------------------- decode loop
    def _decode_loop(self, key: str) -> None:
        engine: Optional[_Engine] = None
        idle_until = time.monotonic() + self.idle_thread_s
        while True:
            admitted: List[_GenRequest] = []
            with self._cond:
                queue = self._queues.get(key)
                active = engine.active() if engine is not None else 0
                while not self._stopping and not queue and active == 0:
                    left = idle_until - time.monotonic()
                    if left <= 0:
                        # retire the idle loop AND its engine: the
                        # bucket's KV caches free with it (a later
                        # submit rebuilds both)
                        if self._loops.get(key) \
                                is threading.current_thread():
                            del self._loops[key]
                            if self._engines.pop(key, None) is not None:
                                self._publish_kv_gauge_locked()
                            if not self._queues.get(key):
                                self._queues.pop(key, None)
                        return
                    self._cond.wait(left)
                    queue = self._queues.get(key)
                if self._stopping:
                    for r in (queue or ()):
                        r.fail(DrainingError(
                            "generation scheduler stopped"))
                    if queue is not None:
                        queue.clear()
                    if engine is not None:
                        engine.fail_all(DrainingError(
                            "generation scheduler stopped"))
                    if self._engines.pop(key, None) is not None:
                        self._publish_kv_gauge_locked()
                    return
                backend = self._backends.get(key)
            if backend is None:
                # the LRU evicted the model with nothing pinning it:
                # queued AND in-flight requests fail cleanly, and the
                # engine (with its KV caches) must go with it — leaving
                # it in _engines would leak the caches and pin the dead
                # model object
                if engine is not None:
                    engine.fail_all(DrainingError(
                        f"model {key!r} evicted mid-generation"))
                self._abandon_loop(key, DrainingError(
                    f"model {key!r} evicted with requests queued"))
                return
            admit_ok = True
            if engine is not None and engine.model is not backend[0]:
                # the server LRU evicted this model and a later request
                # reloaded it as a NEW object: rows already decoding
                # keep THEIR model (their KV caches were built from its
                # weights — switching mid-stream would serve garbage),
                # but nothing new may join; the engine rebuilds against
                # the fresh object once its in-flight rows drain
                if engine.active() == 0:
                    with self._cond:
                        self._engines.pop(key, None)
                        self._publish_kv_gauge_locked()
                    engine = None
                else:
                    admit_ok = False
            if engine is None:
                try:
                    engine = _Engine(self, key, backend[0], backend[1])
                except Exception as e:  # noqa: BLE001 — not a decoder
                    self._abandon_loop(key, e)
                    return
                with self._stats_lock:
                    mix = self._mix.most_common()
                if self.prewarm_decode_ladder:
                    rows, ladder = 1, []
                    while rows <= self.max_rows:
                        ladder.append((("decode", rows), 0))
                        rows <<= 1
                    engine.prewarm(ladder, len(ladder))
                if mix:
                    engine.prewarm(mix, self.prewarm_top)
                with self._cond:
                    self._engines[key] = engine
            # JOIN: admit as many queued requests as capacity allows,
            # priority first — this happens EVERY iteration, so
            # requests join mid-flight of their batchmates
            while admit_ok:
                with self._cond:
                    queue = self._queues.get(key)
                    req = queue[0] if queue else None
                    if req is not None:
                        queue.popleft()
                if req is None:
                    break
                if req.deadline.expired():
                    req.fail(DeadlineExceeded(
                        "generate: budget exhausted in queue"))
                    continue
                if not engine.try_admit(req):
                    # no capacity: put it back at the FRONT OF ITS
                    # CLASS (not the absolute front — a blocked bulk
                    # head must not shadow an interactive arrival that
                    # could preempt its way in)
                    self._requeue(key, req)
                    break
                admitted.append(req)
            if engine.active() == 0:
                # nothing decodable (queue blocked on capacity is
                # impossible with 0 active; queue empty otherwise)
                idle_until = time.monotonic() + self.idle_thread_s
                continue
            # small join window at low occupancy: let concurrent
            # arrivals coalesce into the same decode step
            if self.max_wait_s > 0 and engine.active() < self.max_rows \
                    and not admitted:
                with self._cond:
                    if not self._queues.get(key):
                        self._cond.wait(self.max_wait_s)
            try:
                engine.decode_iteration()
            except Exception as e:  # noqa: BLE001 — the loop survives
                engine.fail_all(e)
            idle_until = time.monotonic() + self.idle_thread_s

    # ------------------------------------------------------------ lifecycle
    def evict_model(self, key: str) -> None:
        """Drop the compiled buckets and the backend registration for
        an evicted model (the compile cache dies with the server LRU).
        Any still-queued or in-flight generation for the key fails
        cleanly with DRAINING at the next loop iteration — callers who
        want in-flight work to finish must not evict while ops are in
        flight (KerasServer's pinned-model LRU guarantees exactly
        that, so over the gateway this only ever fires idle)."""
        with self._cond:   # serialize purge+pop against compile puts
            self._compiled.evict_model(self._cache_owner, key)
            self._backends.pop(key, None)

    def stop(self, grace_s: float = 5.0) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
            loops = list(self._loops.values())
        for w in loops:
            w.join(grace_s)
        # release this scheduler's slice of the global compile cache
        self._compiled.evict_owner(self._cache_owner)

    def stats(self) -> dict:
        p50, p99 = self.ttft.quantiles()
        with self._cond:
            engines = list(self._engines.values())
        # pool occupancy: used = allocated page groups, shared = pages
        # with refcount > 1 (prefix dedup across rows / the registry) —
        # the dedup savings the page pool buys below its fixed ceiling
        pages_total = sum(e.usable_pages for e in engines)
        pages_used = sum(e.total_pages - 1 - len(e.free_pages)
                        for e in engines)
        pages_shared = sum(
            sum(1 for pid in range(1, e.total_pages)
                if e.page_ref[pid] > 1) for e in engines)
        with self._stats_lock:
            return {
                "compile_s": round(self.compile_s, 3),
                "compiles": self.compiles,
                "tokens_out": self.tokens_out,
                "prefill_steps": self.prefill_steps,
                "prefix_lookups": self.prefix_lookups,
                "prefix_hits": self.prefix_hits,
                "prefix_cache_hit_rate": round(
                    self.prefix_hits / max(1, self.prefix_lookups), 4),
                "kv_pages_total": pages_total,
                "kv_pages_used": pages_used,
                "kv_pages_shared": pages_shared,
                "bucket_mix": {f"{k}:{b}": n for (k, b), n in
                               sorted(self._mix.items())},
                "bucket_compiles": {f"{m}:{k}:{b}": n
                                    for (m, k, b), n in sorted(
                                        self._compiles_per_bucket
                                        .items())},
                "ttft_p50_ms": (None if p50 is None
                                else round(p50 * 1000, 2)),
                "ttft_p99_ms": (None if p99 is None
                                else round(p99 * 1000, 2)),
            }
