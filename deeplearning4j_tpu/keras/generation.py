"""Token-level continuous batching for autoregressive decoders
(ISSUE 15 — the iteration-level LM serving engine).

PR 6's scheduler batches *whole predicts*: every request occupies its
batch for the full dispatch. For an autoregressive decoder that wastes
the accelerator on every step a short request pads out a long one — the
right scheduling unit is the DECODE STEP. This module serves the GPT
decoder (``models/gpt.py``) iteration-level:

- **Requests join and leave the running batch every decode step.** A
  per-model decode loop owns a pow2-row bucket; an admitted request is
  prefilled (its own pow2 prompt-length bucket), its KV cache row is
  inserted into the bucket, and from then on each loop iteration
  decodes ONE token for every live row. A finished (or failed) row
  leaves immediately; the bucket compacts to the next power of two.
- **Prefill and decode are separate AOT buckets.** Prefill compiles per
  pow2 prompt length (``jit(prefill).lower(...).compile()`` — params
  and states stay arguments, so ``fit`` never invalidates a bucket);
  decode compiles per pow2 row count. Steady state runs with ZERO
  recompiles: a second wave of identical bucket shapes adds no traces.
- **KV caches are carry-threaded state** (the serving analog of the
  tBPTT scan carries in ``nn/graph.py``): static ``[rows, H, max_len,
  D]`` shapes per attention node, donated to the decode step every
  iteration (shardcheck SC009 statically verifies the donation landed
  as ``input_output_alias``), each row masking its own prefix — which
  is what makes batched greedy decode BITWISE equal to singleton
  decode on CPU, join/leave churn included.
- **Ring-buffer cache eviction under HBM pressure.** The bucket grows
  on demand until ``cache_budget_bytes`` (or ``max_rows``) stops it;
  past that, an INTERACTIVE arrival evicts the oldest-admitted BULK
  row (ring order) instead of waiting behind it — the victim's prompt
  + generated-so-far tokens re-queue and RE-PREFILL when capacity
  returns (never garbage: the re-prefilled cache is rebuilt from the
  tokens, not salvaged). ``evict_cache`` chaos forces the same path.
- **Priority classes**: the admission queue orders ``interactive``
  ahead of ``bulk`` (stable FIFO within a class) — same discipline as
  the predict scheduler's queue.

Every PR 6 invariant carries over: admission only through the server's
ServiceGuard, the nonfinite sentinel runs PER ROW per step (a poisoned
request fails alone MID-STREAM — ``poison_decode`` chaos proves it; its
batchmates keep decoding), a batch-level decode failure re-runs each
row as a singleton before anything surfaces, and compiled steps live in
the budgeted cross-model :class:`~.batching.CompileCache`.

Observability: ``serving_generated_tokens_total``,
``serving_decode_steps_total``, ``serving_decode_batch_rows``
histogram, ``serving_ttft_seconds`` + ``serving_ttft_p50/p99_ms``
(time-to-first-token = admission to the prefill's first token),
``serving_kv_cache_bytes`` gauge, ``serving_kv_evictions_total`` /
``serving_reprefills_total``, and ``serve:prefill`` / ``serve:decode``
tracer spans.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.keras.batching import (CompileCache, _LatencyWindow,
                                               get_compile_cache,
                                               next_cache_owner,
                                               priority_insert,
                                               priority_rank)
from deeplearning4j_tpu.profiling.flightrec import record as flight_record
from deeplearning4j_tpu.profiling.metrics import get_registry
from deeplearning4j_tpu.profiling.tracer import get_tracer
from deeplearning4j_tpu.profiling.watchdog import beat as watchdog_beat
from deeplearning4j_tpu.resilience import faultinject
from deeplearning4j_tpu.resilience.sentinel import host_nonfinite
from deeplearning4j_tpu.resilience.service import (Deadline,
                                                   DeadlineExceeded,
                                                   DrainingError,
                                                   NonFiniteOutput)
from deeplearning4j_tpu.util.math_utils import next_pow_of_2

#: row-count edges for the serving_decode_batch_rows histogram
DECODE_ROWS_BUCKETS = (1, 2, 4, 8, 16, 32, 64)


class _GenRequest:
    """One generation in flight: the prompt (plus any tokens already
    generated before a cache eviction), its budget, and the future the
    submitting handler thread blocks on."""

    __slots__ = ("prompt", "max_new", "priority", "deadline", "event",
                 "tokens", "error", "t0", "ttft_s", "index", "steps",
                 "reprefills", "admit_seq", "model_obj", "on_token")

    def __init__(self, prompt: np.ndarray, max_new: int, priority: int,
                 deadline: Deadline, index: int, on_token=None):
        self.prompt = prompt
        self.on_token = on_token         # per-token stream hook
        self.max_new = max_new
        self.priority = priority
        self.deadline = deadline
        self.event = threading.Event()
        self.tokens: List[int] = []      # generated so far
        self.error: Optional[BaseException] = None
        self.t0 = time.monotonic()
        self.ttft_s: Optional[float] = None
        self.index = index               # admission order (chaos seam)
        self.steps = 0                   # decode steps taken
        self.reprefills = 0
        self.admit_seq = -1              # ring position (eviction order)
        self.model_obj = None            # the weights my tokens came from

    def push_token(self, tok: int) -> None:
        """Append one generated token and stream it to the submitter's
        ``on_token`` hook (the gateway's partial-line writer). A hook
        failure — the client hung up mid-stream — unhooks streaming but
        never touches the generation itself: tokens keep accumulating
        and the final result (or the handler's own write failure)
        settles the request. Called only on the decode-loop thread, and
        always BEFORE ``finish()`` sets the event, so every partial is
        on the wire before the final response line."""
        self.tokens.append(tok)
        cb = self.on_token
        if cb is not None:
            try:
                cb(tok)
            except Exception:  # noqa: BLE001 — stream loss ≠ decode loss
                self.on_token = None

    def history(self) -> np.ndarray:
        """prompt + generated tokens — what a re-prefill rebuilds from."""
        if not self.tokens:
            return self.prompt
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.event.set()

    def finish(self) -> None:
        self.event.set()


class _Engine:
    """Per-model decode state: the pow2 row bucket, its KV caches, and
    the AOT-compiled prefill/decode executables. All mutation happens on
    the owning scheduler's decode-loop thread; the scheduler lock only
    guards the queue handoff."""

    def __init__(self, scheduler: "GenerationScheduler", key: str,
                 model, lock: threading.Lock):
        prefill, decode = model.decode_fns()   # validates decodability
        self.scheduler = scheduler
        self.key = key
        self.model = model
        self.lock = lock
        self._prefill_fn = prefill
        self._decode_fn = decode
        self.vocab = model.decode_vocab()
        self.max_len = model.decode_max_len()
        self.row_bytes = model.decode_cache_bytes(1)
        budget = scheduler.cache_budget_bytes
        if budget is not None and budget < self.row_bytes:
            raise ValueError(
                f"cache_budget_bytes={budget} cannot hold even one "
                f"decode row ({self.row_bytes} bytes/row)")
        self.rows = 0
        self.caches = None
        self.slots: List[Optional[_GenRequest]] = []
        self.tokens: List[int] = []      # next token to feed, per slot
        self.positions: List[int] = []   # next decode position, per slot
        self.iteration = 0
        self._admit_seq = 0
        self._eye = np.eye(self.vocab, dtype=np.float32)

    # ---------------------------------------------------------- compiled
    def _compiled(self, kind: str, bucket: int):
        """The AOT executable for one (kind, bucket): ``("prefill",
        pow2 prompt len)`` or ``("decode", pow2 rows)`` — cached in the
        budgeted cross-model cache, compiled once. Caches are DONATED
        (argnums 2): each call consumes the previous iteration's cache
        buffers in place of allocating a second copy."""
        sched = self.scheduler
        cache_key = (sched._cache_owner, self.key, kind, bucket)
        runner = sched._compiled.get(cache_key)
        if runner is not None:
            return runner
        import jax
        t0 = time.perf_counter()
        fn = self._prefill_fn if kind == "prefill" else self._decode_fn
        caches = self.model.init_decode_cache(
            bucket if kind == "decode" else 1)
        if kind == "prefill":
            x = jax.ShapeDtypeStruct((1, bucket, self.vocab), np.float32)
            aux = jax.ShapeDtypeStruct((1,), np.int32)
        else:
            x = jax.ShapeDtypeStruct((bucket, 1, self.vocab), np.float32)
            aux = jax.ShapeDtypeStruct((bucket,), np.int32)
        compiled = jax.jit(fn, donate_argnums=(2,)).lower(
            self.model.params, self.model.states, caches, x, aux
        ).compile()
        elapsed = time.perf_counter() - t0
        get_registry().counter(
            "serving_compile_seconds_total",
            help="seconds spent AOT-compiling per-bucket predict "
                 "steps").inc(elapsed)
        with sched._stats_lock:
            sched.compile_s += elapsed
            sched.compiles += 1
            sched._compiles_per_bucket[(self.key, kind, bucket)] += 1

        def runner(params, states, c, xv, av, _c=compiled):
            return _c(params, states, c, xv, av)

        with sched._cond:
            cur = sched._backends.get(self.key)
            if cur is not None and cur[0] is self.model:
                # cache only while the key still maps to THIS model
                # object — an evict (purge serializes on this cond) or
                # a swap-to-fresh-load while we compiled must not get
                # a stale executable re-landed behind it
                sched._compiled.put(
                    cache_key, runner,
                    CompileCache.compiled_nbytes(compiled))
        return runner

    def prewarm(self, mix, top: int) -> int:
        """Speculatively compile the most-observed prefill/decode
        buckets for this (fresh) engine before traffic needs them."""
        done = 0
        for (kind, bucket), _ in mix:
            if done >= top:
                break
            if self.scheduler._compiled.get(
                    (self.scheduler._cache_owner, self.key, kind,
                     bucket)) is None:
                try:
                    self._compiled(kind, bucket)
                    done += 1
                except Exception:  # noqa: BLE001 — prewarm is speculative
                    continue
        if done:
            get_registry().counter(
                "serving_prewarmed_buckets_total",
                help="AOT buckets compiled speculatively from the "
                     "observed request-size mix").inc(done)
        return done

    # ------------------------------------------------------------ prefill
    def prefill_bucket(self, n_tokens: int) -> int:
        return min(next_pow_of_2(n_tokens), self.max_len)

    def _prefill(self, req: _GenRequest):
        """Run the request's prompt (or re-prefill history) through its
        pow2 length bucket; returns (first token, 1-row caches)."""
        history = req.history()
        L = len(history)
        bucket = self.prefill_bucket(L)
        x = np.zeros((1, bucket, self.vocab), np.float32)
        x[0, :L] = self._eye[history]
        runner = self._compiled("prefill", bucket)
        with self.scheduler._stats_lock:   # traffic mix (prewarm signal)
            self.scheduler._mix[("prefill", bucket)] += 1
        flight_record("serving", "prefill_dispatch", model=self.key,
                      bucket=bucket, tokens=L)
        with get_tracer().span("serve:prefill", model=self.key,
                               bucket=bucket, tokens=L):
            with self.lock:
                probs, caches = runner(
                    self.model.params, self.model.states,
                    self.model.init_decode_cache(1), x,
                    np.asarray([L], np.int32))
        return int(np.asarray(probs)[0].argmax()), caches

    # ----------------------------------------------------- slot lifecycle
    def active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def _publish_cache_gauge(self) -> None:
        with self.scheduler._cond:   # _engines mutates under the cond
            self.scheduler._publish_kv_gauge_locked()

    def _grow_allowed(self, new_rows: int) -> bool:
        if new_rows > self.scheduler.max_rows:
            return False
        budget = self.scheduler.cache_budget_bytes
        return budget is None or new_rows * self.row_bytes <= budget

    def _resize(self, new_rows: int) -> None:
        """Re-bucket the decode batch: live rows keep their cache
        contents (row gather — values untouched, so parity is
        unaffected); free rows' contents are irrelevant because a JOIN
        always overwrites its whole cache row."""
        import jax.numpy as jnp
        live = [i for i, s in enumerate(self.slots) if s is not None]
        assert len(live) <= new_rows
        if self.caches is None:
            self.caches = self.model.init_decode_cache(new_rows)  # lockcheck: disable=LC004 -- caches is decode-loop private; decode_iteration's lock guards the model op, not this field
        elif new_rows != self.rows:
            idx = np.asarray(live + [0] * (new_rows - len(live)),
                             np.int32)
            self.caches = {n: {k: jnp.take(v, idx, axis=0)
                               for k, v in kv.items()}
                           for n, kv in self.caches.items()}
        new_slots: List[Optional[_GenRequest]] = [None] * new_rows
        new_tokens, new_positions = [0] * new_rows, [0] * new_rows
        for j, i in enumerate(live):
            new_slots[j] = self.slots[i]
            new_tokens[j] = self.tokens[i]
            new_positions[j] = self.positions[i]
        self.slots, self.tokens, self.positions = (new_slots, new_tokens,
                                                   new_positions)
        self.rows = new_rows
        self._publish_cache_gauge()

    def try_admit(self, req: _GenRequest) -> bool:
        """JOIN: prefill the request and insert its cache row. Returns
        False when no capacity exists (caller re-queues)."""
        row = next((i for i, s in enumerate(self.slots) if s is None),
                   None)
        if row is None:
            new_rows = next_pow_of_2(self.active() + 1)
            if not self._grow_allowed(new_rows):
                if not self._preempt_for(req):
                    return False
                row = next(i for i, s in enumerate(self.slots)
                           if s is None)
            else:
                self._resize(new_rows)
                row = next(i for i, s in enumerate(self.slots)
                           if s is None)
        if req.tokens and req.model_obj is not self.model:
            # an evicted victim re-admitted after the model was
            # reloaded as a NEW object: re-prefilling its old-model
            # tokens under the new weights would blend two models in
            # one response — fail it honestly instead
            req.fail(RuntimeError(
                "model reloaded while this generation awaited "
                "re-prefill after a cache eviction; retry"))
            return True
        req.model_obj = self.model
        history_len = len(req.history())
        try:
            first, cache1 = self._prefill(req)
        except Exception as e:  # noqa: BLE001 — fail THIS request alone
            req.fail(e)
            return True
        if req.ttft_s is None:  # a re-prefilled victim keeps its first
            req.ttft_s = time.monotonic() - req.t0
            self.scheduler.ttft.observe(req.ttft_s)
        req.push_token(first)
        req.admit_seq = self._admit_seq
        self._admit_seq += 1
        self.slots[row] = req
        self.tokens[row] = first
        # next decode writes `first`'s K/V at position = history length
        self.positions[row] = history_len
        for name, kv in cache1.items():
            for k, v in kv.items():
                self.caches[name][k] = self.caches[name][k].at[row].set(
                    v[0])
        if len(req.tokens) >= req.max_new \
                or self.positions[row] >= self.max_len:
            self._complete(row)      # prompt-only TTFT request
        return True

    def _preempt_for(self, req: _GenRequest) -> bool:
        """Ring-buffer eviction under pressure: an INTERACTIVE arrival
        evicts the oldest-admitted BULK row rather than waiting behind
        it. Bulk arrivals never preempt."""
        if req.priority != 0:
            return False
        victims = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                   if s is not None and s.priority > 0]
        if not victims:
            return False
        self.evict_row(min(victims)[1], reason="preempt")
        return True

    def evict_row(self, row: int, reason: str = "pressure") -> None:
        """LEAVE (involuntary): push the victim back onto the queue;
        its history re-prefills when capacity returns — the cache row
        is abandoned, never reused."""
        victim = self.slots[row]
        if victim is None:
            return
        victim.reprefills += 1
        self.slots[row] = None
        reg = get_registry()
        reg.counter("serving_kv_evictions_total",
                    help="KV-cache rows evicted (ring-buffer pressure "
                         "or chaos)").inc()
        reg.counter("serving_reprefills_total",
                    help="evicted generations re-queued for "
                         "re-prefill").inc()
        get_tracer().instant("kv_evicted", model=self.key, row=row,
                             reason=reason)
        flight_record("serving", "kv_evicted", model=self.key, row=row,
                      reason=reason)
        self.scheduler._requeue(self.key, victim)

    def ring_victim(self) -> Optional[int]:
        """Oldest-admitted live row — the ring-buffer eviction order."""
        live = [(s.admit_seq, i) for i, s in enumerate(self.slots)
                if s is not None]
        return min(live)[1] if live else None

    def _complete(self, row: int) -> None:
        req = self.slots[row]
        self.slots[row] = None
        get_registry().counter(
            "serving_generated_tokens_total",
            help="tokens generated by the decode engine").inc(
                len(req.tokens))
        with self.scheduler._stats_lock:
            self.scheduler.tokens_out += len(req.tokens)
        req.finish()

    # ------------------------------------------------------------- decode
    def decode_iteration(self) -> None:
        """One engine step: decode ONE token for every live row."""
        self.iteration += 1
        live = [i for i, s in enumerate(self.slots) if s is not None]
        if not live:
            return
        # deadline-blown rows leave before paying for the step
        for i in list(live):
            req = self.slots[i]
            if req.deadline.expired():
                req.fail(DeadlineExceeded(
                    "generate: budget exhausted mid-stream at "
                    f"token {len(req.tokens)}"))
                self.slots[i] = None
                live.remove(i)
        if not live:
            return
        x = np.zeros((self.rows, 1, self.vocab), np.float32)
        for i in live:
            x[i, 0] = self._eye[self.tokens[i]]
        positions = np.asarray(self.positions, np.int32)
        runner = self._compiled("decode", self.rows)
        tracer = get_tracer()
        watchdog_beat("serving_decode")
        flight_record("serving", "decode_dispatch", model=self.key,
                      rows=self.rows, live=len(live),
                      iteration=self.iteration)
        with tracer.span("serve:decode", model=self.key, rows=self.rows,
                         live=len(live), iteration=self.iteration):
            try:
                with self.lock:
                    probs, self.caches = runner(
                        self.model.params, self.model.states,
                        self.caches, x, positions)
                probs = np.asarray(probs)
            except Exception:  # noqa: BLE001 — isolate batchmates
                # batch-level decode failure: re-run each live row ALONE
                # before surfacing anything (PR 6 singleton-fallback
                # discipline, per decode step)
                get_registry().counter(
                    "serving_decode_fallbacks_total",
                    help="decode steps re-run as singletons after a "
                         "batch-level failure").inc()
                if self._caches_deleted():
                    # the failed call had already CONSUMED the donated
                    # cache buffers (a runtime fault after dispatch):
                    # the singleton fallback has nothing to slice.
                    # Rebuild instead of failing everyone — every live
                    # row re-queues for RE-PREFILL from its tokens,
                    # the same never-garbage path eviction uses.
                    for i in list(live):
                        self.evict_row(i, reason="donated-cache-lost")
                    self.caches = self.model.init_decode_cache(self.rows)
                    return
                probs = self._singleton_fallback(live, x, positions)
        reg = get_registry()
        reg.counter("serving_decode_steps_total",
                    help="batched decode steps executed").inc()
        reg.histogram("serving_decode_batch_rows",
                      help="live generations per decode step",
                      buckets=DECODE_ROWS_BUCKETS).observe(len(live))
        with self.scheduler._stats_lock:   # traffic mix (prewarm signal)
            self.scheduler._mix[("decode", self.rows)] += 1
        for i in live:
            req = self.slots[i]
            if req is None:
                continue
            row_probs = probs[i] if probs is not None else None
            if row_probs is None:
                continue  # fallback already failed this row
            if faultinject.poison_decode_row(req.index, req.steps + 1):
                row_probs = np.full_like(row_probs, np.nan)
            if host_nonfinite(row_probs):
                reg.counter(
                    "serving_nonfinite_outputs_total",
                    help="predictions refused because the model output "
                         "carried NaN/Inf").inc()
                req.fail(NonFiniteOutput(
                    f"generation row turned NaN/Inf at token "
                    f"{len(req.tokens) + 1}"))
                self.slots[i] = None     # fails ALONE, mid-stream
                continue
            tok = int(row_probs.argmax())
            req.push_token(tok)
            req.steps += 1
            self.tokens[i] = tok
            self.positions[i] += 1
            if len(req.tokens) >= req.max_new \
                    or self.positions[i] >= self.max_len:
                self._complete(i)
        # evict_cache chaos: force one ring eviction, exactly what HBM
        # pressure would do — the victim must re-prefill, never garbage
        if faultinject.check_evict_cache():
            victim = self.ring_victim()
            if victim is not None:
                self.evict_row(victim, reason="chaos")
        # compact: a half-empty bucket shrinks to its pow2
        target = max(1, next_pow_of_2(max(1, self.active())))
        if target < self.rows:
            self._resize(target)

    def _caches_deleted(self) -> bool:
        """True when the bucket's cache buffers were invalidated by a
        donation that dispatched before the step failed."""
        for kv in self.caches.values():
            for v in kv.values():
                deleted = getattr(v, "is_deleted", None)
                if deleted is not None and deleted():
                    return True
        return False

    def _singleton_fallback(self, live, x, positions):
        """Re-run each live row in the 1-row decode bucket; rows that
        fail alone surface their own error (and only those may charge
        the caller's breaker). Successful rows' cache updates write
        back into the bucket."""
        probs = np.zeros((self.rows, self.vocab), np.float32)
        import jax.numpy as jnp
        for i in live:
            req = self.slots[i]
            try:
                c1 = {n: {k: v[i:i + 1] for k, v in kv.items()}
                      for n, kv in self.caches.items()}
                runner = self._compiled("decode", 1)
                with self.lock:
                    p1, c1 = runner(self.model.params, self.model.states,
                                    c1, x[i:i + 1], positions[i:i + 1])
                probs[i] = np.asarray(p1)[0]
                for n, kv in c1.items():
                    for k, v in kv.items():
                        self.caches[n][k] = \
                            self.caches[n][k].at[i].set(jnp.asarray(v)[0])
            except Exception as e:  # noqa: BLE001 — per-row verdict
                req.fail(e)
                self.slots[i] = None
        return probs

    def fail_all(self, error: BaseException) -> None:
        for i, req in enumerate(self.slots):
            if req is not None:
                req.fail(error)
                self.slots[i] = None


class GenerationScheduler:
    """Per-server token-level scheduler. ``submit()`` is called by an
    admitted handler thread (holding its ServiceGuard slot) and blocks
    until the generation completes; a per-model decode-loop thread owns
    the engine. The caller resolves the model key ONCE at admission —
    eviction or an LRU swap can never retarget a queued request."""

    def __init__(self, max_rows: int = 8, max_wait_ms: float = 0.0,
                 cache_budget_bytes: Optional[int] = None,
                 idle_thread_s: float = 30.0,
                 compile_cache: Optional[CompileCache] = None,
                 prewarm_top: int = 3,
                 prewarm_decode_ladder: bool = False):
        if max_rows < 1:
            raise ValueError("max_rows must be >= 1")
        self.max_rows = next_pow_of_2(int(max_rows))
        if self.max_rows > max_rows:
            self.max_rows >>= 1
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.cache_budget_bytes = cache_budget_bytes
        self.idle_thread_s = idle_thread_s
        self.prewarm_top = prewarm_top
        # compile the whole pow2 decode-rows ladder at engine build:
        # log2(max_rows)+1 small programs buy DETERMINISTIC zero-
        # recompile steady state whatever row counts churn produces
        self.prewarm_decode_ladder = prewarm_decode_ladder
        self._cond = threading.Condition()
        self._queues: Dict[str, collections.deque] = {}
        self._backends: Dict[str, tuple] = {}
        self._engines: Dict[str, _Engine] = {}
        self._loops: Dict[str, threading.Thread] = {}
        self._compiled = (compile_cache if compile_cache is not None
                          else get_compile_cache())
        self._cache_owner = next_cache_owner()
        self._stopping = False
        self._stats_lock = threading.Lock()
        self.compile_s = 0.0
        self.compiles = 0
        self.tokens_out = 0
        # _mix = OBSERVED traffic per (kind, bucket) — the speculative-
        # prewarm ranking signal; _compiles_per_bucket = compiles per
        # bucket — the zero-recompile gate surface (a value > 1 means a
        # shape was re-traced, whatever the traffic was)
        self._mix: collections.Counter = collections.Counter()
        self._compiles_per_bucket: collections.Counter = \
            collections.Counter()
        self._submits = 0
        self.ttft = _LatencyWindow(
            hist_name="serving_ttft_seconds",
            hist_help="time to first token (admission to the "
                      "prefill's first greedy token)",
            gauge_prefix="serving_ttft", gauge_what="time to first "
                                                    "token")

    # -------------------------------------------------------------- submit
    def submit(self, key: str, model, lock: threading.Lock,
               prompt, max_new_tokens: int, deadline: Deadline,
               priority: str = "interactive", on_token=None) -> dict:
        """Queue one generation and block until it completes. Returns
        ``{"tokens": [...], "ttft_ms": ..., "reprefills": n}``; raises
        the request's own structured error. ``on_token`` (optional) is
        invoked on the decode-loop thread with each token the moment it
        is generated — the streaming-gateway seam; exceptions it raises
        only stop the streaming, never the generation."""
        prompt = np.asarray(prompt, np.int32).ravel()
        vocab = model.decode_vocab()
        max_len = model.decode_max_len()
        if prompt.size < 1:
            raise ValueError("generate needs a non-empty prompt")
        if prompt.min() < 0 or prompt.max() >= vocab:
            raise ValueError(f"prompt token out of range [0, {vocab})")
        if prompt.size >= max_len:
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate (max sequence length {max_len})")
        max_new = min(int(max_new_tokens), max_len - prompt.size)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        deadline.check("generate enqueue")
        with self._cond:
            if self._stopping:
                raise DrainingError("generation scheduler stopped")
            self._submits += 1
            req = _GenRequest(prompt, max_new, priority_rank(priority),
                              deadline, faultinject.on_generate_submit(),
                              on_token=on_token)
            self._backends[key] = (model, lock)
            self._enqueue_locked(key, req)
            loop = self._loops.get(key)
            if loop is None or not loop.is_alive():
                loop = threading.Thread(
                    target=self._decode_loop, args=(key,), daemon=True,
                    name=f"gen-decode-{len(self._loops)}")
                self._loops[key] = loop
                loop.start()
            self._cond.notify_all()
        while not req.event.is_set():
            remaining = deadline.remaining()
            timeout = 5.0 if remaining is None else max(0.0,
                                                        remaining) + 0.05
            if req.event.wait(timeout):
                break
            deadline.check("generate in flight")
        if req.error is not None:
            raise req.error
        if not req.event.is_set() or (req.error is None
                                      and not req.tokens):
            raise DrainingError("generation scheduler stopped")
        return {"tokens": list(req.tokens),
                "ttft_ms": (None if req.ttft_s is None
                            else round(req.ttft_s * 1000.0, 3)),
                "reprefills": req.reprefills}

    def _enqueue_locked(self, key: str, req: _GenRequest) -> None:
        priority_insert(
            self._queues.setdefault(key, collections.deque()), req)

    def _requeue(self, key: str, req: _GenRequest) -> None:
        """An evicted victim goes back FIRST within its priority class:
        it already waited its turn once."""
        with self._cond:
            priority_insert(
                self._queues.setdefault(key, collections.deque()), req,
                front_of_class=True)
            self._cond.notify_all()

    def _abandon_loop(self, key: str, error: BaseException) -> None:
        """Abnormal decode-loop exit: fail the queue AND deregister the
        loop in ONE cond hold — a submit that lands after this hold
        sees no (still-alive) loop entry and spawns a fresh one, so a
        request can never be stranded behind a thread that is merely
        unwinding."""
        with self._cond:
            for r in (self._queues.get(key) or ()):
                r.fail(error)
            self._queues.pop(key, None)
            if self._loops.get(key) is threading.current_thread():
                del self._loops[key]
            if self._engines.pop(key, None) is not None:
                self._publish_kv_gauge_locked()

    def _publish_kv_gauge_locked(self) -> None:
        """Publish resident KV bytes across live engines — callers hold
        ``self._cond`` (every resize, retire, and swap republishes, so
        freed caches never linger on the gauge)."""
        get_registry().gauge(
            "serving_kv_cache_bytes",
            help="resident KV-cache bytes across decode buckets"
        ).set(sum(e.rows * e.row_bytes for e in self._engines.values()))

    # --------------------------------------------------------- decode loop
    def _decode_loop(self, key: str) -> None:
        engine: Optional[_Engine] = None
        idle_until = time.monotonic() + self.idle_thread_s
        while True:
            admitted: List[_GenRequest] = []
            with self._cond:
                queue = self._queues.get(key)
                active = engine.active() if engine is not None else 0
                while not self._stopping and not queue and active == 0:
                    left = idle_until - time.monotonic()
                    if left <= 0:
                        # retire the idle loop AND its engine: the
                        # bucket's KV caches free with it (a later
                        # submit rebuilds both)
                        if self._loops.get(key) \
                                is threading.current_thread():
                            del self._loops[key]
                            if self._engines.pop(key, None) is not None:
                                self._publish_kv_gauge_locked()
                            if not self._queues.get(key):
                                self._queues.pop(key, None)
                        return
                    self._cond.wait(left)
                    queue = self._queues.get(key)
                if self._stopping:
                    for r in (queue or ()):
                        r.fail(DrainingError(
                            "generation scheduler stopped"))
                    if queue is not None:
                        queue.clear()
                    if engine is not None:
                        engine.fail_all(DrainingError(
                            "generation scheduler stopped"))
                    if self._engines.pop(key, None) is not None:
                        self._publish_kv_gauge_locked()
                    return
                backend = self._backends.get(key)
            if backend is None:
                # the LRU evicted the model with nothing pinning it:
                # queued AND in-flight requests fail cleanly, and the
                # engine (with its KV caches) must go with it — leaving
                # it in _engines would leak the caches and pin the dead
                # model object
                if engine is not None:
                    engine.fail_all(DrainingError(
                        f"model {key!r} evicted mid-generation"))
                self._abandon_loop(key, DrainingError(
                    f"model {key!r} evicted with requests queued"))
                return
            admit_ok = True
            if engine is not None and engine.model is not backend[0]:
                # the server LRU evicted this model and a later request
                # reloaded it as a NEW object: rows already decoding
                # keep THEIR model (their KV caches were built from its
                # weights — switching mid-stream would serve garbage),
                # but nothing new may join; the engine rebuilds against
                # the fresh object once its in-flight rows drain
                if engine.active() == 0:
                    with self._cond:
                        self._engines.pop(key, None)
                        self._publish_kv_gauge_locked()
                    engine = None
                else:
                    admit_ok = False
            if engine is None:
                try:
                    engine = _Engine(self, key, backend[0], backend[1])
                except Exception as e:  # noqa: BLE001 — not a decoder
                    self._abandon_loop(key, e)
                    return
                with self._stats_lock:
                    mix = self._mix.most_common()
                if self.prewarm_decode_ladder:
                    rows, ladder = 1, []
                    while rows <= self.max_rows:
                        ladder.append((("decode", rows), 0))
                        rows <<= 1
                    engine.prewarm(ladder, len(ladder))
                if mix:
                    engine.prewarm(mix, self.prewarm_top)
                with self._cond:
                    self._engines[key] = engine
            # JOIN: admit as many queued requests as capacity allows,
            # priority first — this happens EVERY iteration, so
            # requests join mid-flight of their batchmates
            while admit_ok:
                with self._cond:
                    queue = self._queues.get(key)
                    req = queue[0] if queue else None
                    if req is not None:
                        queue.popleft()
                if req is None:
                    break
                if req.deadline.expired():
                    req.fail(DeadlineExceeded(
                        "generate: budget exhausted in queue"))
                    continue
                if not engine.try_admit(req):
                    # no capacity: put it back at the FRONT OF ITS
                    # CLASS (not the absolute front — a blocked bulk
                    # head must not shadow an interactive arrival that
                    # could preempt its way in)
                    self._requeue(key, req)
                    break
                admitted.append(req)
            if engine.active() == 0:
                # nothing decodable (queue blocked on capacity is
                # impossible with 0 active; queue empty otherwise)
                idle_until = time.monotonic() + self.idle_thread_s
                continue
            # small join window at low occupancy: let concurrent
            # arrivals coalesce into the same decode step
            if self.max_wait_s > 0 and engine.active() < self.max_rows \
                    and not admitted:
                with self._cond:
                    if not self._queues.get(key):
                        self._cond.wait(self.max_wait_s)
            try:
                engine.decode_iteration()
            except Exception as e:  # noqa: BLE001 — the loop survives
                engine.fail_all(e)
            idle_until = time.monotonic() + self.idle_thread_s

    # ------------------------------------------------------------ lifecycle
    def evict_model(self, key: str) -> None:
        """Drop the compiled buckets and the backend registration for
        an evicted model (the compile cache dies with the server LRU).
        Any still-queued or in-flight generation for the key fails
        cleanly with DRAINING at the next loop iteration — callers who
        want in-flight work to finish must not evict while ops are in
        flight (KerasServer's pinned-model LRU guarantees exactly
        that, so over the gateway this only ever fires idle)."""
        with self._cond:   # serialize purge+pop against compile puts
            self._compiled.evict_model(self._cache_owner, key)
            self._backends.pop(key, None)

    def stop(self, grace_s: float = 5.0) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
            loops = list(self._loops.values())
        for w in loops:
            w.join(grace_s)
        # release this scheduler's slice of the global compile cache
        self._compiled.evict_owner(self._cache_owner)

    def stats(self) -> dict:
        p50, p99 = self.ttft.quantiles()
        with self._stats_lock:
            return {
                "compile_s": round(self.compile_s, 3),
                "compiles": self.compiles,
                "tokens_out": self.tokens_out,
                "bucket_mix": {f"{k}:{b}": n for (k, b), n in
                               sorted(self._mix.items())},
                "bucket_compiles": {f"{m}:{k}:{b}": n
                                    for (m, k, b), n in sorted(
                                        self._compiles_per_bucket
                                        .items())},
                "ttft_p50_ms": (None if p50 is None
                                else round(p50 * 1000, 2)),
                "ttft_p99_ms": (None if p99 is None
                                else round(p99 * 1000, 2)),
            }
