"""Continuous-batching scheduler for the Keras gateway.

KerasServer used to dispatch one request = one compiled call, so
concurrent predicts on one model serialized on the per-model op lock
and serving throughput was bounded by single-request latency — and
every new input shape paid a recompile. This module is the serving-edge
analog of the µ-cuDNN micro-batching trick (arXiv 1804.04806): predict
requests admitted for the same model land in a per-model queue, a
dispatcher thread coalesces them into padded, shape-bucketed batches,
executes ONE ahead-of-time-compiled step per bucket, and splits the
result back to per-request futures with the padding rows dropped.

Batching discipline:

- **Bucket** = next power-of-two row count up to ``max_batch`` (the
  "precompile the shapes you'll actually run" discipline of arXiv
  1410.0759); the non-batch feature shape and dtype are exact-matched —
  only same-shaped requests coalesce. A request larger than
  ``max_batch`` runs alone in its own (still cached) bucket.
- **AOT compile cache**: one compiled executable per (model, bucket,
  feature-shape) triple via ``jit(infer).lower(...).compile()`` —
  params/states stay arguments, so fit updates never invalidate the
  executable. The cache is keyed like the server's LRU model cache and
  evicted with it (``evict_model``). Per-request recompiles are dead:
  after warmup, a wave of identical-bucket requests adds zero traces.
- **Deadline-aware flush**: a batch flushes when it is full
  (``reason=full``), when a member's ``deadline_ms`` budget is nearly
  spent (``reason=deadline`` — the margin covers dispatch), or when
  ``max_wait_ms`` elapses at low load (``reason=idle``), so worst-case
  added latency is bounded.
- **Per-row nonfinite guard**: the sentinel check runs per request,
  not per batch — one poisoned request gets ``NONFINITE`` alone; its
  batchmates are served. A *batch-level* execution failure falls back
  to singleton re-execution before any request surfaces an error, so
  the circuit breaker is only charged for requests that fail alone.

Everything is observable: ``serving_batch_size`` histogram,
``serving_batched_requests_total`` / ``serving_batch_flushes_total``
(by flush reason) / ``serving_batch_fallbacks_total`` counters,
``serving_compile_seconds_total``, p50/p99 predict-latency gauges, and
``serve:batch`` tracer spans.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.profiling.metrics import get_registry
from deeplearning4j_tpu.profiling.tracer import get_tracer
from deeplearning4j_tpu.resilience import faultinject
from deeplearning4j_tpu.resilience.service import (Deadline,
                                                   DeadlineExceeded,
                                                   DrainingError,
                                                   NonFiniteOutput)
from deeplearning4j_tpu.util.math_utils import next_pow_of_2

# row-count edges for the serving_batch_size histogram (requests per
# executed batch — NOT seconds, hence not DEFAULT_TIME_BUCKETS)
BATCH_SIZE_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)

# sub-second-focused edges for predict latency (the default time
# buckets are compile-scale and would put every predict in one bucket)
PREDICT_LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                           0.1, 0.25, 0.5, 1.0, 2.5, 10.0)

FLUSH_REASONS = ("full", "deadline", "idle")

#: priority classes for the batch queue (ISSUE 15): an INTERACTIVE
#: request is inserted ahead of every queued BULK request, so a latency-
#: sensitive predict/generate never waits behind a bulk scorer's
#: backlog. Ordering is stable within a class (FIFO).
PRIORITIES = {"interactive": 0, "bulk": 1}


def priority_rank(priority: str) -> int:
    try:
        return PRIORITIES[priority]
    except KeyError:
        raise ValueError(f"unknown priority {priority!r}; "
                         f"one of {tuple(PRIORITIES)}") from None


def priority_insert(queue, item, *, front_of_class: bool = False) -> None:
    """Insert ``item`` (anything with a ``priority`` rank) into a
    priority-ordered deque: ahead of every lower-priority entry, FIFO
    within its class — the ONE insert discipline both batch queues
    (predict and generate) share. ``front_of_class`` puts the item
    ahead of its own class too (an evicted victim that already waited
    its turn)."""
    if front_of_class:
        idx = next((i for i, q in enumerate(queue)
                    if q.priority >= item.priority), len(queue))
        queue.insert(idx, item)
        return
    if queue and queue[-1].priority > item.priority:
        idx = next(i for i, q in enumerate(queue)
                   if q.priority > item.priority)
        queue.insert(idx, item)
    else:
        queue.append(item)


class CompileCache:
    """Cross-model AOT compile cache with a GLOBAL entry/bytes budget
    (ISSUE 15 satellite). PR 6 cached one compiled executable per
    (model, bucket, shape) with no bound except the model LRU — a
    gateway serving many models with ragged traffic could accumulate
    executables without limit. This cache is shared by every scheduler
    in the process (predict buckets AND generation prefill/decode
    buckets): entries are LRU-ordered across models, the budget counts
    entries and compiled bytes (XLA's own memory analysis where the
    backend reports it), and evictions land in
    ``serving_compile_cache_evictions_total``. A model evicted from the
    server LRU still drops all of its entries at once
    (``evict_model`` — the cache is evicted WITH the model cache)."""

    def __init__(self, max_entries: int = 128,
                 max_bytes: Optional[int] = 512 * 1024 * 1024):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.max_entries = int(max_entries)
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()  # key -> (value, nbytes)
        self._bytes = 0

    @staticmethod
    def compiled_nbytes(compiled) -> int:
        """Budget-relevant footprint of one XLA executable: generated
        code + scratch. Backends without memory analysis cost 0 bytes
        (the entry budget still bounds them)."""
        try:
            ma = compiled.memory_analysis()
            return int(getattr(ma, "generated_code_size_in_bytes", 0)
                       + getattr(ma, "temp_size_in_bytes", 0))
        except Exception:  # noqa: BLE001 — sizing is best-effort
            return 0

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            return entry[0]

    def put(self, key, value, nbytes: int = 0) -> None:
        evicted = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, int(nbytes))
            self._bytes += int(nbytes)
            while len(self._entries) > 1 and (
                    len(self._entries) > self.max_entries
                    or (self.max_bytes is not None
                        and self._bytes > self.max_bytes)):
                _, (_, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                evicted += 1
            self._publish_locked()
        if evicted:
            get_registry().counter(
                "serving_compile_cache_evictions_total",
                help="AOT-compiled steps evicted by the cross-model "
                     "compile-cache budget").inc(evicted)

    def _publish_locked(self) -> None:
        reg = get_registry()
        reg.gauge("serving_compile_cache_entries",
                  help="AOT-compiled steps resident in the cross-model "
                       "compile cache").set(len(self._entries))
        reg.gauge("serving_compile_cache_bytes",
                  help="estimated compiled bytes resident in the "
                       "cross-model compile cache").set(self._bytes)

    def remove(self, key) -> None:
        """Drop one entry (a put that lost a race with eviction)."""
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
                self._publish_locked()

    def evict_model(self, owner: int, model_key: str) -> None:
        """Drop every entry one scheduler cached for one model key —
        called when the server LRU evicts the model."""
        with self._lock:
            for k in [k for k in self._entries
                      if k[0] == owner and k[1] == model_key]:
                self._bytes -= self._entries.pop(k)[1]
            self._publish_locked()

    def evict_owner(self, owner: int) -> None:
        """Drop every entry a (stopped) scheduler owns — owner serials
        are never reused, so a dead scheduler's executables would
        otherwise sit in the GLOBAL cache until the budget pushes them
        out."""
        with self._lock:
            for k in [k for k in self._entries if k[0] == owner]:
                self._bytes -= self._entries.pop(k)[1]
            self._publish_locked()

    def keys(self) -> List[tuple]:
        with self._lock:
            return list(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes}


_compile_cache_lock = threading.Lock()
_compile_cache: Optional[CompileCache] = None
_owner_serial = 0


def next_cache_owner() -> int:
    """Monotonic owner id for compile-cache keys. ``id(scheduler)``
    would be reused after garbage collection, letting a new scheduler
    hit a dead scheduler's stale executables (compiled against another
    model's shapes)."""
    global _owner_serial
    with _compile_cache_lock:
        _owner_serial += 1
        return _owner_serial


def get_compile_cache() -> CompileCache:
    """The process-global compile cache every scheduler shares — ONE
    budget across models, buckets, and predict/generate kinds."""
    global _compile_cache
    with _compile_cache_lock:
        if _compile_cache is None:
            _compile_cache = CompileCache()
        return _compile_cache


def set_compile_cache(cache: Optional[CompileCache]
                      ) -> Optional[CompileCache]:
    """Swap the global cache (tests / budget reconfiguration); returns
    the previous one."""
    global _compile_cache
    with _compile_cache_lock:
        prev, _compile_cache = _compile_cache, cache
        return prev


def bucket_rows(rows: int) -> int:
    """The padded row count for a ``rows``-row batch: the next power of
    two. The scheduler caps COALESCED rows at ``max_batch`` before
    calling (max_batch is normalized to a power of two, so coalesced
    buckets never exceed it); a single oversize request gets its own
    larger pow2 bucket — it can never coalesce, but its compile is
    still cached."""
    if rows < 1:
        raise ValueError(f"rows must be >= 1, got {rows}")
    return next_pow_of_2(rows)


def _pow2_floor(n: int) -> int:
    p = next_pow_of_2(n)
    return p if p == n else p >> 1


def quantile(ordered, q: float) -> float:
    """Nearest-rank quantile of an already-sorted sequence — the ONE
    convention the p50/p99 gauges, ``stats()``, and the bench serve
    rung all share."""
    return ordered[min(len(ordered) - 1, int(len(ordered) * q))]


class _Pending:
    """One queued predict: the request's features, its deadline, and the
    future (event + result/error) its handler thread waits on."""

    __slots__ = ("features", "deadline", "event", "result", "error",
                 "rows", "shape_key", "t0", "priority")

    def __init__(self, features: np.ndarray, deadline: Deadline,
                 priority: int = 0):
        self.features = features
        self.deadline = deadline
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.rows = int(features.shape[0])
        # only exact non-batch shape + dtype matches may share a batch
        self.shape_key = (tuple(features.shape[1:]), str(features.dtype))
        self.t0 = time.monotonic()
        self.priority = priority


class _LatencyWindow:
    """Bounded reservoir of recent latencies; publishes p50/p99 gauges
    on every observation (a scrape of ``/api/metrics`` sees the current
    quantiles without histogram interpolation). The metric family is
    parameterized so the generation scheduler's TTFT window shares the
    machinery (``serving_ttft_*``) with the predict window."""

    # republish the gauges every Nth observation: a per-request sort of
    # the whole reservoir would serialize the serving hot path for
    # quantiles that only matter at scrape cadence
    REFRESH_EVERY = 16

    def __init__(self, maxlen: int = 1024,
                 hist_name: str = "serving_predict_seconds",
                 hist_help: str = "end-to-end predict latency "
                                  "(admission to response), successful "
                                  "requests",
                 gauge_prefix: str = "serving_predict",
                 gauge_what: str = "predict latency"):
        self._lock = threading.Lock()
        self._window = collections.deque(maxlen=maxlen)
        self._since_refresh = 0
        self._hist_name = hist_name
        self._hist_help = hist_help
        self._gauge_prefix = gauge_prefix
        self._gauge_what = gauge_what

    def observe(self, seconds: float) -> None:
        get_registry().histogram(
            self._hist_name, help=self._hist_help,
            buckets=PREDICT_LATENCY_BUCKETS).observe(seconds)
        with self._lock:
            self._window.append(seconds)
            self._since_refresh += 1
            refresh = (self._since_refresh >= self.REFRESH_EVERY
                       or len(self._window) == 1)
            if refresh:
                self._since_refresh = 0
        if refresh:
            self._publish(*self.quantiles())

    def _publish(self, p50: float, p99: float) -> None:
        reg = get_registry()
        reg.gauge(f"{self._gauge_prefix}_p50_ms",
                  help=f"median {self._gauge_what} over the recent "
                       "window (ms)").set(p50 * 1000.0)
        reg.gauge(f"{self._gauge_prefix}_p99_ms",
                  help=f"p99 {self._gauge_what} over the recent window "
                       "(ms)").set(p99 * 1000.0)

    def quantiles(self) -> Tuple[Optional[float], Optional[float]]:
        with self._lock:
            if not self._window:
                return None, None
            ordered = sorted(self._window)
        return quantile(ordered, 0.5), quantile(ordered, 0.99)


class BatchScheduler:
    """Per-server continuous-batching engine. ``submit()`` is called by
    an admitted handler thread (holding its ServiceGuard slot) and
    blocks until the request's rows come back; a per-model dispatcher
    thread forms and executes the batches. The caller resolves the
    model key ONCE at admission and threads it through — eviction or an
    LRU swap can never retarget a queued request."""

    def __init__(self, max_batch: int = 32, max_wait_ms: float = 5.0,
                 deadline_margin_ms: float = 50.0,
                 idle_thread_s: float = 30.0,
                 compile_cache: Optional[CompileCache] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        # buckets are powers of two "up to max_batch": normalize down so
        # no bucket ever exceeds the configured cap
        self.max_batch = _pow2_floor(int(max_batch))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.deadline_margin_s = max(0.0, float(deadline_margin_ms)) / 1000.0
        self.idle_thread_s = idle_thread_s
        self._cond = threading.Condition()
        self._queues: Dict[str, collections.deque] = {}
        self._backends: Dict[str, tuple] = {}  # key -> (model, lock)
        self._dispatchers: Dict[str, threading.Thread] = {}
        # compiled steps live in the budgeted CROSS-MODEL cache (global
        # by default): per-scheduler keys, one process-wide budget
        self._compiled = (compile_cache if compile_cache is not None
                          else get_compile_cache())
        self._cache_owner = next_cache_owner()
        # observed request-size mix: (shape_key, bucket) -> batches
        # executed — the speculative-prewarm signal
        self._bucket_mix: collections.Counter = collections.Counter()
        self._stopping = False
        # serve-rung stats (also on /api/metrics, but the bench child
        # wants per-scheduler numbers, not process-global ones)
        self._stats_lock = threading.Lock()
        self.compile_s = 0.0
        self._batch_sizes: collections.Counter = collections.Counter()
        self.latency = _LatencyWindow()

    # ------------------------------------------------------------- metrics
    @staticmethod
    def _flush_counter(reason: str):
        return get_registry().labeled_counter(
            "serving_batch_flushes_total",
            help="batches dispatched, by flush reason").labels(
                reason=reason)

    # -------------------------------------------------------------- submit
    def submit(self, key: str, model, lock: threading.Lock,
               features: np.ndarray, deadline: Deadline,
               priority: str = "interactive") -> np.ndarray:
        """Queue one predict for ``key`` and block until its rows are
        back. Raises the request's own structured error (DEADLINE /
        NONFINITE / the singleton re-execution's failure).
        ``priority``: queue class — an ``interactive`` request is
        inserted ahead of every queued ``bulk`` request."""
        features = np.asarray(features)
        if features.ndim < 1 or features.shape[0] < 1:
            raise ValueError(
                f"predict features must have a leading batch axis with "
                f">= 1 rows, got shape {features.shape}")
        deadline.check("predict enqueue")
        pending = _Pending(features, deadline, priority_rank(priority))
        with self._cond:
            if self._stopping:
                raise DrainingError("batch scheduler stopped")
            # the model/lock pair travels with the KEY, pinned by the
            # caller for the life of this op: a cache swap mid-queue
            # cannot retarget the request
            self._backends[key] = (model, lock)
            queue = self._queues.setdefault(key, collections.deque())
            priority_insert(queue, pending)
            worker = self._dispatchers.get(key)
            if worker is None or not worker.is_alive():
                worker = threading.Thread(
                    target=self._dispatch_loop, args=(key,), daemon=True,
                    name=f"batch-dispatch-{len(self._dispatchers)}")
                self._dispatchers[key] = worker
                worker.start()
            self._cond.notify_all()
        while not pending.event.is_set():
            remaining = deadline.remaining()
            timeout = 5.0 if remaining is None else max(0.0,
                                                        remaining) + 0.05
            if pending.event.wait(timeout):
                break
            # budget gone while the batch is still in flight: report
            # DEADLINE now; the dispatcher completes (and discards) the
            # orphan later. No-deadline requests loop until completion.
            deadline.check("predict batched dispatch")
        if pending.error is not None:
            raise pending.error
        if pending.result is None:  # stop() raced the wait
            raise DrainingError("batch scheduler stopped")
        return pending.result

    # ----------------------------------------------------------- dispatcher
    def _dispatch_loop(self, key: str) -> None:
        idle_until = time.monotonic() + self.idle_thread_s
        while True:
            with self._cond:
                queue = self._queues.get(key)
                while not self._stopping and not queue:
                    left = idle_until - time.monotonic()
                    if left <= 0:
                        # nothing queued for a while: retire the thread
                        # and its empty queue (a later submit recreates
                        # both — without this a long-lived server leaks
                        # a deque per model key ever served)
                        if (self._dispatchers.get(key)
                                is threading.current_thread()):
                            del self._dispatchers[key]
                            if not self._queues.get(key):
                                self._queues.pop(key, None)
                        return
                    self._cond.wait(left)
                    queue = self._queues.get(key)
                if self._stopping:
                    # queue may be None here: stop() can race the idle
                    # retirement above (another pass popped the deque
                    # between our wait and this re-fetch)
                    for p in (queue or ()):
                        p.error = DrainingError("batch scheduler stopped")
                        p.event.set()
                    if queue is not None:
                        queue.clear()
                    return
                batch, reason = self._form_batch(queue)
            try:
                self._execute(key, batch, reason)
            except Exception as e:  # noqa: BLE001 — the dispatcher must
                # survive anything: a dead dispatcher would strand every
                # queued request behind a still-alive-looking thread
                for p in batch:
                    if not p.event.is_set():
                        p.error = e
                        p.event.set()
            idle_until = time.monotonic() + self.idle_thread_s

    def _form_batch(self, queue) -> Tuple[List[_Pending], str]:
        """Collect one flushable batch from ``queue`` (held lock).
        Blocks on the condition while the flush conditions say wait."""
        while True:
            head = queue[0]
            batch, rows = [], 0
            for p in queue:
                if p.shape_key != head.shape_key:
                    continue  # different feature shape: a later batch
                if batch and rows + p.rows > self.max_batch:
                    break  # bucket capacity; an oversize HEAD runs alone
                batch.append(p)
                rows += p.rows
            if rows >= self.max_batch:
                reason = "full"
            else:
                now = time.monotonic()
                wait_idle = (head.t0 + self.max_wait_s) - now
                wait_deadline = float("inf")
                for p in batch:
                    remaining = p.deadline.remaining()
                    if remaining is not None:
                        wait_deadline = min(
                            wait_deadline,
                            remaining - self.deadline_margin_s)
                wait = min(wait_idle, wait_deadline)
                if wait > 0:
                    self._cond.wait(wait)
                    if self._stopping:
                        # the outer loop fails the queue; flush nothing
                        return [], "idle"
                    continue  # re-collect: new arrivals may have landed
                reason = "deadline" if wait_deadline < wait_idle else "idle"
            for p in batch:
                queue.remove(p)
            return batch, reason

    # ------------------------------------------------------------ execution
    def _execute(self, key: str, batch: List[_Pending],
                 reason: str) -> None:
        # members whose WHOLE budget is already gone get DEADLINE
        # without paying for execution (their submitters have raised
        # and left — running the step would burn exactly the backend
        # capacity an overloaded server needs to recover). No counter
        # here: the submitter's own deadline.check already counted.
        live = []
        for p in batch:
            if p.deadline.expired():
                p.error = DeadlineExceeded("predict: batch member "
                                           "expired before dispatch")
                p.event.set()
            else:
                live.append(p)
        batch = live
        if not batch:
            return
        with self._cond:
            backend = self._backends.get(key)
        if backend is None:
            # every queued request pins its model, so a missing backend
            # means only orphans remained and the LRU moved on — fail
            # them cleanly instead of KeyError-ing the dispatcher
            for p in batch:
                p.error = DrainingError(f"model {key!r} evicted with "
                                        "only abandoned requests queued")
                p.event.set()
            return
        model, lock = backend
        rows = sum(p.rows for p in batch)
        bucket = bucket_rows(rows)
        shape_key = batch[0].shape_key
        tracer = get_tracer()
        with tracer.span("serve:batch", model=key, size=len(batch),
                         rows=rows, bucket=bucket, reason=reason):
            # slow_batch chaos seam: stall THIS batch (outside every
            # lock — a stalled batch must not freeze the scheduler)
            faultinject.on_batch_dispatch(key)
            x = np.concatenate([p.features for p in batch], axis=0)
            if bucket > rows:
                pad = np.zeros((bucket - rows,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            try:
                runner = self._runner(key, model, bucket, shape_key)
                with lock:  # predict and fit on one model never interleave
                    y = np.asarray(runner(model, x))[:rows]
            except Exception:  # noqa: BLE001 — isolate batchmates
                # batch-level failure (compile error, backend fault):
                # re-execute each request ALONE before surfacing
                # anything — only a request that fails by itself may
                # charge the caller's circuit breaker
                get_registry().counter(
                    "serving_batch_fallbacks_total",
                    help="batches that fell back to singleton "
                         "re-execution after a batch-level failure").inc()
                self._singleton_fallback(model, lock, batch)
                self._account(batch, reason)
                return
            offset = 0
            for p in batch:
                self._finish_rows(p, y[offset:offset + p.rows])
                offset += p.rows
        self._account(batch, reason)

    def _singleton_fallback(self, model, lock,
                            batch: List[_Pending]) -> None:
        for p in batch:
            try:
                with lock:
                    y = np.asarray(model.output(p.features))
                self._finish_rows(p, y)
            except Exception as e:  # noqa: BLE001 — per-request verdict
                p.error = e
                p.event.set()

    def _finish_rows(self, p: _Pending, y: np.ndarray) -> None:
        """Per-ROW sentinel: a poisoned request fails alone — its
        batchmates' rows are served."""
        from deeplearning4j_tpu.resilience.sentinel import host_nonfinite
        if host_nonfinite(y):
            get_registry().counter(
                "serving_nonfinite_outputs_total",
                help="predictions refused because the model output "
                     "carried NaN/Inf").inc()
            p.error = NonFiniteOutput("prediction contains NaN/Inf")
        else:
            p.result = y
        p.event.set()

    def _account(self, batch: List[_Pending], reason: str) -> None:
        reg = get_registry()
        reg.histogram("serving_batch_size",
                      help="requests coalesced per executed batch",
                      buckets=BATCH_SIZE_BUCKETS).observe(len(batch))
        reg.counter("serving_batched_requests_total",
                    help="predict requests served through the "
                         "batching scheduler").inc(len(batch))
        self._flush_counter(reason).inc()
        with self._stats_lock:
            self._batch_sizes[len(batch)] += 1
            rows = sum(p.rows for p in batch)
            self._bucket_mix[(batch[0].shape_key,
                              bucket_rows(rows))] += 1

    # ------------------------------------------------------- compile cache
    def _runner(self, key: str, model, bucket: int, shape_key):
        """The AOT-compiled step for (model key, bucket, feature shape)
        — compiled once, reused until the model is evicted. Runners
        take ``(model, x)``: the executable binds only SHAPES, never a
        model object, so a fit or an evict-and-reload of the same key
        can never serve stale weights from a cache hit. Falls back to
        the model's own jitted ``output`` when the container exposes no
        AOT seam (jit still caches per shape: one trace per bucket)."""
        cache_key = (self._cache_owner, key, bucket, shape_key)
        runner = self._compiled.get(cache_key)
        if runner is not None:
            return runner
        t0 = time.perf_counter()
        runner, nbytes = self._aot_compile(model, bucket, shape_key)
        if runner is None:
            runner, nbytes = (lambda m, x: m.output(x)), 0  # noqa: E731
        elapsed = time.perf_counter() - t0
        get_registry().counter(
            "serving_compile_seconds_total",
            help="seconds spent AOT-compiling per-bucket predict "
                 "steps").inc(elapsed)
        with self._stats_lock:
            self.compile_s += elapsed
        with self._cond:
            current = self._backends.get(key)
            if current is not None and current[0] is model:
                # put UNDER the cond: an evict_model racing between the
                # check and the put could otherwise land a stale
                # executable for a gone model (the cache's own lock is
                # a leaf — no path nests it around the cond)
                self._compiled.put(cache_key, runner, nbytes)
            # else: the key was evicted (or swapped to a fresh load)
            # while we compiled — serve this batch with the uncached
            # runner and let the next batch compile against the
            # current object, rather than caching for a gone model
        return runner

    @staticmethod
    def _aot_compile(model, bucket: int, shape_key):
        """``jit(infer).lower(spec).compile()`` against the container's
        cached jitted inference forward; params/states remain call
        arguments so fit updates keep the executable valid. Returns
        ``(runner, compiled_bytes)`` — the bytes charge the cross-model
        compile-cache budget."""
        import jax

        shape, dtype = shape_key
        spec = jax.ShapeDtypeStruct((bucket,) + tuple(shape), dtype)
        try:
            jitted = model._infer_fn()
            if hasattr(model, "layers"):  # MultiLayerNetwork
                compiled = jitted.lower(model.params, model.states,
                                        spec, None).compile()
                return (lambda m, x: compiled(m.params, m.states,
                                              x, None),
                        CompileCache.compiled_nbytes(compiled))
            # ComputationGraph: dict input map, list of outputs
            name = model.conf.network_inputs[0]
            compiled = jitted.lower(model.params, model.states,
                                    {name: spec}, None).compile()
            return (lambda m, x: compiled(m.params, m.states,
                                          {name: x}, None)[0],
                    CompileCache.compiled_nbytes(compiled))
        except Exception:  # noqa: BLE001 — AOT is an optimization
            return None, 0

    # ----------------------------------------------------------- prewarming
    def prewarm(self, key: str, model, top: int = 4) -> int:
        """Speculatively AOT-compile the ``top`` most-observed
        (feature shape, bucket) combinations for a freshly loaded
        model, so the first real wave against it pays zero compiles.
        The signal is the scheduler's OBSERVED request-size mix across
        every model it has served (traffic shape is a gateway property,
        not a model property). Returns the number of buckets compiled;
        call from a background thread — compiles are slow."""
        with self._stats_lock:
            mix = self._bucket_mix.most_common()
        done = 0
        # pin the backend so _runner may cache against it — but
        # remember OUR insertion: if the server LRU evicts this model
        # while we compile and no request re-registers it, the pin
        # must come back out or the dead model object leaks in
        # _backends forever
        pin = (model, threading.Lock())
        with self._cond:
            if self._stopping:
                return 0
            pinned = key not in self._backends
            if pinned:
                self._backends[key] = pin
        try:
            for (shape_key, bucket), _ in mix:
                if done >= top:
                    break
                cache_key = (self._cache_owner, key, bucket, shape_key)
                if self._compiled.get(cache_key) is not None:
                    continue
                with self._cond:
                    if self._stopping:
                        break
                try:
                    self._runner(key, model, bucket, shape_key)
                except Exception:  # noqa: BLE001 — speculative
                    continue
                done += 1
        finally:
            if pinned:
                with self._cond:
                    if (self._backends.get(key) is pin
                            and not self._queues.get(key)):
                        self._backends.pop(key)
        if done:
            get_registry().counter(
                "serving_prewarmed_buckets_total",
                help="AOT buckets compiled speculatively from the "
                     "observed request-size mix").inc(done)
        return done

    # ------------------------------------------------------------ lifecycle
    def evict_model(self, key: str) -> None:
        """Drop the compiled-step cache for an evicted model — the AOT
        cache is keyed like the server's LRU and dies with it. Purge
        and backend-pop happen under ONE cond hold so they serialize
        against _runner's check-and-put (purging first would let a
        concurrent put re-land a stale executable)."""
        with self._cond:
            self._compiled.evict_model(self._cache_owner, key)
            self._backends.pop(key, None)
            if not self._queues.get(key):  # drop the empty deque too
                self._queues.pop(key, None)

    def stop(self, grace_s: float = 5.0) -> None:
        """Fail queued work with DRAINING, wake and join dispatchers;
        release this scheduler's slice of the global compile cache."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
            workers = list(self._dispatchers.values())
        for w in workers:
            w.join(grace_s)
        self._compiled.evict_owner(self._cache_owner)

    def stats(self) -> dict:
        """Per-scheduler serve stats (the bench serve rung's record)."""
        p50, p99 = self.latency.quantiles()
        with self._stats_lock:
            return {
                "compile_s": round(self.compile_s, 3),
                "batch_size_mix": {str(k): v for k, v in
                                   sorted(self._batch_sizes.items())},
                "p50_ms": None if p50 is None else round(p50 * 1000, 2),
                "p99_ms": None if p99 is None else round(p99 * 1000, 2),
            }
