"""Measured probes: a few REAL compiled steps per shortlisted candidate.

The analytic model ranks; the probe decides. Each probe builds a FRESH
net from the model's own configuration (same seed — deterministic
init), wraps it in a ``ParallelTrainer`` constructed from the
candidate's ``trainer_kwargs()`` (the exact recipe ``TunedConfig`` uses,
so what is measured is what ships), pays the compile in warmup steps,
then times ``steps`` asynchronously-dispatched steps closed by one
``block_until_ready`` — the same discipline as bench.py's timed loop,
so a probe number and a bench number mean the same thing. Compile time
is reported separately (``compile_s``), never inside the measurement.

Probes never touch the caller's net: parameter state, optimizer state
and RNG all belong to the throwaway probe net.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np


def _one_hot_labels(rng, t, batch_size: int):
    """Deterministic one-hot labels matching one loss head's OUTPUT
    InputType: [B, K] for feed-forward heads, [B, T, K] per-timestep
    for recurrent heads (the LM case)."""
    k = max(2, int(t.size or 2))
    if t.kind == "rnn":
        T = int(t.timesteps or 1)
        return np.eye(k, dtype=np.float32)[
            rng.integers(0, k, (batch_size, T))]
    return np.eye(k, dtype=np.float32)[rng.integers(0, k, batch_size)]


def synthesize_batch(conf, batch_size: int):
    """A deterministic synthetic batch for a shape-resolved config
    (seeded by the conf's own seed).

    MultiLayer configs: random-normal features in the input type's
    example shape, one-hot labels at the loss head's width.

    ComputationGraph configs (ROADMAP item 4d): one feature array per
    ``network_inputs`` entry from the declared ``input_types``, one
    one-hot label array per ``network_outputs`` head from the RESOLVED
    output type — returned as a DataSet for single-input/single-output
    graphs (every trainer path accepts it) and a MultiDataSet
    otherwise, so ``autotune(ComputationGraph(...), ...)`` and
    ``tools/autotune.py`` need no explicit example batch."""
    from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
    rng = np.random.default_rng(int(conf.training.seed))
    if hasattr(conf, "nodes"):  # ComputationGraph configuration
        if not conf.input_types or not conf.resolved_types:
            raise ValueError(
                "cannot synthesize a probe batch: the graph config has "
                "no input_types (call set_input_types(...) at build, or "
                "pass batch= to autotune())")
        feats = []
        for name in conf.network_inputs:
            t = conf.input_types[name]
            feats.append(rng.normal(
                size=(batch_size,) + tuple(t.example_shape())
                ).astype(np.float32))
        labels = [_one_hot_labels(rng, conf.resolved_types[o], batch_size)
                  for o in conf.network_outputs]
        if len(feats) == 1 and len(labels) == 1:
            return DataSet(feats[0], labels[0])
        return MultiDataSet(feats, labels)
    input_type = getattr(conf, "input_type", None)
    if input_type is None:
        raise ValueError(
            "cannot synthesize a probe batch: the config has no "
            "input_type")
    feats = rng.normal(size=(batch_size,) + tuple(
        input_type.example_shape())).astype(np.float32)
    head = conf.layers[-1]
    n_out = int(getattr(head, "n_out", None) or 2)
    labels = np.eye(n_out, dtype=np.float32)[
        rng.integers(0, n_out, batch_size)]
    if input_type.kind == "rnn":
        # recurrent heads emit per-timestep distributions: [B, T, K]
        T = feats.shape[1] if feats.ndim == 3 else 1
        labels = np.eye(n_out, dtype=np.float32)[
            rng.integers(0, n_out, (batch_size, T))]
    return DataSet(feats, labels)


def build_probe_net(net):
    """A fresh, identically-seeded container from ``net``'s config —
    the throwaway model every probe trains instead of the caller's."""
    fresh = type(net)(net.conf)
    fresh.init()
    return fresh


def measure_candidate(net, candidate, batch, steps: int = 3,
                      warmup: int = 1,
                      devices: Optional[list] = None) -> dict:
    """Run one candidate for real and return
    {measured_step_s, compile_s, losses}.

    ``net`` is only the blueprint (config + container class); the
    trained state lives and dies here. ``candidate`` must be probeable
    (pp == 1 — enforced by the tuner's shortlist).
    """
    import jax

    from deeplearning4j_tpu.parallel.mesh import MeshContext
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer

    if not candidate.probeable:
        raise ValueError(f"candidate {candidate.slug()} is not probeable "
                         "(pp > 1 needs the pipeline trainer)")
    probe_net = build_probe_net(net)
    mesh = MeshContext.create(n_data=candidate.dp, n_model=candidate.tp,
                              n_seq=candidate.sp, devices=devices)
    trainer = ParallelTrainer(probe_net, mesh,
                              **candidate.trainer_kwargs())
    t0 = time.perf_counter()
    losses = []
    for _ in range(max(1, warmup)):
        losses.append(trainer.fit_batch(batch))
    jax.block_until_ready(probe_net.params)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(max(1, steps)):
        losses.append(trainer.fit_batch(batch))
    jax.block_until_ready(probe_net.params)
    dt = time.perf_counter() - t0
    return {"measured_step_s": dt / max(1, steps),
            "compile_s": compile_s,
            "losses": [float(np.asarray(l)) for l in losses]}
