"""autotune: cost-model-driven configuration search with measured-probe
validation (ROADMAP item 4; µ-cuDNN arXiv 1804.04806 generalized from
conv microbatch sizes to the whole training configuration).

    from deeplearning4j_tpu.autotune import autotune

    tuned = autotune(net, devices=8, hbm_budget=16 << 30)
    trainer = tuned.trainer(net)            # or ParallelTrainer(net,
    trainer.fit(data)                       #        tuned=tuned)

The search is CPU-provable end to end: enumeration and pruning are pure
metadata, ranking is the analytic cost model, and the probes are short
real compiled steps on whatever backend is attached. See
``tools/autotune.py`` (CLI) and ``tools/autotune_smoke.py`` (the
run_checks gate).
"""

from deeplearning4j_tpu.autotune.config import ProbeRecord, TunedConfig
from deeplearning4j_tpu.autotune.space import (
    Candidate, default_candidate, enumerate_space, mesh_shapes,
    serve_bucket_set,
)
from deeplearning4j_tpu.autotune.tuner import (
    AutotuneError, analytic_search, autotune,
)

__all__ = [
    "autotune", "analytic_search", "AutotuneError",
    "TunedConfig", "ProbeRecord",
    "Candidate", "enumerate_space", "mesh_shapes",
    "default_candidate", "serve_bucket_set",
]
