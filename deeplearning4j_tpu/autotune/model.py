"""Analytic step-time / MFU model the autotuner ranks candidates with.

Built entirely from numbers the repo already predicts — the compiled
step's FLOP census (``profiling/cost.train_step_cost``), the ring-model
collective bytes (``profiling/cost.dp_comm_bytes_per_update``) and the
MemoryReport HBM walk (``analysis/memory``) — composed into one
seconds-per-step estimate per candidate:

    step_s = (compute_s + comm_s) * pipeline_bubble

- ``compute_s``: the program's FLOPs split over the chips that actually
  share the work (dp, tp, pp always split compute; sp splits it only
  when the model has an attention layer to ring over), at the chip's
  matmul rate for the candidate's compute dtype.
- ``comm_s``: the dp gradient exchange (exact ring model, shared with
  BENCH records), plus first-order activation-exchange terms for tp/sp
  and boundary transfers for pp.
- ``pipeline_bubble``: the GPipe factor ``(pp - 1 + m) / m`` with
  ``m = gradient_accumulation`` microbatches.

This is a RANKING model, not a stopwatch: its absolute error is exactly
what the measured probes exist to expose, and the per-config
``measured_vs_predicted_gap`` is the calibration surface
(ROADMAP item 4, SC007's tolerance gate reads the same numbers).

Two census sources feed it:

- :func:`census_from_net` — an initialized container: exact param count
  (memoized, ``profiling/cost.param_census``) and the compiled step's
  real FLOPs (one AOT compile, memoized on batch signature).
- :func:`census_from_conf` — a bare config (graphcheck's GC016 path,
  where compiling would be too heavy): param count from the MemoryReport
  walk and FLOPs estimated at :data:`FLOPS_PER_PARAM` per example.
  Both sides of a GC016 comparison use the same census, so the >2x
  mistuning ratio is self-consistent even where the absolute FLOPs are
  crude.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: fwd+bwd+update FLOPs per parameter per example for the config-only
#: census (2 MAC-FLOPs forward per param, x3 for the backward pair) —
#: the standard dense-model rule of thumb
FLOPS_PER_PARAM = 6.0

#: fraction of a chip's (bf16 MXU) peak that fp32 matmuls achieve on
#: accelerators (the MXU runs half-precision twice as fast)...
ACCEL_FP32_FRACTION = 0.5
#: ...and the inverse on CPU, where half precision is EMULATED: the
#: tuner must never "discover" bf16 speedups a CPU probe then refutes
CPU_HALF_FRACTION = 0.5

#: half-precision compute dtypes (graphcheck's jax-light list)
_HALF = ("bfloat16", "bf16", "float16", "fp16", "half")

#: tensor/sequence parallelism splits compute SUBLINEARLY: per-layer
#: collectives serialize against the matmuls they feed, and kernels
#: whose dims don't divide the axis stay replicated — an N-way tp axis
#: yields ~N^0.75 effective compute shards. Data and pipeline
#: parallelism stay linear (embarrassingly parallel over examples /
#: stages; pp pays its own bubble term instead).
TP_SPLIT_EXPONENT = 0.75


@dataclass(frozen=True)
class Hardware:
    """The per-chip constants the model scales by."""
    peak_flops: float          # dense matmul FLOP/s at the native dtype
    ici_bytes_per_s: float     # per-chip interconnect bandwidth
    is_accelerator: bool = True
    device_kind: str = "unknown"

    def matmul_fraction(self, precision: str) -> float:
        half = str(precision or "fp32").lower() in _HALF
        if self.is_accelerator:
            return 1.0 if half else ACCEL_FP32_FRACTION
        return CPU_HALF_FRACTION if half else 1.0

    @staticmethod
    def detect() -> "Hardware":
        """The current backend's constants. TPU ICI is ~100 GB/s per
        chip per direction on recent generations; the CPU 'mesh' of
        forced host devices exchanges via plain memcpy, modeled at host
        memory bandwidth (50 GB/s) — collectives stay visible in the
        ranking but cannot dominate it the way a real wire would."""
        from deeplearning4j_tpu.profiling.cost import peak_flops
        try:
            import jax
            dev = jax.devices()[0]
            kind = str(getattr(dev, "device_kind", dev.platform))
            accel = dev.platform not in ("cpu",)
        except Exception:  # noqa: BLE001 — model must work chip-less
            kind, accel = "cpu", False
        return Hardware(
            peak_flops=peak_flops(kind) or 1e12,
            ici_bytes_per_s=100e9 if accel else 50e9,
            is_accelerator=accel, device_kind=kind)

    @staticmethod
    def reference() -> "Hardware":
        """Fixed machine-independent constants (the CPU profile) — what
        graphcheck's GC016 compares with, so the same config gets the
        same verdict on every box and the validator never initializes a
        jax backend. The tuner proper uses :meth:`detect` — its probes
        measure the real machine anyway."""
        return Hardware(peak_flops=1e12, ici_bytes_per_s=50e9,
                        is_accelerator=False, device_kind="reference")


@dataclass
class ModelCensus:
    """Everything the analytic model needs to know about ONE model.

    Built ONCE per search (one shape walk, one optional AOT compile);
    every per-candidate prediction then reuses the cached
    ``LayerMemoryEntry`` rows — a MemoryReport per candidate costs dict
    math, never another ``eval_shape`` walk."""
    conf: object
    param_count: int
    flops_per_example: float
    dtype_bytes: int = 4
    mem_dtype: str = "float32"
    updater: str = "sgd"
    has_attention: bool = False
    n_layers: int = 1
    #: pre-walked LayerMemoryEntry rows (analysis/memory) — batch- and
    #: layout-independent, so one walk serves every candidate
    entries: List = field(default_factory=list)

    @property
    def activation_elems_per_example(self) -> int:
        return sum(e.activation_elems for e in self.entries)

    def memory_report_at(self, batch_size: int,
                         weight_update_sharding: str, dp: int):
        """A MemoryReport at one candidate's layout, from the cached
        entries (no re-walk)."""
        from deeplearning4j_tpu.analysis.memory import MemoryReport
        return MemoryReport(
            entries=self.entries, batch_size=max(1, int(batch_size)),
            dtype=self.mem_dtype, updater=self.updater,
            remat=getattr(self.conf.training, "remat", False),
            weight_update_sharding=weight_update_sharding,
            dp=max(1, int(dp)))


def _base_census(conf, walk: Optional[List[Tuple]] = None) -> ModelCensus:
    from deeplearning4j_tpu.analysis.graphcheck import iter_config_layers
    from deeplearning4j_tpu.analysis.memory import memory_report
    if walk is None:
        walk = list(iter_config_layers(conf))
    rep = memory_report(conf, batch_size=1, layers=walk)
    return ModelCensus(
        conf=conf, param_count=rep.total_params,
        flops_per_example=FLOPS_PER_PARAM * max(rep.total_params, 1),
        mem_dtype=rep.dtype, updater=rep.updater,
        has_attention=any("Attention" in type(l).__name__
                          for _, l, _ in walk),
        n_layers=max(1, len(walk)), entries=rep.entries)


def census_from_conf(conf, walk: Optional[List[Tuple]] = None
                     ) -> ModelCensus:
    """Config-only census (no net, no compile): the GC016 path. FLOPs
    are the :data:`FLOPS_PER_PARAM` estimate — crude absolutely, but
    identical on both sides of any comparison made with it."""
    return _base_census(conf, walk)


def census_from_net(net, batch) -> ModelCensus:
    """Census from an initialized container: exact params (memoized,
    ``profiling/cost.param_census``) and the compiled step's REAL
    per-example FLOPs (one AOT compile, memoized on batch signature)."""
    from deeplearning4j_tpu.profiling.cost import (param_census,
                                                   train_step_cost)
    census = _base_census(net.conf)
    pc = param_census(net)
    census.param_count = pc["param_count"]
    census.dtype_bytes = pc["dtype_bytes"]
    census.updater = pc["updater"]
    flops_ex = None
    try:
        flops_ex = train_step_cost(net, batch).get("flops_per_example")
    except Exception:  # noqa: BLE001 — fall back to the param estimate
        pass
    census.flops_per_example = float(
        flops_ex or FLOPS_PER_PARAM * max(census.param_count, 1))
    return census


def predict(census: ModelCensus, cand, global_batch: int,
            hardware: Optional[Hardware] = None) -> Dict[str, float]:
    """Analytic cost of one :class:`~deeplearning4j_tpu.autotune.space.
    Candidate`: {step_s, compute_s, comm_s, bubble, hbm_bytes, mfu}.
    Deterministic — same inputs, same floats."""
    hw = hardware or Hardware.detect()
    B = max(1, int(global_batch))
    dp, tp, pp, sp = cand.dp, cand.tp, cand.pp, cand.sp
    accum = max(1, cand.gradient_accumulation)

    # -- compute: FLOPs split over the chips that share them (tp/sp
    # split sublinearly — see TP_SPLIT_EXPONENT; sp splits nothing when
    # the model has no attention layer to ring over, so those chips
    # idle and the candidate ranks accordingly)
    sp_effective = sp if census.has_attention else 1
    compute_shards = (dp * pp * tp ** TP_SPLIT_EXPONENT
                      * sp_effective ** TP_SPLIT_EXPONENT)
    rate = hw.peak_flops * hw.matmul_fraction(cand.precision)
    compute_s = (census.flops_per_example * B) / (compute_shards * rate)

    # -- communication (per step, per chip, ring model)
    from deeplearning4j_tpu.profiling.cost import dp_comm_bytes_per_update
    local_params = census.param_count // max(1, tp * pp)
    comm_bytes = dp_comm_bytes_per_update(
        local_params, dp, 4,  # gradients exchange in fp32 on every policy
        gradient_accumulation=accum,
        weight_update_sharding=cand.weight_update_sharding)
    compute_dtype_bytes = (2 if str(cand.precision).lower() in _HALF
                           else census.dtype_bytes)
    act_bytes = (census.activation_elems_per_example * (B // max(1, dp))
                 * compute_dtype_bytes)
    if tp > 1:   # fwd + bwd activation exchange per layer boundary
        comm_bytes += 2 * act_bytes * (tp - 1) // tp
    if sp_effective > 1:  # ring attention: one KV rotation each way
        comm_bytes += act_bytes * (sp_effective - 1) // sp_effective
    if pp > 1:   # microbatch boundary activations between stages
        comm_bytes += 2 * (pp - 1) * (act_bytes // census.n_layers)
    comm_s = comm_bytes / hw.ici_bytes_per_s

    # -- GPipe bubble
    bubble = (pp - 1 + accum) / accum if pp > 1 else 1.0
    step_s = (compute_s + comm_s) * bubble

    # -- per-chip HBM at this layout (MemoryReport from the cached
    # entries): the params/grads/updater terms additionally divide over
    # tp*pp (each chip holds only its kernel/stage shard); activations
    # scale with the per-microbatch slice and the compute dtype
    micro = max(1, B // max(1, dp * accum))
    rep = census.memory_report_at(
        micro, cand.weight_update_sharding, dp)
    model_shards = max(1, tp * pp)
    hbm = (-(-(rep.param_bytes + rep.gradient_bytes
               + rep.updater_state_bytes) // model_shards)
           + rep.activation_bytes * compute_dtype_bytes
           // max(1, census.dtype_bytes))

    # MFU charges every chip of the mesh, idle or not — a shape that
    # parks devices shows the honest utilization loss
    mfu = (census.flops_per_example * B / cand.devices
           / (step_s * hw.peak_flops)) if step_s > 0 else 0.0
    return {"step_s": step_s, "compute_s": compute_s, "comm_s": comm_s,
            "bubble": bubble, "hbm_bytes": int(hbm),
            "comm_bytes_per_step": int(comm_bytes), "mfu": mfu}
