"""The search loop: enumerate -> prune -> rank -> probe -> TunedConfig.

``autotune(net, devices=..., hbm_budget=...)`` closes the loop the cost
model opened (ROADMAP item 4): given a model, a device count, and an
HBM budget, the system picks its own configuration —

1. **Enumerate** the structural space (``autotune/space``): every
   dp x tp x pp x sp factorization of the device count, crossed with
   gradient-accumulation, precision preset, and weight-update-sharding
   choices.
2. **Prune** with the validators the repo already trusts: any candidate
   whose ``analysis.graphcheck.validate_config`` run produces an ERROR
   finding is out (GC008/GC010/GC011/GC015 are reused as hard
   constraints, never re-implemented — legality is memoized per
   (mesh, wus, precision) because accumulation cannot change it), and
   any candidate whose analytic per-chip HBM exceeds the budget is out
   (the MemoryReport estimate, same walk graphcheck uses).
3. **Rank** survivors by the analytic step-time model
   (``autotune/model``), deterministically (ties break toward the
   simplest shape).
4. **Probe** the top-K probeable candidates — plus the naive default
   config (``MeshContext.create()``'s all-devices dp) — with a few REAL
   compiled steps (``autotune/probe``). The winner is the best MEASURED
   candidate, so the tuner can never ship a config that measures slower
   than the default it was asked to beat.
5. Emit a :class:`~deeplearning4j_tpu.autotune.config.TunedConfig`
   carrying the choice AND the per-config
   ``measured_vs_predicted_gap`` — the calibration surface, exported as
   ``autotune_*`` metrics on ``/api/metrics`` and persisted in bench
   records (``BENCH_AUTOTUNE=1``).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.autotune import model as cost_model
from deeplearning4j_tpu.autotune import space as cfg_space
from deeplearning4j_tpu.autotune.config import ProbeRecord, TunedConfig
from deeplearning4j_tpu.autotune.space import Candidate

logger = logging.getLogger(__name__)


class AutotuneError(ValueError):
    """No legal configuration survived pruning (or probing failed in a
    way that leaves nothing to choose)."""


def _resolve_devices(devices):
    """(device_list_or_None, count) from None / int / a device list."""
    import jax
    if devices is None:
        return None, jax.device_count()
    if isinstance(devices, int):
        if devices < 1:
            raise AutotuneError(f"devices must be >= 1, got {devices}")
        return list(jax.devices())[:devices], devices
    devices = list(devices)
    return devices, len(devices)


def legal_findings(conf, candidate: Candidate, global_batch: int,
                   _cache: Optional[dict] = None):
    """graphcheck's verdict on one candidate (the ERROR findings that
    make it illegal). Memoized on (mesh, wus, precision) — the only
    knobs the rules read; gradient accumulation cannot change legality,
    so a 100-config sweep runs the validator once per distinct layout,
    not once per candidate."""
    from deeplearning4j_tpu.analysis.findings import Severity
    from deeplearning4j_tpu.analysis.graphcheck import validate_config
    key = (tuple(sorted(candidate.mesh_axes.items())),
           candidate.weight_update_sharding, candidate.precision)
    if _cache is not None and key in _cache:
        return _cache[key]
    findings = [f for f in validate_config(
        conf, mesh=candidate.mesh_axes, batch_size=global_batch,
        weight_update_sharding=candidate.weight_update_sharding,
        precision=candidate.precision)
        if f.severity == Severity.ERROR]
    if _cache is not None:
        _cache[key] = findings
    return findings


def analytic_search(census, n_devices: int, global_batch: int,
                    hbm_budget: Optional[int] = None,
                    accum_choices: Sequence[int] = cfg_space.DEFAULT_ACCUM,
                    precisions: Sequence[str] = cfg_space.DEFAULT_PRECISIONS,
                    wus_modes: Sequence[str] = cfg_space.DEFAULT_WUS_MODES,
                    hardware: Optional[cost_model.Hardware] = None,
                    ) -> Tuple[List[Tuple[Candidate, dict]], Dict[str, int]]:
    """Enumerate + prune + rank. Returns (ranked survivors as
    (candidate, predicted-cost dict) best first, prune counters).
    Shared by :func:`autotune` and graphcheck's GC016 rule, so the
    validator's notion of "the best legal config" IS the tuner's."""
    from deeplearning4j_tpu.analysis.memory import DEFAULT_HBM_BYTES
    budget = hbm_budget or DEFAULT_HBM_BYTES
    hw = hardware or cost_model.Hardware.detect()
    legality_cache: dict = {}
    counters = {"candidates": 0, "pruned_illegal": 0, "pruned_hbm": 0}
    survivors: List[Tuple[Candidate, dict]] = []
    for cand in cfg_space.enumerate_space(
            n_devices, global_batch, accum_choices=accum_choices,
            precisions=precisions, wus_modes=wus_modes):
        counters["candidates"] += 1
        if legal_findings(census.conf, cand, global_batch,
                          _cache=legality_cache):
            counters["pruned_illegal"] += 1
            continue
        predicted = cost_model.predict(census, cand, global_batch,
                                       hardware=hw)
        if predicted["hbm_bytes"] > budget:
            counters["pruned_hbm"] += 1
            continue
        survivors.append((cand, predicted))
    survivors.sort(key=lambda cp: (cp[1]["step_s"], cp[0].sort_key()))
    return survivors, counters


def analytic_best(census, n_devices: int, global_batch: int,
                  hbm_budget: Optional[int] = None,
                  hardware: Optional[cost_model.Hardware] = None
                  ) -> Optional[Tuple[Candidate, dict]]:
    """The best LEGAL candidate by prediction alone — graphcheck's
    GC016 path. Ranks the whole structural space analytically (cheap:
    dict math per candidate), then walks down the ranking running the
    validator only until the first legal config, so the mistuning rule
    costs a handful of validator passes instead of one per layout."""
    from deeplearning4j_tpu.analysis.memory import DEFAULT_HBM_BYTES
    budget = hbm_budget or DEFAULT_HBM_BYTES
    hw = hardware or cost_model.Hardware.detect()
    ranked = sorted(
        ((cand, cost_model.predict(census, cand, global_batch,
                                   hardware=hw))
         for cand in cfg_space.enumerate_space(n_devices, global_batch)),
        key=lambda cp: (cp[1]["step_s"], cp[0].sort_key()))
    cache: dict = {}
    for cand, predicted in ranked:
        if predicted["hbm_bytes"] > budget:
            continue
        if not legal_findings(census.conf, cand, global_batch,
                              _cache=cache):
            return cand, predicted
    return None


def autotune(net, devices=None, hbm_budget: Optional[int] = None,
             batch=None, global_batch: Optional[int] = None,
             accum_choices: Sequence[int] = cfg_space.DEFAULT_ACCUM,
             precisions: Sequence[str] = cfg_space.DEFAULT_PRECISIONS,
             wus_modes: Sequence[str] = cfg_space.DEFAULT_WUS_MODES,
             top_k: int = 3, probe_steps: int = 3, probe_warmup: int = 1,
             include_default: bool = True,
             probe_fn=None) -> TunedConfig:
    """Pick the configuration for ``net`` on ``devices`` chips within
    ``hbm_budget`` bytes per chip. Returns a
    :class:`~deeplearning4j_tpu.autotune.config.TunedConfig` the
    trainers and the serving gateway accept directly (``tuned=``).

    ``batch``: an example DataSet for the FLOP census and the probes
    (synthesized deterministically from the config when omitted — for
    BOTH config kinds: graph configs synthesize per-input features and
    per-head one-hot labels from their declared/resolved types, a
    MultiDataSet when the graph is multi-input/-output).
    ``global_batch``: the training batch size the search plans for
    (default: the example batch's row count).
    ``top_k``: how many analytically-best candidates get a measured
    probe; 0 skips probing entirely (analytic winner, no calibration).
    ``probe_fn``: measurement injection seam (tests) — same signature
    and return shape as ``autotune.probe.measure_candidate``.
    """
    from deeplearning4j_tpu.autotune import probe as probe_mod
    from deeplearning4j_tpu.profiling.metrics import get_registry

    t_start = time.perf_counter()
    device_list, n_devices = _resolve_devices(devices)
    if batch is None:
        batch = probe_mod.synthesize_batch(net.conf,
                                           int(global_batch or 32))
    B = int(global_batch or batch.num_examples())
    if batch.num_examples() != B:
        # probes train `batch`, but legality/prediction/selection plan
        # for B — a mismatch would measure one workload while choosing
        # for another, so every gap (and the winner) would be fiction
        raise AutotuneError(
            f"example batch has {batch.num_examples()} rows but "
            f"global_batch={B}; pass a batch of exactly the planned "
            "size (or omit one of the two)")
    census = cost_model.census_from_net(net, batch)
    hw = cost_model.Hardware.detect()
    survivors, counters = analytic_search(
        census, n_devices, B, hbm_budget=hbm_budget,
        accum_choices=accum_choices, precisions=precisions,
        wus_modes=wus_modes, hardware=hw)
    if not survivors:
        raise AutotuneError(
            f"no legal configuration for {n_devices} device(s), "
            f"batch {B}, hbm_budget={hbm_budget}: "
            f"{counters['pruned_illegal']} illegal, "
            f"{counters['pruned_hbm']} over budget "
            f"of {counters['candidates']} candidates")

    # -- shortlist: top-K probeable + the naive default (the baseline
    # the winner must not lose to). Unprobeable analytic leaders (pp>1)
    # are counted, logged, and ranked on prediction alone.
    by_cand = {c: p for c, p in survivors}
    shortlist: List[Candidate] = []
    unprobeable = 0
    for cand, _ in survivors:
        if len(shortlist) >= max(0, top_k):
            break
        if not cand.probeable:
            unprobeable += 1
            continue
        shortlist.append(cand)
    if include_default and top_k > 0:
        default = cfg_space.default_candidate(n_devices, B)
        if default in by_cand and default not in shortlist:
            shortlist.append(default)
    if unprobeable:
        logger.info("autotune: %d analytically-ranked candidate(s) "
                    "not probeable (pp > 1); ranked on prediction only",
                    unprobeable)

    # -- probes: measure, record the gap per config
    measure = probe_fn or probe_mod.measure_candidate
    probes: List[Tuple[Candidate, ProbeRecord]] = []
    reg = get_registry()
    for cand in shortlist:
        predicted = by_cand[cand]["step_s"]
        try:
            m = measure(net, cand, batch, steps=probe_steps,
                        warmup=probe_warmup, devices=device_list)
        except Exception as e:  # noqa: BLE001 — one bad probe must not
            logger.warning("autotune: probe %s failed: %s",  # kill the run
                           cand.slug(), e)
            continue
        measured = float(m["measured_step_s"])
        gap = measured / predicted if predicted > 0 else float("inf")
        rec = ProbeRecord(config=cand.slug(),
                          predicted_step_s=predicted,
                          measured_step_s=measured,
                          measured_vs_predicted_gap=gap,
                          compile_s=float(m.get("compile_s", 0.0)))
        probes.append((cand, rec))
        reg.gauge(f"autotune_gap_{cand.slug()}",
                  help="measured/predicted step time of one probed "
                       "config (cost-model calibration)").set(gap)

    # -- winner: best measured when probes ran, else analytic best
    if probes:
        winner, winner_rec = min(
            probes, key=lambda cr: (cr[1].measured_step_s,
                                    cr[0].sort_key()))
    else:
        if top_k > 0:
            logger.warning("autotune: no probe completed; falling back "
                           "to the analytic winner uncalibrated")
        winner, winner_rec = survivors[0][0], None
    predicted = by_cand[winner]

    counters["probes"] = len(probes)
    counters["unprobeable"] = unprobeable
    counters["survivors"] = len(survivors)
    tuned = TunedConfig(
        dp=winner.dp, tp=winner.tp, pp=winner.pp, sp=winner.sp,
        gradient_accumulation=winner.gradient_accumulation,
        precision=winner.precision,
        weight_update_sharding=winner.weight_update_sharding,
        global_batch=B, device_count=n_devices,
        hbm_budget_bytes=hbm_budget,
        serve_buckets=cfg_space.serve_bucket_set(B),
        predicted_step_s=predicted["step_s"],
        measured_step_s=(winner_rec.measured_step_s
                         if winner_rec else None),
        measured_vs_predicted_gap=(winner_rec.measured_vs_predicted_gap
                                   if winner_rec else None),
        predicted_hbm_bytes=predicted["hbm_bytes"],
        predicted_mfu=predicted["mfu"],
        probes=[rec for _, rec in probes],
        search=dict(counters))

    # -- observability: the search and its calibration on /api/metrics
    reg.counter("autotune_searches_total",
                help="autotune() runs completed").inc()
    reg.counter("autotune_candidates_total",
                help="configurations enumerated across searches"
                ).inc(counters["candidates"])
    reg.counter("autotune_pruned_illegal_total",
                help="candidates rejected by graphcheck legality"
                ).inc(counters["pruned_illegal"])
    reg.counter("autotune_pruned_hbm_total",
                help="candidates rejected by the HBM budget"
                ).inc(counters["pruned_hbm"])
    reg.counter("autotune_probes_total",
                help="measured probes executed").inc(len(probes))
    reg.gauge("autotune_best_predicted_step_s",
              help="winner's analytic seconds/step"
              ).set(predicted["step_s"])
    if winner_rec is not None:
        reg.gauge("autotune_best_measured_step_s",
                  help="winner's measured probe seconds/step"
                  ).set(winner_rec.measured_step_s)
        reg.gauge("autotune_measured_vs_predicted_gap",
                  help="winner's measured/predicted step-time ratio "
                       "(the cost-model calibration headline)"
                  ).set(winner_rec.measured_vs_predicted_gap)
    logger.info("autotune: %s in %.1fs (%s)", winner.slug(),
                time.perf_counter() - t_start,
                ", ".join(f"{k}={v}" for k, v in sorted(counters.items())))
    return tuned
