"""Configuration search space: the legal knobs the autotuner sweeps.

A :class:`Candidate` is one complete training configuration — mesh shape
(dp x tp x pp x sp over ALL devices), microbatching
(``gradient_accumulation``), precision preset, and
``weight_update_sharding`` mode — in the exact vocabulary the trainers
take, so a candidate is constructible without translation
(:meth:`Candidate.trainer_kwargs`).

:func:`enumerate_space` is pure combinatorics; it applies only the
constraints that are STRUCTURAL (the mesh must use every device, the
microbatch split must divide the per-replica batch — the trainer's own
``B % accum`` trace-time requirement). Everything graphcheck already
rules on (dp divisibility GC008, zero1/zero2 mesh legality GC011,
precision legality GC015, elastic plans GC014) is deliberately NOT
re-implemented here: the tuner prunes candidates by running
``analysis.graphcheck.validate_config`` and discarding any candidate
with an ERROR finding (see ``autotune/tuner.py``), so the search can
never disagree with the validator about what is legal.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Sequence, Tuple

#: precision presets the sweep considers by default (fp16 needs a loss
#: scale to be safe and is opt-in via ``precisions=``)
DEFAULT_PRECISIONS = ("fp32", "bf16")

#: weight-update layouts the sweep considers (parallel.mesh
#: WeightUpdateSharding.MODES minus nothing — all three are probe-able)
DEFAULT_WUS_MODES = ("off", "zero1", "zero2")

#: gradient-accumulation (microbatch) choices
DEFAULT_ACCUM = (1, 2, 4)

#: serving bucket sets never exceed this many rows per compiled bucket
SERVE_MAX_BATCH_CAP = 128


@dataclass(frozen=True)
class Candidate:
    """One point of the search space, in trainer vocabulary."""
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    gradient_accumulation: int = 1
    precision: str = "fp32"
    weight_update_sharding: str = "off"

    @property
    def devices(self) -> int:
        return self.dp * self.tp * self.pp * self.sp

    @property
    def mesh_axes(self) -> Dict[str, int]:
        """The dict form graphcheck's ``mesh=`` kwarg takes."""
        axes = {"dp": self.dp}
        if self.tp > 1:
            axes["tp"] = self.tp
        if self.pp > 1:
            axes["pp"] = self.pp
        if self.sp > 1:
            axes["sp"] = self.sp
        return axes

    @property
    def probeable(self) -> bool:
        """True when ``ParallelTrainer`` can run this candidate as one
        SPMD step (pp > 1 needs the pipeline trainer's schedule and is
        ranked analytically only)."""
        return self.pp == 1

    def slug(self) -> str:
        """Stable metric/log key: ``dp2_ga4_bf16_zero1`` (axes at 1 and
        defaults omitted so the common shapes stay readable)."""
        parts = [f"dp{self.dp}"]
        for name in ("tp", "pp", "sp"):
            v = getattr(self, name)
            if v > 1:
                parts.append(f"{name}{v}")
        parts.append(f"ga{self.gradient_accumulation}")
        parts.append(self.precision)
        parts.append(self.weight_update_sharding)
        return "_".join(parts)

    def trainer_kwargs(self) -> dict:
        """The ``ParallelTrainer`` kwargs (minus mesh) this candidate
        prescribes — the one construction recipe ``TunedConfig`` and the
        probe harness share, so a tuned trainer and a hand-built one
        cannot drift."""
        return dict(gradient_accumulation=self.gradient_accumulation,
                    weight_update_sharding=self.weight_update_sharding,
                    precision=self.precision)

    def sort_key(self) -> tuple:
        """Deterministic tiebreak for equal predicted step times: prefer
        the simplest shape (pure dp before tp/sp/pp, no accumulation,
        fp32 before half, replicated before sharded updates) — the
        config with the fewest moving parts wins a tie."""
        return (self.pp, self.sp, self.tp,
                self.gradient_accumulation,
                DEFAULT_PRECISIONS.index(self.precision)
                if self.precision in DEFAULT_PRECISIONS else 99,
                DEFAULT_WUS_MODES.index(self.weight_update_sharding)
                if self.weight_update_sharding in DEFAULT_WUS_MODES else 99,
                -self.dp)


def mesh_shapes(n_devices: int) -> List[Tuple[int, int, int, int]]:
    """Every (dp, tp, pp, sp) factorization using EXACTLY ``n_devices``
    chips. Idle chips are never optimal for a fixed fleet, and the naive
    default the tuner measures against (``MeshContext.create()``) also
    uses them all."""
    n = max(1, int(n_devices))
    shapes = []
    for dp in range(1, n + 1):
        if n % dp:
            continue
        rem_dp = n // dp
        for tp in range(1, rem_dp + 1):
            if rem_dp % tp:
                continue
            rem_tp = rem_dp // tp
            for pp in range(1, rem_tp + 1):
                if rem_tp % pp:
                    continue
                shapes.append((dp, tp, pp, rem_tp // pp))
    return shapes


def enumerate_space(n_devices: int, global_batch: int,
                    accum_choices: Sequence[int] = DEFAULT_ACCUM,
                    precisions: Sequence[str] = DEFAULT_PRECISIONS,
                    wus_modes: Sequence[str] = DEFAULT_WUS_MODES,
                    ) -> Iterator[Candidate]:
    """Yield every structurally-possible candidate, deterministically
    ordered. Structural filters only (see module docstring): the
    GLOBAL batch must split into ``accum`` whole microbatches — the
    trainer's own trace-time ``B % accum`` requirement — and must
    cover the dp axis at all. Legality proper (graphcheck) is the
    tuner's job."""
    for dp, tp, pp, sp in mesh_shapes(n_devices):
        if global_batch < dp:
            continue
        for accum in accum_choices:
            if global_batch % max(1, accum):
                continue
            for precision in precisions:
                for wus in wus_modes:
                    yield Candidate(
                        dp=dp, tp=tp, pp=pp, sp=sp,
                        gradient_accumulation=int(accum),
                        precision=str(precision),
                        weight_update_sharding=str(wus))


def default_candidate(n_devices: int, global_batch: int) -> Candidate:
    """The config a user gets WITHOUT tuning: ``MeshContext.create()``
    puts every device on the data axis, no accumulation, fp32,
    replicated weight update. Falls back to dp=1 when the global batch
    cannot shard that wide (the same degradation the untuned path hits
    at trace time). This is the baseline every autotune run probes —
    the winner must measure no slower than it."""
    dp = int(n_devices)
    if dp < 1 or (global_batch and global_batch % dp):
        dp = 1
    return Candidate(dp=dp)


def serve_bucket_set(global_batch: int, max_batch_cap: int
                     = SERVE_MAX_BATCH_CAP) -> Tuple[int, ...]:
    """The power-of-two serving bucket set implied by a tuned training
    batch: buckets up to the largest pow2 <= max(global_batch, 1),
    capped. The KerasServer batching scheduler compiles one AOT step per
    bucket — this is the set a warmed gateway holds."""
    from deeplearning4j_tpu.util.math_utils import next_pow_of_2
    top = max(1, min(int(max_batch_cap), int(global_batch) or 1))
    p = next_pow_of_2(top)
    if p > top:
        p >>= 1
    buckets, b = [], 1
    while b <= p:
        buckets.append(b)
        b <<= 1
    return tuple(buckets)
