"""TunedConfig: the autotuner's output, in a form every consumer takes.

One object carries the chosen training layout (mesh shape dp x tp x pp
x sp, gradient accumulation, precision preset, weight-update sharding),
the serving bucket set the same budget implies, and the calibration
evidence (every probed config's predicted vs measured step time and the
``measured_vs_predicted_gap``). It serializes to JSON so a tuned config
can be CHECKED IN next to the model and rebuilt bit-for-bit later —
probe parity (``tools/autotune_smoke.py``, ``tests/test_autotune.py``)
guarantees a trainer built from a ``TunedConfig`` trains bitwise
identically to one hand-built with the same knobs, because
``trainer_kwargs`` is the single construction recipe both paths share.

Consumers (all accept ``tuned=``):

- ``parallel.ParallelTrainer`` / ``parallel.ParallelWrapper``
- ``parallel.multihost.data_parallel_trainer``
- ``keras.KerasServer`` (batching scheduler ``max_batch`` = the top
  tuned bucket)
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.autotune.space import Candidate


@dataclass(frozen=True)
class ProbeRecord:
    """One measured probe: what the model predicted, what the chip (or
    CPU) measured, and the gap — the per-config calibration surface."""
    config: str                    # Candidate.slug()
    predicted_step_s: float
    measured_step_s: float
    measured_vs_predicted_gap: float   # measured / predicted
    compile_s: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "ProbeRecord":
        return ProbeRecord(**d)


@dataclass
class TunedConfig:
    """The winning configuration plus its evidence."""
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    gradient_accumulation: int = 1
    precision: str = "fp32"
    weight_update_sharding: str = "off"
    global_batch: int = 32
    device_count: int = 1
    hbm_budget_bytes: Optional[int] = None
    serve_buckets: Tuple[int, ...] = (1,)
    # calibration outputs
    predicted_step_s: Optional[float] = None
    measured_step_s: Optional[float] = None
    measured_vs_predicted_gap: Optional[float] = None
    predicted_hbm_bytes: Optional[int] = None
    predicted_mfu: Optional[float] = None
    probes: List[ProbeRecord] = field(default_factory=list)
    # search bookkeeping (how the space shrank — serialized so a
    # checked-in config documents what was ruled out and why)
    search: Dict[str, int] = field(default_factory=dict)

    FORMAT = "TunedConfig.v1"

    # ----------------------------------------------------------- derived
    @property
    def candidate(self) -> Candidate:
        return Candidate(
            dp=self.dp, tp=self.tp, pp=self.pp, sp=self.sp,
            gradient_accumulation=self.gradient_accumulation,
            precision=self.precision,
            weight_update_sharding=self.weight_update_sharding)

    @property
    def serve_max_batch(self) -> int:
        return max(self.serve_buckets) if self.serve_buckets else 1

    def mesh_context(self, devices=None):
        """The MeshContext this config prescribes (pp excluded — the
        pipeline trainer owns stage placement)."""
        from deeplearning4j_tpu.parallel.mesh import MeshContext
        if self.pp > 1:
            raise ValueError(
                f"TunedConfig with pp={self.pp} maps to the pipeline "
                "trainer, not a flat MeshContext; build a "
                "PipelineTrainer from .candidate explicitly")
        return MeshContext.create(n_data=self.dp, n_model=self.tp,
                                  n_seq=self.sp, devices=devices)

    def trainer_kwargs(self) -> dict:
        """ParallelTrainer kwargs (minus mesh) — delegated to the
        candidate so TunedConfig and the probe harness can never
        construct differently."""
        return self.candidate.trainer_kwargs()

    def trainer(self, net, devices=None, **kwargs):
        """One-call trainer at the tuned config:
        ``autotune(net).trainer(net).fit(...)``."""
        from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
        return ParallelTrainer(net, self.mesh_context(devices=devices),
                               tuned=self, **kwargs)

    # ------------------------------------------------------------- serde
    def to_dict(self) -> dict:
        d = asdict(self)
        d["format"] = self.FORMAT
        d["serve_buckets"] = list(self.serve_buckets)
        d["probes"] = [p.to_dict() if isinstance(p, ProbeRecord) else dict(p)
                       for p in self.probes]
        return d

    @staticmethod
    def from_dict(d: dict) -> "TunedConfig":
        d = dict(d)
        fmt = d.pop("format", TunedConfig.FORMAT)
        if fmt != TunedConfig.FORMAT:
            raise ValueError(f"unsupported TunedConfig format {fmt!r}")
        d["serve_buckets"] = tuple(d.get("serve_buckets", (1,)))
        d["probes"] = [ProbeRecord.from_dict(p)
                       for p in d.get("probes", [])]
        d["search"] = dict(d.get("search", {}))
        return TunedConfig(**d)

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "TunedConfig":
        return TunedConfig.from_dict(json.loads(s))

    def save(self, path: str) -> None:
        """Atomic write (resilience/atomic.py — a torn tuned config
        must never be half-loaded into a fleet)."""
        from deeplearning4j_tpu.resilience.atomic import atomic_write_bytes
        atomic_write_bytes(path, (self.to_json() + "\n").encode())

    @staticmethod
    def load(path: str) -> "TunedConfig":
        with open(path, "r", encoding="utf-8") as fh:
            return TunedConfig.from_json(fh.read())

    # ------------------------------------------------------------ display
    def summary(self) -> str:
        lines = [
            f"TunedConfig  (devices={self.device_count}, "
            f"batch={self.global_batch})",
            f"  mesh: dp={self.dp} tp={self.tp} pp={self.pp} sp={self.sp}"
            f"  accum={self.gradient_accumulation}"
            f"  precision={self.precision}"
            f"  wus={self.weight_update_sharding}",
            f"  serve buckets: {list(self.serve_buckets)}",
            f"  predicted {self.predicted_step_s!r} s/step, "
            f"measured {self.measured_step_s!r} s/step, "
            f"gap {self.measured_vs_predicted_gap!r}",
        ]
        if self.search:
            lines.append("  search: " + ", ".join(
                f"{k}={v}" for k, v in sorted(self.search.items())))
        for p in self.probes:
            lines.append(
                f"    probe {p.config:<28} predicted {p.predicted_step_s:.5f}s"
                f" measured {p.measured_step_s:.5f}s"
                f" gap {p.measured_vs_predicted_gap:.2f}x")
        return "\n".join(lines)
