"""Sequence/context parallelism: ring attention over a mesh axis.

The reference has no sequence parallelism (SURVEY §5.7); this is the
first-class long-context path of the TPU build. Ring attention
(Liu et al.): shard the sequence over mesh axis ``sp``; each device holds
Q/K/V shards, iterates n_sp steps, computing blockwise attention of its Q
shard against the KV shard currently resident, then passes KV to the next
ring neighbor with ``jax.lax.ppermute`` over ICI. Compute overlaps
communication (the permute is issued alongside the attention block), and
the flash-style log-sum-exp accumulators make the per-step partial results
exactly composable.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.nn.layers.attention import (
    NEG_INF, blockwise_attention, finalize_attention,
)


def ring_attention_sharded(q, k, v, axis_name: str, *, causal: bool = False,
                           block_size: int = 512, kv_mask=None):
    """Runs INSIDE shard_map. q,k,v: local shards [B, H, T_local, D];
    the global sequence is axis_size * T_local. ``kv_mask``: the local
    [B, T_local] key-validity shard (sequence padding) — it rotates
    around the ring alongside its KV shard. Returns the local output
    shard [B, H, T_local, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    T_local = q.shape[2]
    q_offset = my_idx * T_local
    # when unmasked, keep the 5-element carry: an all-ones mask would
    # still be ppermuted every ring step (a dead ICI collective per layer)
    has_mask = kv_mask is not None

    def step(carry, i):
        if has_mask:
            out, m, lse, k_cur, v_cur, mask_cur = carry
        else:
            out, m, lse, k_cur, v_cur = carry
            mask_cur = None
        # which device's KV shard are we holding at ring step i?
        src = (my_idx - i) % axis_size
        o_blk, m_blk, lse_blk = blockwise_attention(
            q, k_cur, v_cur, block_size=block_size, causal=False,
            kv_mask=mask_cur)  # None when unmasked
        if causal:
            # causal across shards: KV shard `src` is fully visible if
            # src < my_idx, invisible if src > my_idx, diagonal if equal.
            kv_offset = src * T_local
            q_pos = q_offset + jnp.arange(T_local)
            # recompute the diagonal block with exact causal mask
            o_diag, m_diag, lse_diag = blockwise_attention(
                q, k_cur, v_cur, block_size=block_size, causal=True,
                q_offset=q_offset - kv_offset, kv_mask=mask_cur)
            fully_visible = src < my_idx
            o_blk = jnp.where(fully_visible, o_blk, o_diag)
            m_blk = jnp.where(fully_visible, m_blk, m_diag)
            lse_blk = jnp.where(fully_visible, lse_blk, lse_diag)
            invisible = src > my_idx
            o_blk = jnp.where(invisible, 0.0, o_blk)
            m_blk = jnp.where(invisible, NEG_INF, m_blk)
            lse_blk = jnp.where(invisible, 0.0, lse_blk)
        # combine running accumulators (same algebra as blockwise inner loop)
        m_new = jnp.maximum(m, m_blk)
        corr_old = jnp.exp(m - m_new)
        corr_blk = jnp.exp(m_blk - m_new)
        out = out * corr_old[..., None] + o_blk * corr_blk[..., None]
        lse = lse * corr_old + lse_blk * corr_blk
        # rotate KV (and its validity mask) around the ring (ICI hop)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        if has_mask:
            mask_nxt = jax.lax.ppermute(mask_cur, axis_name, perm)
            return (out, m_new, lse, k_nxt, v_nxt, mask_nxt), None
        return (out, m_new, lse, k_nxt, v_nxt), None

    # q-derived initial carries: correct varying-manual-axes under shard_map
    out0 = q * 0.0
    m0 = q[..., 0] * 0.0 + NEG_INF
    lse0 = q[..., 0] * 0.0
    carry0 = ((out0, m0, lse0, k, v, kv_mask) if has_mask
              else (out0, m0, lse0, k, v))
    final_carry, _ = jax.lax.scan(step, carry0, jnp.arange(axis_size))
    out, m, lse = final_carry[:3]
    return finalize_attention(out, lse)


def ring_self_attention(x, params, mesh: Mesh, *, n_heads: int,
                        head_dim: int, seq_axis: str = "data",
                        batch_axis: Optional[str] = None,
                        causal: bool = False, block_size: int = 512,
                        mask=None):
    """Full sequence-parallel self attention: x [B, T, F] sharded over
    ``seq_axis`` on its T dimension (and over ``batch_axis`` on B when
    composing with data parallelism — without it every dp device would
    redundantly attend over the whole batch); QKV projections are local,
    attention runs as a ring. ``mask``: [B, T] sequence-padding validity
    — its key shard rotates with the KVs and the output is zeroed at
    masked query positions, matching the local layer path. Entry point
    used by SelfAttentionLayer when a mesh context is active, and
    directly by transformer blocks."""
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.6 ships it under experimental
        import functools
        from jax.experimental.shard_map import shard_map as _exp
        # see parallel/pipeline.py: the old replication checker predates
        # pvary/pcast and rejects valid ring programs
        shard_map = functools.partial(_exp, check_rep=False)

    def local_fn(x_l, Wq, Wk, Wv, Wo, *mask_rest):
        mask_l = mask_rest[0] if mask_rest else None
        B, T_l, F = x_l.shape

        def split(h):
            return h.reshape(B, T_l, n_heads, head_dim).transpose(0, 2, 1, 3)

        q, k, v = split(x_l @ Wq), split(x_l @ Wk), split(x_l @ Wv)
        out = ring_attention_sharded(
            q, k, v, seq_axis, causal=causal, block_size=block_size,
            kv_mask=None if mask is None else mask_l)
        out = out.transpose(0, 2, 1, 3).reshape(B, T_l, n_heads * head_dim)
        out = out @ Wo
        if mask is not None:
            out = out * mask_l[..., None]
        return out

    spec_x = P(batch_axis, seq_axis, None)
    spec_w = P()
    in_specs = [spec_x, spec_w, spec_w, spec_w, spec_w]
    args = [x, params["Wq"], params["Wk"], params["Wv"], params["Wo"]]
    if mask is not None:
        in_specs.append(P(batch_axis, seq_axis))
        args.append(jnp.asarray(mask, x.dtype))
    fn = shard_map(local_fn, mesh=mesh, in_specs=tuple(in_specs),
                   out_specs=spec_x)
    return fn(*args)
