"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

No counterpart exists in the reference (data parallelism only — SURVEY
§2.3); this is part of the TPU build's first-class scale-out. Design: the
S pipeline stages are homogeneous (same activation shapes), their params
stacked on a leading stage axis sharded over mesh axis ``pp``. Inside
``shard_map`` every device runs the same program: at tick t it applies its
stage to the activation it holds, then passes the result to its ring
neighbor with ``ppermute`` (ICI neighbor hop). Stage 0 injects microbatch
t; stage S-1 collects finished microbatches. M microbatches drain the
bubble in S-1 ticks — utilization M/(M+S-1), the GPipe schedule.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh: Mesh, axis: str = "pp"):
    """Run the pipeline.

    stage_fn(params_slice, x) -> y with y.shape == x.shape (homogeneous
    stages). ``stacked_params``: pytree with leading stage axis S == mesh
    size over ``axis``. ``x_microbatches``: [M, B_mb, ...] (replicated).
    Returns [M, B_mb, ...] outputs of the final stage.
    """
    S = mesh.shape[axis]
    M = x_microbatches.shape[0]
    T = M + S - 1  # total ticks incl. pipeline fill

    def device_fn(params, xs):
        # params: this stage's slice, leading axis 1; xs: all microbatches
        params = jax.tree.map(lambda a: a[0], params)
        sid = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % S) for j in range(S)]

        def tick(carry, t):
            held, outbuf = carry
            # stage 0 injects microbatch t (zeros once drained)
            inject = jnp.where(t < M, t, 0)
            x_in = jnp.where(sid == 0, xs[inject], held)
            y = stage_fn(params, x_in)
            # last stage stores finished microbatch t-(S-1)
            done_idx = t - (S - 1)
            store = jnp.logical_and(sid == S - 1, done_idx >= 0)
            idx = jnp.maximum(done_idx, 0)
            cur = jax.lax.dynamic_index_in_dim(outbuf, idx, 0, keepdims=False)
            val = jnp.where(store, y, cur)
            outbuf = jax.lax.dynamic_update_index_in_dim(outbuf, val, idx, 0)
            # hand activation to the next stage
            held_next = jax.lax.ppermute(y, axis, perm)
            return (held_next, outbuf), None

        # pvary: carries must be device-varying to match the scan body
        held0 = jax.lax.pvary(xs[0] * 0.0, (axis,))
        outbuf0 = jax.lax.pvary(xs * 0.0, (axis,))
        (_, outbuf), _ = jax.lax.scan(tick, (held0, outbuf0), jnp.arange(T))
        # every device returns its buffer; only the last stage's is real.
        # psum gathers it to all (cheap: zeros elsewhere).
        return jax.lax.psum(outbuf, axis)

    fn = shard_map(device_fn, mesh=mesh,
                   in_specs=(P(axis), P()),
                   out_specs=P())
    return fn(stacked_params, x_microbatches)


def stack_stage_params(param_list):
    """Stack per-stage param pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)
