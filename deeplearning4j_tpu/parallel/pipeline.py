"""Pipeline parallelism: GPipe-style microbatch pipeline over a mesh axis.

No counterpart exists in the reference (data parallelism only — SURVEY
§2.3); this is part of the TPU build's first-class scale-out. Two tiers:

- ``pipeline_apply`` — the homogeneous-stage primitive (same activation
  shape everywhere): stacked params sharded over mesh axis ``pp``, one
  ``ppermute`` ring hop per tick (ICI neighbor traffic only).
- ``PipelineTrainer`` — a real ``MultiLayerNetwork`` partitioned into S
  contiguous stages balanced by parameter count, with NON-homogeneous
  activation shapes and heterogeneous per-stage layer programs. Every
  device runs the same SPMD program (an XLA requirement): stage programs
  are branches of one ``lax.switch`` selected by the device's position on
  the ``pp`` axis, and both params and boundary activations travel as
  flat, right-padded buffers of the maximum stage size, reshaped to their
  true shapes inside each branch. The GPipe schedule is unchanged: M
  microbatches drain the bubble in S-1 ticks — utilization M/(M+S-1).
  Composes with data parallelism: if the mesh also has a ``dp`` axis the
  microbatch batch dim is sharded over it (dp×pp), and XLA inserts the
  gradient all-reduce over ``dp`` outside the shard_map.

Reachable through the strategy SPI: ``create_trainer("pipeline", net,
mesh)`` (ref: TrainingMaster SPI, spark/dl4j-spark/.../api/
TrainingMaster.java:29 — the strategy seam this plugs into).
"""

from __future__ import annotations

import logging
import time
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
    _COMPAT_SHARD_MAP = False
except ImportError:  # jax < 0.6 ships it under experimental
    import functools
    from jax.experimental.shard_map import shard_map as _exp_shard_map
    # the pre-varying-types replication checker cannot type the ring's
    # lax.switch branches (newer jax proves the same property via
    # pvary/pcast); its own error message prescribes check_rep=False
    shard_map = functools.partial(_exp_shard_map, check_rep=False)
    # with check_rep off, an out_spec that omits a mesh axis is UNDEFINED
    # under jit (the eager path happens to pick a valid replica; jit does
    # not) — _make_ring tiles its outputs over every axis instead
    _COMPAT_SHARD_MAP = True
from jax.sharding import Mesh, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.updater import compute_updates, l1_l2_penalty
from deeplearning4j_tpu.profiling import get_tracer

logger = logging.getLogger(__name__)

# one process-wide aux-loss semantics warning (see PipelineTrainer)
_WARNED_AUX_MICROBATCH = False


def _pvary(x, axis):
    # jax.lax.pvary was deprecated in favor of pcast(..., to='varying')
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis, to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, (axis,))
    # pre-varying-type jax (< 0.5): values need no device-varying marking
    return x


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh: Mesh, axis: str = "pp"):
    """Run a homogeneous pipeline.

    stage_fn(params_slice, x) -> y with y.shape == x.shape (homogeneous
    stages). ``stacked_params``: pytree with leading stage axis S == mesh
    size over ``axis``. ``x_microbatches``: [M, B_mb, ...] (replicated).
    Returns [M, B_mb, ...] outputs of the final stage.
    """
    S = mesh.shape[axis]
    M = x_microbatches.shape[0]
    T = M + S - 1  # total ticks incl. pipeline fill

    def device_fn(params, xs):
        # params: this stage's slice, leading axis 1; xs: all microbatches
        params = jax.tree.map(lambda a: a[0], params)
        sid = jax.lax.axis_index(axis)
        perm = [(j, (j + 1) % S) for j in range(S)]

        def tick(carry, t):
            held, outbuf = carry
            # stage 0 injects microbatch t (zeros once drained)
            inject = jnp.where(t < M, t, 0)
            x_in = jnp.where(sid == 0, xs[inject], held)
            y = stage_fn(params, x_in)
            # last stage stores finished microbatch t-(S-1)
            done_idx = t - (S - 1)
            store = jnp.logical_and(sid == S - 1, done_idx >= 0)
            idx = jnp.maximum(done_idx, 0)
            cur = jax.lax.dynamic_index_in_dim(outbuf, idx, 0, keepdims=False)
            val = jnp.where(store, y, cur)
            outbuf = jax.lax.dynamic_update_index_in_dim(outbuf, val, idx, 0)
            # hand activation to the next stage
            held_next = jax.lax.ppermute(y, axis, perm)
            return (held_next, outbuf), None

        # carries must be device-varying to match the scan body
        held0 = _pvary(xs[0] * 0.0, axis)
        outbuf0 = _pvary(xs * 0.0, axis)
        (_, outbuf), _ = jax.lax.scan(tick, (held0, outbuf0), jnp.arange(T))
        # every device returns its buffer; only the last stage's is real.
        # psum gathers it to all (cheap: zeros elsewhere).
        return jax.lax.psum(outbuf, axis)

    fn = shard_map(device_fn, mesh=mesh,
                   in_specs=(P(axis), P()),
                   out_specs=P())
    return fn(stacked_params, x_microbatches)


def stack_stage_params(param_list):
    """Stack per-stage param pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *param_list)


def _make_ring(mesh: Mesh, axis: str, dp_axis: Optional[str], S: int,
               M: int, branches):
    """The GPipe ring schedule as a shard_map callable shared by the MLN
    and graph pipeline trainers:
    pipe(param_bufs [S, Pmax], state_bufs [S, Smax], carry_bufs [S, Cmax],
    xs [M, B_mb, Amax]) -> (outputs [M, B_mb, Amax],
    new_state_bufs [S, Smax], new_carry_bufs [S, Cmax]).

    Each branch is branch(pflat, sflat, cflat, xbuf, key, m) ->
    (ybuf, sflat_new, cflat_new); ``key`` is a per-(tick, stage[, dp
    shard]) PRNG key folded from the step's base rng — the dropout
    stream — and ``m`` the microbatch index the tick processes (carry
    segments are per-microbatch slices). State updates apply only on
    REAL ticks (stage s works on genuine microbatches at ticks
    s <= t < s+M; fill/drain ticks process ring garbage). Running-state
    rows pmean-sync over ``dp_axis`` after the window; carry rows do NOT
    (tBPTT carries are per-batch-row, never averaged — the trainers
    reject dp meshes when carries are live)."""

    def device_fn(bufs, sbufs, cbufs, xs, rng):
        sid = jax.lax.axis_index(axis)
        if _COMPAT_SHARD_MAP:
            # bufs/sbufs/cbufs arrive REPLICATED (see the spec selection
            # below): each device picks its own stage row. jax 0.4.x
            # miscompiles a P(axis)-sharded operand that is COMPUTED
            # inside the enclosing jit (pack_bufs/pack_states) — the
            # manual region reads garbage; replicate-and-index sidesteps
            # the partitioner entirely at a CPU-test-only memory cost.
            pflat = jax.lax.dynamic_index_in_dim(bufs, sid, 0,
                                                 keepdims=False)
            srow = jax.lax.dynamic_index_in_dim(sbufs, sid, 0,
                                                keepdims=False)
            crow = jax.lax.dynamic_index_in_dim(cbufs, sid, 0,
                                                keepdims=False)
        else:
            pflat, srow, crow = bufs[0], sbufs[0], cbufs[0]
        perm = [(j, (j + 1) % S) for j in range(S)]
        key_base = jax.random.fold_in(rng, sid)
        if dp_axis is not None:
            # decorrelate dropout masks across dp shards
            key_base = jax.random.fold_in(
                key_base, jax.lax.axis_index(dp_axis))

        def tick(carry, t):
            held, outbuf, sflat, cflat = carry
            inject = jnp.where(t < M, t, 0)
            x_in = jnp.where(sid == 0, xs[inject], held)
            m = jnp.clip(t - sid, 0, M - 1)
            y, sflat2, cflat2 = jax.lax.switch(
                sid, branches, pflat, sflat, cflat, x_in,
                jax.random.fold_in(key_base, t), m)
            real = jnp.logical_and(t >= sid, t < sid + M)
            sflat = jnp.where(real, sflat2, sflat)
            cflat = jnp.where(real, cflat2, cflat)
            done_idx = t - (S - 1)
            store = jnp.logical_and(sid == S - 1, done_idx >= 0)
            idx = jnp.maximum(done_idx, 0)
            cur = jax.lax.dynamic_index_in_dim(outbuf, idx, 0,
                                               keepdims=False)
            outbuf = jax.lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(store, y, cur), idx, 0)
            return (jax.lax.ppermute(y, axis, perm), outbuf, sflat,
                    cflat), None

        held0 = _pvary(xs[0] * 0.0, axis)
        outbuf0 = _pvary(xs * 0.0, axis)
        # the state carry must enter the switch varying over EVERY mesh
        # axis: stateful branches derive their output from the
        # (dp-varying) batch shard while stateless ones return the carry
        # itself — mismatched varying sets are a type error
        sflat0 = srow
        cflat0 = crow
        if dp_axis is not None:
            sflat0 = _pvary(sflat0, dp_axis)
            cflat0 = _pvary(cflat0, dp_axis)
        (_, outbuf, sflat, cflat), _ = jax.lax.scan(
            tick, (held0, outbuf0, sflat0, cflat0), jnp.arange(M + S - 1))
        if dp_axis is not None:
            # dp replicas saw different microbatch shards: sync the
            # running averages (normalization itself stays per-replica,
            # standard unsynced-BN semantics)
            sflat = jax.lax.pmean(sflat, dp_axis)
            cflat = jax.lax.pmean(cflat, dp_axis)  # dummy rows when dp on
        out = jax.lax.psum(outbuf, axis)
        if _COMPAT_SHARD_MAP:
            # every output dimension maps a mesh axis (see import shim):
            # out gains a leading pp axis; state/carry rows gain a dp axis
            # when dp is on. All tiles are identical (post-psum/pmean), so
            # the caller strips index 0.
            if dp_axis is not None:
                return out[None], sflat[None, None], cflat[None, None]
            return out[None], sflat[None], cflat[None]
        return out, sflat[None], cflat[None]

    batch_spec = P(None, dp_axis, None)
    if _COMPAT_SHARD_MAP:
        # replicated param/state/carry operands (see device_fn), and
        # out_specs that mention EVERY mesh axis (an omitted axis is
        # undefined under jit with check_rep=False) — all tiles are
        # identical post-psum/pmean, so the wrapper strips index 0
        fn = shard_map(
            device_fn, mesh=mesh,
            in_specs=(P(), P(), P(), batch_spec, P()),
            out_specs=(P(axis, None, dp_axis, None),
                       P(axis, dp_axis) if dp_axis else P(axis),
                       P(axis, dp_axis) if dp_axis else P(axis)))

        def pipe(*args):
            outs, sbufs, cbufs = fn(*args)
            if dp_axis is not None:
                return outs[0], sbufs[:, 0], cbufs[:, 0]
            return outs[0], sbufs, cbufs

        return pipe
    return shard_map(device_fn, mesh=mesh,
                     in_specs=(P(axis), P(axis), P(axis), batch_spec, P()),
                     out_specs=(batch_spec, P(axis), P(axis)))


class _RingFitMixin:
    """fit_batch/fit shared by the MLN and graph pipeline trainers (the
    jitted step signature and all bookkeeping are identical; only stage
    construction differs). Subclasses provide ``_build_step(b_mb)``
    setting ``self._amax``, and the attrs net/M/mesh/dp_axis; they may
    set ``training_stats`` (a TrainingStats) for per-phase telemetry."""

    training_stats = None
    _tbptt = False

    def fit_batch(self, batch: DataSet) -> float:
        net = self.net
        multi_io = getattr(self, "in_names", None)
        if not isinstance(batch, DataSet):
            from deeplearning4j_tpu.datasets.dataset import MultiDataSet
            if not (multi_io and isinstance(batch, MultiDataSet)):
                raise ValueError(
                    "this pipeline trainer takes a single-input DataSet; "
                    f"got {type(batch).__name__}")
            if any(m is not None for m in (batch.features_masks or []))\
                    or any(m is not None
                           for m in (batch.labels_masks or [])):
                raise ValueError("masked MultiDataSets are unsupported "
                                 "in the pipeline trainers")
            if len(batch.features) != len(self.in_names) \
                    or len(batch.labels) != len(self.out_names):
                raise ValueError(
                    f"MultiDataSet arity {len(batch.features)}in/"
                    f"{len(batch.labels)}out != network "
                    f"{len(self.in_names)}in/{len(self.out_names)}out")
            B = batch.features[0].shape[0]
            rt = net.conf.resolved_types
            for name, f in zip(self.in_names, batch.features):
                want = _type_elems(rt[name])
                got = int(np.prod(f.shape[1:]))
                if got != want:
                    raise ValueError(
                        f"input {name!r}: got {got} elements/sample "
                        f"{tuple(f.shape)}, network expects {want} "
                        f"({rt[name]})")
            # stage 0 unpacks the inputs from one concatenated flat
            # buffer, in network_inputs order (matches _make_branch)
            feats = jnp.concatenate(
                [jnp.asarray(f).reshape(B, -1) for f in batch.features],
                axis=1)
            labels = {o: jnp.asarray(l)
                      for o, l in zip(self.out_names, batch.labels)}
        else:
            if (batch.features_mask is not None
                    or batch.labels_mask is not None):
                # loud, like the other unsupported features — a silently
                # dropped mask would train a whole run subtly wrong
                raise ValueError("masked DataSets are unsupported in the "
                                 "pipeline trainers (mask threading "
                                 "through the ring schedule is future "
                                 "work)")
            feats = jnp.asarray(batch.features)
            labels = jnp.asarray(batch.labels)
        B = feats.shape[0]
        if B % self.M != 0:
            raise ValueError(f"batch size {B} not divisible by "
                             f"n_microbatches={self.M}")
        b_mb = B // self.M
        if self.dp_axis is not None:
            dp = self.mesh.shape[self.dp_axis]
            if b_mb % dp != 0:
                raise ValueError(
                    f"microbatch size {b_mb} (batch {B} / {self.M} "
                    f"microbatches) not divisible by the dp axis ({dp})")
        if self._tbptt and feats.ndim == 3:
            # rank-3 features + truncated_bptt => window the updates,
            # exactly MLN.fit_batch's routing (multilayer.py:327) —
            # including its loud rank-3-labels requirement: slicing a
            # rank-2 label tensor along time would shear off classes
            if labels.ndim != 3:
                raise ValueError(
                    "truncated_bptt requires rank-3 (time-distributed) "
                    "labels [B, T, K]; got rank-"
                    f"{labels.ndim} {tuple(labels.shape)} — use "
                    "standard backprop for sequence-to-one training")
            return self._fit_batch_tbptt(feats, labels, b_mb, B)
        if (self._step is None or getattr(self, "_b_mb", None) != b_mb
                or getattr(self, "_step_sentinel", None)
                is not getattr(net, "_sentinel", None)):
            # microbatch shape OR sentinel changed: different program
            self._step_sentinel = getattr(net, "_sentinel", None)
            self._step = self._build_step(b_mb)
            self._b_mb = b_mb
            self._tbptt_cache = getattr(self, "_tbptt_cache", {})
            self._tbptt_cache.clear()
        stats = self.training_stats
        # `with` spans (not bare begin/end): a raising step must close
        # its span and note it on the tracer's error stack, or a caught
        # exception would leak an open span into later hang diagnoses
        tracer = get_tracer()
        with tracer.span("shard"):
            t_shard = time.perf_counter() if stats else 0.0
            x = feats.reshape(self.M, b_mb, -1)
            xs = jnp.pad(x, ((0, 0), (0, 0),
                             (0, self._amax - x.shape[-1])))
            if stats:
                jax.block_until_ready(xs)
                stats.record("shard", time.perf_counter() - t_shard)
                t_step = time.perf_counter()
        with tracer.span("step", microbatches=self.M):
            net._rng, step_rng = jax.random.split(net._rng)
            cbuf = jnp.zeros((self.S, getattr(self, "_cmax", 1)),
                             jnp.float32)
            out = self._step(
                net.params, net.opt_state, net.states, cbuf, xs, labels,
                step_rng)
            net.params, net.opt_state, net.states, _, loss = out[:5]
            if stats:
                jax.block_until_ready(loss)
                stats.record("step", time.perf_counter() - t_step)
        net.last_batch_size = B
        net.score_value = loss
        net.iteration_count += 1
        if hasattr(net, "_observe_sentinel"):
            net._observe_sentinel(out[5] if len(out) > 5 else None)
        with tracer.span("listener"):
            t_l = time.perf_counter() if stats else 0.0
            for listener in net.listeners:
                listener.iteration_done(net, net.iteration_count,
                                        net.score_value)
            if stats:
                stats.record("listener", time.perf_counter() - t_l)
        return net._score_raw

    def _fit_batch_tbptt(self, feats, labels, b_mb: int, B: int) -> float:
        """Truncated BPTT through the ring: time windows run one pipeline
        step each; recurrent layers' final carries ride the (no-grad)
        carry buffer between windows, so gradients stop at window edges
        exactly like MLN._fit_tbptt (ref:
        MultiLayerNetwork.doTruncatedBPTT:1119-1183). Carries reset to
        zeros at batch start."""
        net = self.net
        fwd = net.conf.training.tbptt_fwd_length
        T = feats.shape[1]
        if (getattr(self, "_tbptt_sentinel", None)
                is not getattr(net, "_sentinel", None)):
            # sentinel changed: cached window steps are unguarded (or
            # stale-guarded) programs — rebuild them
            self._tbptt_sentinel = getattr(net, "_sentinel", None)
            self._tbptt_cache.clear()
        cbuf = None
        total, slices = 0.0, 0
        for start in range(0, T, fwd):
            end = min(start + fwd, T)
            w = end - start
            key = (b_mb, w)
            if key not in self._tbptt_cache:
                step = self._build_step(b_mb, timesteps=w)
                self._tbptt_cache[key] = (step, self._amax, self._cmax)
            step, amax, cmax = self._tbptt_cache[key]
            if cbuf is None:
                cbuf = jnp.zeros((self.S, cmax), jnp.float32)
            stats = self.training_stats
            t_shard = time.perf_counter() if stats else 0.0
            x = jnp.asarray(feats[:, start:end]).reshape(self.M, b_mb, -1)
            xs = jnp.pad(x, ((0, 0), (0, 0), (0, amax - x.shape[-1])))
            lw = jnp.asarray(labels[:, start:end])
            if stats:
                jax.block_until_ready((xs, lw))
                stats.record("shard", time.perf_counter() - t_shard)
                t_step = time.perf_counter()
            net._rng, step_rng = jax.random.split(net._rng)
            out = step(
                net.params, net.opt_state, net.states, cbuf, xs, lw,
                step_rng)
            net.params, net.opt_state, net.states, cbuf, loss = out[:5]
            if stats:
                jax.block_until_ready(loss)
                stats.record("step", time.perf_counter() - t_step)
            total = total + loss
            slices += 1
            net.score_value = loss
            net.iteration_count += 1
            if hasattr(net, "_observe_sentinel"):
                net._observe_sentinel(out[5] if len(out) > 5 else None)
            t_l = time.perf_counter() if stats else 0.0
            for listener in net.listeners:
                listener.iteration_done(net, net.iteration_count,
                                        net.score_value)
            if stats:
                stats.record("listener", time.perf_counter() - t_l)
        net.last_batch_size = B
        # device scalar, like MLN._fit_tbptt: converting here would sync
        # the dispatch pipeline every batch (multilayer.py:459-465)
        return total / max(slices, 1)

    def fit(self, data, epochs: int = 1):
        from deeplearning4j_tpu.optimize.listeners import TrainingListener
        net = self.net
        if isinstance(data, DataSet):
            data = [data]
        stats = self.training_stats
        for _ in range(epochs):
            for listener in net.listeners:
                if isinstance(listener, TrainingListener):
                    listener.on_epoch_start(net)
            src = stats.timed_iter(data) if stats else data
            for batch in src:
                self.fit_batch(batch)
            net.epoch_count += 1
            for listener in net.listeners:
                if isinstance(listener, TrainingListener):
                    listener.on_epoch_end(net)
        return self


def _reject_remat(conf):
    """The pipeline branches run layer.apply without jax.checkpoint: a
    remat'd config would silently lose its gradient checkpointing (and
    its memory headroom) — fail loudly like the other unsupported
    features."""
    if getattr(conf.training, "remat", False):
        raise ValueError(
            "gradient_checkpointing (remat) is unsupported in the "
            "pipeline trainers — stage branches store activations for "
            "backward; disable remat or train without the pipeline")


# ---------------------------------------------------------------------------
# heterogeneous pipeline over a real MultiLayerNetwork
# ---------------------------------------------------------------------------

def _optimal_cuts(costs, boundaries, n_stages):
    """Place ``n_stages - 1`` cuts from the candidate ``boundaries``
    (each a (position, activation_elems) pair; position b cuts between
    item b-1 and item b) minimizing

        max_stage(sum costs) + act_weight-scaled max_cut(activation)

    where the caller pre-scales the activation term into the boundary
    values. Exact O(S * n^2) DP — the candidate sets are tiny (layers of
    one network). Returns the chosen cut positions, sorted."""
    n = len(costs)
    ps = [0]
    for c in costs:
        ps.append(ps[-1] + c)

    def seg(a, b):  # cost of items a..b-1
        return ps[b] - ps[a]

    acts = sorted({a for _, a in boundaries})
    best_obj, best_cuts = None, None
    for amax in acts:
        allowed = sorted(p for p, a in boundaries if a <= amax)
        if len(allowed) < n_stages - 1:
            continue
        # dp over (stage count k, last cut position): minimal max stage
        # cost for items[0:pos] split into k stages. This pass finds only
        # the optimal VALUE; the winning amax's DP is re-run below with
        # parent links to recover the actual cut positions.
        INF = float("inf")
        dp = {0: 0.0}  # pos -> best max-cost using k cuts so far
        for _ in range(n_stages - 1):
            nxt = {}
            for pos, m in dp.items():
                for q in allowed:
                    if q <= pos:
                        continue
                    v = max(m, seg(pos, q))
                    if v < nxt.get(q, INF):
                        nxt[q] = v
            dp = nxt
            if not dp:
                break
        if not dp:
            continue
        m = min((max(v, seg(pos, n)), pos) for pos, v in dp.items())
        obj = m[0] + amax
        if best_obj is None or obj < best_obj:
            best_obj, best_cuts = obj, (amax, m[0])
    if best_cuts is None:
        return None
    # re-run the DP for the winning amax, tracking parents, to recover
    # the actual cut positions
    amax = best_cuts[0]
    allowed = sorted(p for p, a in boundaries if a <= amax)
    dp = {0: (0.0, None)}
    layers_dp = [dp]
    for _ in range(n_stages - 1):
        nxt = {}
        for pos, (m, _par) in layers_dp[-1].items():
            for q in allowed:
                if q <= pos:
                    continue
                v = max(m, seg(pos, q))
                if q not in nxt or v < nxt[q][0]:
                    nxt[q] = (v, pos)
        layers_dp.append(nxt)
    end = min(layers_dp[-1].items(), key=lambda kv: max(kv[1][0], seg(kv[0], n)))
    cuts = []
    pos = end[0]
    for k in range(n_stages - 1, 0, -1):
        cuts.append(pos)
        pos = layers_dp[k][pos][1]
    return sorted(cuts)


def partition_stages(layers, params, n_stages: int,
                     act_elems: Optional[Sequence[float]] = None,
                     act_weight: float = 1.0) -> List[List[int]]:
    """Split body-layer indices into ``n_stages`` contiguous groups (the
    reference has no analog — its scale-out clones whole models; stage
    partitioning is the TPU build's model-parallel axis).

    Cost model: exact DP minimizing ``max_stage(param_count) +
    act_weight * max_cut(act_elems)``. The second term is the ring's
    per-tick ppermute payload — boundary activations travel right-padded
    to the LARGEST cut's size, so one fat cut (e.g. ResNet's 56x56x256
    early stage) taxes every hop of every tick; a param-only balance
    cannot see that (VERDICT r4 weak #3). ``act_elems[i]`` = activation
    elements per sample crossing the boundary after layer ``i``; when
    None the activation term is zero and the DP reduces to optimal
    param-count balance (better than the old greedy fair-share, same
    objective)."""
    n = len(layers)
    if n_stages > n:
        # more devices on the pp axis than body layers: trailing stages
        # are identity pass-throughs (the ring hop still runs; they add
        # bubble ticks but keep the mesh shape unconstrained)
        return ([[i] for i in range(n)]
                + [[] for _ in range(n_stages - n)])
    costs = [sum(int(np.prod(v.shape)) for v in params[i].values()) + 1
             for i in range(n)]
    if act_elems is None:
        bounds = [(b, 0.0) for b in range(1, n)]
    else:
        bounds = [(b, act_weight * float(act_elems[b - 1]))
                  for b in range(1, n)]
    cuts = _optimal_cuts(costs, bounds, n_stages)
    if cuts is None:  # n_stages == 1
        return [list(range(n))]
    edges = [0] + cuts + [n]
    return [list(range(edges[i], edges[i + 1]))
            for i in range(len(edges) - 1)]


def _type_elems(t) -> int:
    """Per-sample activation elements of an InputType."""
    return int(np.prod(_type_shape(t, 1)))


def _true_layer_shapes(conf, layers, b: int,
                       timesteps: Optional[int] = None) -> List[tuple]:
    """[input_shape, out_of_layer_0, ..., out_of_last] — the TRUE tensor
    shapes flowing between layers. This differs from the InputType walk
    in one place: RnnToFeedForward/FeedForwardToRnn preprocessors are
    no-ops here (the broadcast form keeps [B, T, F] through FF layers,
    see nn/conf/preprocessors.py:84-104), so an ff-typed tensor inside
    such a region still carries the time axis. ``timesteps`` overrides
    the recurrent input length (tBPTT windows)."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.preprocessors import (
        FeedForwardToRnnPreProcessor, RnnToFeedForwardPreProcessor)
    cur = conf.input_type
    if timesteps is not None and cur.kind == "rnn":
        cur = InputType.recurrent(cur.size, timesteps)
    broadcast_t: Optional[int] = None  # live time axis on an ff type

    def true_shape(t, bt):
        if t.kind == "ff" and bt:
            return (b, bt, t.size)
        return _type_shape(t, b)

    shapes = [true_shape(cur, broadcast_t)]
    for i, layer in enumerate(layers):
        if i in conf.preprocessors:
            pre = conf.preprocessors[i]
            if isinstance(pre, RnnToFeedForwardPreProcessor):
                broadcast_t = cur.timesteps
            cur = pre.infer_output_type(cur)
            if (isinstance(pre, FeedForwardToRnnPreProcessor)
                    and cur.timesteps is None and broadcast_t):
                cur = InputType.recurrent(cur.size, broadcast_t)
            if cur.kind != "ff":
                broadcast_t = None
        cur = layer.infer_output_type(cur)
        if cur.kind == "rnn":
            if cur.timesteps is None and broadcast_t:
                cur = InputType.recurrent(cur.size, broadcast_t)
            broadcast_t = None
        shapes.append(true_shape(cur, broadcast_t))
    return shapes


def _mln_boundary_elems(conf, layers) -> List[int]:
    """Per-sample activation elements leaving each body layer (the ring
    payload if the stage cut lands after that layer)."""
    shapes = _true_layer_shapes(conf, layers, 1)
    return [int(np.prod(s[1:])) for s in shapes[1:]]


def _type_shape(t, batch: int):
    """Concrete activation shape for an InputType at a given batch size."""
    if t.kind == "ff":
        return (batch, t.size)
    if t.kind == "rnn":
        if t.timesteps is None:
            raise ValueError("PipelineTrainer needs fixed timesteps in the "
                             "recurrent InputType (static shapes under jit)")
        return (batch, t.timesteps, t.size)
    if t.kind == "cnn":
        return (batch, t.height, t.width, t.channels)
    raise ValueError(f"Unsupported InputType kind {t.kind!r}")


class PipelineTrainer(_RingFitMixin):
    """GPipe pipeline-parallel trainer for a ``MultiLayerNetwork``.

    The net's body layers (all but the loss head) are partitioned into S
    contiguous stages; each pipeline tick every device applies ITS stage
    (a ``lax.switch`` branch) to the flat activation buffer it holds and
    ppermutes the result to its ring neighbor. The loss head, gradient
    normalization, optimizer update, and L1/L2 all reuse the exact
    single-device code (``compute_updates``), so a pipeline step is
    loss-parity-identical to ``net.fit_batch`` up to float reassociation.

    Layer running state (BatchNormalization's mean/var) threads through
    the ring schedule: each device carries its stage's flattened state in
    the tick scan, updating it only on REAL ticks (stage s works on
    genuine microbatches at ticks s <= t < s+M; fill/drain ticks process
    ring garbage and must not touch statistics). Note the standard GPipe
    semantics: BN statistics are per-MICROBATCH (and per-dp-replica, with
    running averages pmean-synced over 'dp' after the window), so they
    match the single-device step exactly only when n_microbatches == 1.

    Dropout runs inside the ring: each tick's switch branch receives a
    PRNG key folded from the step rng by (stage, tick[, dp shard]), so
    masks differ per microbatch/stage/shard and a fixed seed reproduces.

    MoE aux-loss semantics under microbatching: with
    ``n_microbatches == M > 1`` each microbatch computes its balancing
    loss over its OWN 1/M slice of the batch and the objective takes
    the mean of those per-microbatch values — which differs from the
    single-device step's aux computed over the full batch (mean of
    per-slice balance != full-batch balance; the same approximation the
    dp gradient all-reduce makes). Exact parity holds only at M=1 on a
    pp-only mesh; a one-time ``logger.warning`` marks runs that train
    aux layers with M > 1 (see PARITY.md).

    Recurrent layers pipeline too: a stage runs its layer's full
    sequence scan in-stage (plain BPTT, zero carry per batch), and under
    truncated BPTT the final carries ride the ring's no-grad carry
    buffer between time windows — per-microbatch slices, gradients
    stopped at window edges by construction (pp-only meshes; see
    __init__).
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, axis: str = "pp",
                 n_microbatches: Optional[int] = None,
                 stages: Optional[Sequence[Sequence[int]]] = None,
                 collect_training_stats: bool = False):
        from deeplearning4j_tpu.optimize.training_stats import TrainingStats
        from deeplearning4j_tpu.parallel.mesh import MeshContext
        if collect_training_stats:
            self.training_stats = TrainingStats()
        if isinstance(mesh, MeshContext):
            mesh = mesh.mesh
        if mesh is None:
            devs = np.array(jax.devices())
            mesh = Mesh(devs.reshape(len(devs)), (axis,))
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
        net._check_init()
        _reject_remat(net.conf)
        if not hasattr(net, "layers"):
            raise ValueError("PipelineTrainer supports MultiLayerNetwork "
                             "(graph stage partitioning is future work)")
        if net.conf.input_type is None:
            raise ValueError("PipelineTrainer needs set_input_type() on the "
                             "config (static boundary shapes under jit)")
        self.net = net
        self.mesh = mesh
        self.axis = axis
        self.dp_axis = "dp" if "dp" in mesh.axis_names else None
        self.S = mesh.shape[axis]
        self.M = int(n_microbatches or self.S)
        body = net.layers[:-1]
        head = net.layers[-1]
        if not hasattr(head, "compute_loss"):
            raise ValueError("Last layer must be an output/loss layer")
        # MixtureOfExperts-style aux losses ride a dedicated
        # DIFFERENTIABLE column of the ring activation buffer (the state
        # buffer is no-grad; the activation buffer is not) — see
        # _make_branch. Under dp each shard accumulates its local aux
        # and the loss takes the row mean, the same approximation the
        # dp gradient all-reduce already makes.
        self._aux_layers = [i for i, l in enumerate(body)
                            if "aux_loss" in net.states[i]]
        global _WARNED_AUX_MICROBATCH
        if self._aux_layers and self.M > 1 and not _WARNED_AUX_MICROBATCH:
            _WARNED_AUX_MICROBATCH = True
            logger.warning(
                "PipelineTrainer: %d aux-loss layer(s) with "
                "n_microbatches=%d — the balancing loss is a mean of "
                "per-microbatch values, not the full-batch aux; exact "
                "single-device parity holds only at n_microbatches=1 "
                "(see the class docstring / PARITY.md)",
                len(self._aux_layers), self.M)
        # recurrent layers run their full sequence INSIDE their stage
        # (zero initial carry per batch, exactly layer.apply); under
        # tBPTT the final carries additionally thread through the ring's
        # no-grad carry buffer across time windows — which gives the
        # stop-gradient-at-window-edges semantics for free (ref:
        # MultiLayerNetwork.doTruncatedBPTT:1119-1183 / LSTMHelpers.java)
        self._carry_layers = [i for i, l in enumerate(body)
                              if getattr(l, "supports_carry", False)]
        # gate on backprop_type alone: a truncated_bptt net with NO
        # carry layers (e.g. bidirectional-only) still windows its
        # updates on a single device, and must window here too — gating
        # on carries would silently train full-sequence BPTT instead
        self._tbptt = (net.conf.training.backprop_type == "truncated_bptt")
        if self._tbptt and self._carry_layers and self.dp_axis is not None:
            raise ValueError(
                "tBPTT under the pipeline needs a pp-only mesh: carries "
                "are per-batch-row and cannot ride the dp-averaged state "
                "buffer — drop the dp axis or train without tBPTT")
        if self._tbptt:
            tr = net.conf.training
            bwd = tr.tbptt_bwd_length or tr.tbptt_fwd_length
            if bwd < tr.tbptt_fwd_length:
                # MLN's split-window trick (forward-only head, backprop
                # tail — multilayer.py:368-378) doesn't fit the ring: a
                # silently full-window backprop would train differently
                raise ValueError(
                    "tbptt_bwd_length < tbptt_fwd_length is unsupported "
                    "under the pipeline (windows backprop whole); set "
                    "bwd == fwd or train without the pipeline")
        self._tbptt_cache = {}
        self.stages = ([list(s) for s in stages] if stages is not None
                       else partition_stages(
                           body, net.params, self.S,
                           act_elems=_mln_boundary_elems(net.conf, body)))
        if len(self.stages) != self.S:
            raise ValueError(f"{len(self.stages)} stages != pp size {self.S}")
        flat = [i for st in self.stages for i in st]
        if flat != list(range(len(body))):
            raise ValueError(f"stages must cover body layers 0..{len(body)-1}"
                             f" contiguously, got {self.stages}")
        if any(not st for st in self.stages[:-1]) and any(
                st for i, st in enumerate(self.stages) if i
                and not self.stages[i - 1]):
            raise ValueError("empty (identity) stages must be trailing, "
                             f"got {self.stages}")
        self._step = None

    # ---------------------------------------------------------------- shapes
    def _boundary_shapes(self, b_mb: int, timesteps: Optional[int] = None):
        """TRUE activation shape entering each stage plus the final body
        output feeding the loss head (via _true_layer_shapes — an ff-typed
        tensor between Rnn<->FF preprocessors still carries its time
        axis). ``timesteps`` overrides the recurrent input length (tBPTT
        windows are shorter than the configured sequence)."""
        body = [self.net.layers[i] for st in self.stages for i in st]
        shapes = _true_layer_shapes(self.net.conf, body, b_mb, timesteps)
        stage_in, pos = [], 0
        for st in self.stages:
            stage_in.append(shapes[pos])
            pos += len(st)
        return stage_in, shapes[-1]

    # ------------------------------------------------------------ stage fns
    def _make_branch(self, stage: List[int], in_shape, amax: int,
                     seg_shapes, state_shapes, smax: int,
                     carry_meta=None):
        """One lax.switch branch: unpack this stage's flat param segment,
        flat state segment, and activation buffer, run its layers exactly
        as MLN._forward does (dropout runs in-ring with per-stage/tick/
        dp-shard folded RNG keys), repack both. Under tBPTT
        (``carry_meta``), recurrent layers read their microbatch-``m``
        carry slice from the no-grad carry buffer, scan the window, and
        write the final carry back — MLN._forward's carries branch, in
        ring form. The batch dim reshapes with -1: under dp×pp the local
        batch is the global microbatch divided by the dp size."""
        net = self.net
        conf = net.conf
        in_size = int(np.prod(in_shape[1:]))
        carry_meta = carry_meta or {}
        if not stage:
            # identity (pass-through) stage
            return lambda pflat, sflat, cflat, xbuf, key, m: (
                xbuf, sflat, cflat)

        def branch(pflat, sflat, cflat, xbuf, key, m):
            # unflatten this stage's params/states from padded segments
            p, s = {}, {}
            off = soff = 0
            for i in stage:
                layer_p, layer_s = {}, {}
                for name in net.layers[i].param_order():
                    shp, dt = seg_shapes[i][name]
                    n = int(np.prod(shp))
                    layer_p[name] = pflat[off:off + n].reshape(shp).astype(dt)
                    off += n
                for name, (shp, dt) in state_shapes[i].items():
                    n = int(np.prod(shp))
                    layer_s[name] = (sflat[soff:soff + n]
                                     .reshape(shp).astype(dt))
                    soff += n
                p[i], s[i] = layer_p, layer_s
            h = xbuf[:, :in_size].reshape((-1,) + in_shape[1:])
            in_types = conf.input_types
            new_s = {}
            for i in stage:
                layer = net.layers[i]
                if i in conf.preprocessors:
                    it = in_types[i] if in_types else None
                    h = conf.preprocessors[i].transform(h, it)
                sub = jax.random.fold_in(key, i)
                if i in carry_meta:
                    coff, per_mb, leaf_meta, treedef = carry_meta[i]
                    seg = jax.lax.dynamic_slice(
                        cflat, (coff + m * per_mb,), (per_mb,))
                    leaves, o = [], 0
                    for shp, dt in leaf_meta:
                        n = int(np.prod(shp))
                        leaves.append(seg[o:o + n].reshape(shp).astype(dt))
                        o += n
                    c_in = jax.tree_util.tree_unflatten(treedef, leaves)
                    # scan() bypasses apply(): input dropout must still
                    # fire (exactly MLN._forward's carries branch)
                    h = layer._dropout_input(h, not layer.frozen, sub)
                    h, c_out = layer.scan(p[i], h, c_in, None)
                    flat_out = jnp.concatenate(
                        [jnp.reshape(x, (-1,)).astype(jnp.float32)
                         for x in jax.tree_util.tree_leaves(c_out)])
                    cflat = jax.lax.dynamic_update_slice(
                        cflat, flat_out, (coff + m * per_mb,))
                    new_s[i] = s[i]
                else:
                    # recurrent layers included: apply() scans the full
                    # window from a zero carry, which _carry_like (in
                    # nn/layers/recurrent.py) marks varying over the mesh
                    # axes so the in-stage lax.scan type-checks under
                    # shard_map
                    h, s_out = layer.apply(p[i], h, state=s[i],
                                           train=not layer.frozen,
                                           rng=sub, mask=None)
                    new_s[i] = s[i] if layer.frozen else s_out
            y = h.reshape(xbuf.shape[0], -1)
            leaves = [new_s[i][name].reshape(-1).astype(jnp.float32)
                      for i in stage for name in state_shapes[i]]
            sflat_new = (jnp.pad(jnp.concatenate(leaves),
                                 (0, smax - sum(l.shape[0] for l in leaves)))
                         if leaves else sflat)
            y_pad = jnp.pad(y, ((0, 0), (0, amax - y.shape[1])))
            # running aux-loss accumulator: read the incoming sum from
            # the (differentiable) last column, add this stage's aux
            # scalars, write it back for the next hop
            aux = xbuf[0, amax - 1]
            for i in stage:
                # same predicate as loss_of's gate (self._aux_layers,
                # init_state-declared) — a split predicate could silently
                # drop a layer's balancing term from the objective
                if i in self._aux_layers and "aux_loss" in new_s[i]:
                    aux = aux + new_s[i]["aux_loss"].astype(jnp.float32)
            y_pad = y_pad.at[:, amax - 1].set(aux.astype(y_pad.dtype))
            return y_pad, sflat_new, cflat

        return branch

    # ------------------------------------------------------------- the step
    def _build_step(self, b_mb: int, timesteps: Optional[int] = None):
        net = self.net
        S, M, axis = self.S, self.M, self.axis
        mesh = self.mesh
        stage_in, head_in_shape = self._boundary_shapes(b_mb, timesteps)
        head_in_size = int(np.prod(head_in_shape[1:]))
        # +1: the last buffer column is the differentiable running
        # aux-loss accumulator (zero-cost when no aux layers exist)
        amax = max([int(np.prod(s[1:])) for s in stage_in]
                   + [head_in_size]) + 1
        # per-layer param segment metadata (static shapes for unflatten)
        seg_shapes = {i: {k: (v.shape, v.dtype)
                          for k, v in net.params[i].items()}
                      for st in self.stages for i in st}
        seg_sizes = [sum(int(np.prod(seg_shapes[i][k][0]))
                         for i in st for k in seg_shapes[i])
                     for st in self.stages]
        pmax = max(seg_sizes)
        # per-layer running-state segment metadata (BN mean/var)
        state_shapes = {i: {k: (v.shape, v.dtype)
                            for k, v in net.states[i].items()}
                        for st in self.stages for i in st}
        ssizes = [sum(int(np.prod(state_shapes[i][k][0]))
                      for i in st for k in state_shapes[i])
                  for st in self.stages]
        smax = max([1] + ssizes)
        self._amax = amax
        # per-stage carry segment layout (tBPTT only): for each recurrent
        # layer, M per-microbatch slices of its flattened (h, c) carry
        carry_metas: List[dict] = []
        csizes = []
        if self._tbptt and self._carry_layers:
            dt_tr = net.params[self._carry_layers[0]][
                net.layers[self._carry_layers[0]].param_order()[0]].dtype
            for st in self.stages:
                meta, coff = {}, 0
                for i in st:
                    if i not in self._carry_layers:
                        continue
                    c0 = net.layers[i].initial_carry(b_mb, dt_tr)
                    leaves, treedef = jax.tree_util.tree_flatten(c0)
                    leaf_meta = [(x.shape, x.dtype) for x in leaves]
                    per_mb = sum(int(np.prod(x.shape)) for x in leaves)
                    meta[i] = (coff, per_mb, leaf_meta, treedef)
                    coff += per_mb * M
                carry_metas.append(meta)
                csizes.append(coff)
        else:
            carry_metas = [{} for _ in self.stages]
        cmax = max([1] + csizes)
        self._cmax = cmax
        branches = [self._make_branch(st, stage_in[s], amax, seg_shapes,
                                      state_shapes, smax, carry_metas[s])
                    for s, st in enumerate(self.stages)]

        def pack_bufs(params):
            """[S, Pmax] padded flat param buffer (differentiable)."""
            rows = []
            for st in self.stages:
                leaves = [params[i][k].reshape(-1).astype(jnp.float32)
                          for i in st for k in net.layers[i].param_order()]
                row = jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
                rows.append(jnp.pad(row, (0, pmax - row.shape[0])))
            return jnp.stack(rows)

        def pack_states(states):
            rows = []
            for st in self.stages:
                leaves = [states[i][k].reshape(-1).astype(jnp.float32)
                          for i in st for k in state_shapes[i]]
                row = jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
                rows.append(jnp.pad(row, (0, smax - row.shape[0])))
            return jnp.stack(rows)

        def unpack_states(sbuf):
            out = list(net.states)
            for s, st in enumerate(self.stages):
                soff = 0
                for i in st:
                    layer_s = {}
                    for name, (shp, dt) in state_shapes[i].items():
                        n = int(np.prod(shp))
                        layer_s[name] = (sbuf[s, soff:soff + n]
                                         .reshape(shp).astype(dt))
                        soff += n
                    out[i] = layer_s
            return out

        pipe = _make_ring(mesh, axis, self.dp_axis, S, M, branches)

        tx = net._tx
        training = net.conf.training
        head = net.layers[-1]
        head_idx = len(net.layers) - 1
        head_pre = net.conf.preprocessors.get(head_idx)
        head_pre_type = (net.conf.input_types[head_idx]
                         if net.conf.input_types else None)

        def loss_of(params, sbuf, cbuf, xs, labels, rng):
            outs, new_sbuf, new_cbuf = pipe(pack_bufs(params), sbuf, cbuf,
                                            xs, rng)
            h = outs[..., :head_in_size].reshape(
                (M * b_mb,) + head_in_shape[1:])
            if head_pre is not None:
                # e.g. the auto CnnToFeedForward flatten before an
                # OutputLayer head — exactly as MLN._forward applies it
                h = head_pre.transform(h, head_pre_type)
            data_loss = head.compute_loss(params[head_idx], h, labels,
                                          mask=None)
            # per-microbatch aux sums arrive in the buffer's last column
            # (rows within a shard are identical; the mean also averages
            # over dp shards and microbatches — exact at M=1, pp-only)
            aux = (outs[..., amax - 1].mean().astype(data_loss.dtype)
                   if self._aux_layers else 0.0)
            return (data_loss + l1_l2_penalty(params, net.layers) + aux,
                    (new_sbuf, new_cbuf))

        sentinel = getattr(net, "_sentinel", None)
        if sentinel is not None:
            from deeplearning4j_tpu.resilience.sentinel import guard_update

        def step(params, opt_state, states, cbuf, xs, labels, rng):
            sbuf = pack_states(states)
            (loss, (new_sbuf, new_cbuf)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, sbuf, cbuf, xs, labels, rng)
            new_params, new_opt = compute_updates(
                tx, grads, opt_state, params, net.layers, training)
            if sentinel is None:
                return (new_params, new_opt, unpack_states(new_sbuf),
                        new_cbuf, loss)
            # non-finite guard incl. the carry buffer: a NaN window must
            # not poison the next tBPTT window's carries
            sel, bad = guard_update(
                loss, grads, (params, opt_state, states, cbuf),
                (new_params, new_opt, unpack_states(new_sbuf), new_cbuf))
            return sel[0], sel[1], sel[2], sel[3], loss, bad

        return jax.jit(step, donate_argnums=(0, 1, 2, 3))


# ---------------------------------------------------------------------------
# pipeline over a ComputationGraph (DAG stage partitioning)
# ---------------------------------------------------------------------------

def find_graph_cut_points(conf) -> List[Tuple[int, str]]:
    """Valid stage boundaries of a DAG: positions ``p`` in the topological
    order where exactly ONE node's activation crosses from the prefix
    ``topo[:p]`` to the suffix — the single tensor the ring can carry.
    Returns [(p, crossing_node_name)]. ResNet-style block chains cut at
    every block output; a skip connection spanning a candidate boundary
    disqualifies it (two tensors would cross). The algorithm itself is
    ``analysis/graphcheck.graph_cut_points`` — ONE implementation, so
    the GC017 composition validator and this trainer's partition can
    never disagree about which cuts exist."""
    from deeplearning4j_tpu.analysis.graphcheck import graph_cut_points
    return graph_cut_points(conf)


class GraphPipelineTrainer(_RingFitMixin):
    """GPipe pipeline-parallel trainer for a ``ComputationGraph`` — the
    DAG analog of PipelineTrainer (ResNet-50, the flagship BASELINE
    model, is a graph here). The topological order is split at single-
    tensor cut points (find_graph_cut_points) into S contiguous stages
    balanced by parameter count; skip connections live entirely inside
    stages, so the ring still carries one activation buffer. Running
    state (BN) threads exactly as in PipelineTrainer; the output node's
    loss head and compute_updates reuse the graph's single-device code.

    Multi-input graphs inject every network input into stage 0 as one
    concatenated flat buffer; multi-output graphs put every loss head's
    input on the final boundary (find_graph_cut_points counts heads as
    consumers, so no cut can strand a head input in an earlier stage)
    and the loss sums the heads, exactly like the single-device graph.

    Out of scope: masks, RNN/carry vertices (LastTimeStep /
    DuplicateToTimeSeries), aux-loss layers, truncated BPTT. Dropout
    runs in-ring (per-stage/tick/dp-shard folded RNG keys), as in
    PipelineTrainer.
    """

    def __init__(self, net, mesh: Optional[Mesh] = None, axis: str = "pp",
                 n_microbatches: Optional[int] = None,
                 collect_training_stats: bool = False):
        from deeplearning4j_tpu.nn.conf.graph import (
            DuplicateToTimeSeriesVertex, LastTimeStepVertex)
        from deeplearning4j_tpu.optimize.training_stats import TrainingStats
        from deeplearning4j_tpu.parallel.mesh import MeshContext
        if collect_training_stats:
            self.training_stats = TrainingStats()
        if isinstance(mesh, MeshContext):
            mesh = mesh.mesh
        if mesh is None:
            devs = np.array(jax.devices())
            mesh = Mesh(devs.reshape(len(devs)), (axis,))
        if axis not in mesh.axis_names:
            raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
        net._check_init()
        _reject_remat(net.conf)
        conf = net.conf
        if not conf.resolved_types:
            raise ValueError("GraphPipelineTrainer needs set_input_types() "
                             "on the config (static boundary shapes)")
        self.net = net
        self.mesh = mesh
        self.axis = axis
        self.dp_axis = "dp" if "dp" in mesh.axis_names else None
        self.S = mesh.shape[axis]
        self.M = int(n_microbatches or self.S)
        # multi-input: every network input is injected into stage 0 as a
        # concatenated flat buffer. Multi-output: heads count as
        # consumers in find_graph_cut_points (they sit in out_set), so
        # no cut can separate a head input from its head — all head
        # inputs are provably computed in the final stage, whose
        # boundary carries their concatenation.
        self.in_names = list(conf.network_inputs)
        self.out_names = list(conf.network_outputs)
        consumers_of = {n: 0 for n in conf.topological_order}
        for n in conf.topological_order:
            for i in conf.nodes[n].inputs:
                consumers_of[i] += 1
        for o in self.out_names:
            out_node = conf.nodes[o]
            if out_node.kind != "layer" \
                    or not hasattr(out_node.layer, "compute_loss"):
                raise ValueError(f"output node {o!r} must be a loss head")
            if consumers_of[o]:
                raise ValueError(f"output node {o!r} feeds other nodes — "
                                 "unsupported in the graph pipeline")
        self.head_in_names = []
        for o in self.out_names:
            for i in conf.nodes[o].inputs:
                if i not in self.head_in_names:
                    self.head_in_names.append(i)
        for name in conf.topological_order:
            node = conf.nodes[name]
            if node.kind == "vertex" and isinstance(
                    node.vertex, (LastTimeStepVertex,
                                  DuplicateToTimeSeriesVertex)):
                raise ValueError(f"vertex {name!r} "
                                 f"({type(node.vertex).__name__}) is "
                                 "unsupported in the graph pipeline v1")
            if node.kind != "layer":
                continue
            l = node.layer
            if "aux_loss" in net.states.get(name, {}):
                raise ValueError(f"layer node {name!r} carries an "
                                 "auxiliary loss — unsupported (see "
                                 "PipelineTrainer)")
            if getattr(l, "supports_carry", False):
                raise ValueError(f"layer node {name!r} is recurrent — "
                                 "unsupported in the graph pipeline v1")
            if getattr(l, "tied_to", None) and name not in self.out_names:
                # tied weights resolve at the LOSS seam (outside the
                # ring), where the full params dict is in scope; a tied
                # layer inside a stage would need its partner's params
                # in the packed buffer — not wired
                raise ValueError(
                    f"layer node {name!r} ties weights (tied_to="
                    f"{l.tied_to!r}) but is not an output head — only "
                    "tied LOSS heads are supported in the graph pipeline")
        if conf.training.backprop_type == "truncated_bptt":
            # the single-device graph windows updates via _fit_tbptt;
            # running full-sequence BPTT here instead would silently
            # train differently (PipelineTrainer implements windowing,
            # the graph trainer does not yet)
            raise ValueError(
                "truncated_bptt is unsupported in the graph pipeline v1 "
                "— use PipelineTrainer (MLN) for windowed tBPTT or "
                "standard backprop for the graph")
        self.stages, self.boundaries = self._partition()
        self._step = None

    # ------------------------------------------------------------ partition
    def _partition(self):
        """Split the non-input, non-head topo nodes into S contiguous
        groups at balanced cut points. Returns (stages: list of
        node-name lists, boundaries: LIST of tensor names entering each
        stage — all network inputs for stage 0, the single crossing
        node after)."""
        conf = self.net.conf
        topo = list(conf.topological_order)
        heads = set(self.out_names)
        body = [n for n in topo
                if conf.nodes[n].kind != "input" and n not in heads]
        if not body:
            raise ValueError("no body nodes to pipeline")
        body_set = set(body)
        cuts = [(p, n) for p, n in find_graph_cut_points(conf)
                if 0 < p < len(topo) and n in body_set]

        def cost(name):
            node = conf.nodes[name]
            if node.kind != "layer":
                return 1
            return 1 + sum(int(np.prod(v.shape))
                           for v in self.net.params[name].values())

        # map topo cut positions onto body-list boundaries, with the
        # crossing tensor's per-sample size as the cut's activation term
        # (same DP + cost model as partition_stages: max stage params +
        # max ring payload — a fat skip-free boundary early in a ResNet
        # would otherwise set every tick's ppermute size)
        topo_to_bidx = {}
        b = 0
        for p, name in enumerate(topo):
            topo_to_bidx[p + 1] = b + (1 if name in body_set else 0)
            if name in body_set:
                b += 1
        rt = conf.resolved_types
        boundaries, bound_name = [], {}
        for p, crossing in cuts:
            bidx = topo_to_bidx[p]
            if 0 < bidx < len(body):
                boundaries.append((bidx, float(_type_elems(rt[crossing]))))
                bound_name[bidx] = crossing
        costs = [cost(n) for n in body]
        n_cuts_usable = min(self.S - 1, len(boundaries))
        cut_idx = (_optimal_cuts(costs, boundaries, n_cuts_usable + 1)
                   if n_cuts_usable else None) or []
        stages, bounds = [], [list(self.in_names)]
        edges = [0] + list(cut_idx) + [len(body)]
        for i in range(len(edges) - 1):
            stages.append(body[edges[i]:edges[i + 1]])
            if i + 1 < len(edges) - 1:
                bounds.append([bound_name[edges[i + 1]]])
        # fewer cut points than stages: trailing identity stages
        while len(stages) < self.S:
            stages.append([])
            bounds.append(bounds[-1])
        return stages, bounds

    # ---------------------------------------------------------------- shapes
    def _boundary_shapes(self, b_mb: int):
        """Per-stage lists of (name, shape) entering each stage + the
        final boundary (the concatenated head inputs)."""
        rt = self.net.conf.resolved_types
        stage_in = [[(n, _type_shape(rt[n], b_mb)) for n in names]
                    for names in self.boundaries]
        head_in = [(n, _type_shape(rt[n], b_mb))
                   for n in self.head_in_names]
        return stage_in, head_in

    # ------------------------------------------------------------ stage fns
    def _make_branch(self, stage: List[str], b_in: List[str],
                     b_out: Optional[List[str]], amax: int,
                     seg_shapes, state_shapes, smax: int):
        """``b_in``/``b_out``: the named tensors entering/leaving this
        stage, packed as one concatenated flat buffer (stage 0 unpacks
        every network input; the last real stage emits every head
        input)."""
        net = self.net
        conf = net.conf
        # deterministic per-node dropout-stream ids (Python's hash() is
        # salted per process — it would break seed reproducibility and
        # desync masks across multihost trace constants)
        node_ix = {n: i for i, n in enumerate(net._layer_nodes)}

        if not stage:
            return lambda pflat, sflat, cflat, xbuf, key, m: (
                xbuf, sflat, cflat)

        rt = conf.resolved_types
        in_shapes = [(n, _type_shape(rt[n], 1)[1:]) for n in b_in]

        def branch(pflat, sflat, cflat, xbuf, key, m):
            p, s = {}, {}
            off = soff = 0
            for name in stage:
                if conf.nodes[name].kind != "layer":
                    continue
                layer_p, layer_s = {}, {}
                for pname in conf.nodes[name].layer.param_order():
                    shp, dt = seg_shapes[name][pname]
                    n = int(np.prod(shp))
                    layer_p[pname] = (pflat[off:off + n]
                                      .reshape(shp).astype(dt))
                    off += n
                for sname, (shp, dt) in state_shapes[name].items():
                    n = int(np.prod(shp))
                    layer_s[sname] = (sflat[soff:soff + n]
                                      .reshape(shp).astype(dt))
                    soff += n
                p[name], s[name] = layer_p, layer_s
            acts = {}
            xoff = 0
            for name, shp in in_shapes:
                n = int(np.prod(shp))
                acts[name] = xbuf[:, xoff:xoff + n].reshape((-1,) + shp)
                xoff += n
            new_s = {}
            for name in stage:
                node = conf.nodes[name]
                in_acts = [acts[i] for i in node.inputs]
                if node.kind == "vertex":
                    acts[name] = node.vertex.apply(in_acts)
                else:
                    h = in_acts[0]
                    if node.preprocessor is not None:
                        h = node.preprocessor.transform(h, None)
                    layer = node.layer
                    h, s_out = layer.apply(
                        p[name], h, state=s[name],
                        train=not layer.frozen,
                        rng=jax.random.fold_in(key, node_ix[name]),
                        mask=None)
                    new_s[name] = s[name] if layer.frozen else s_out
                    acts[name] = h
            rows = xbuf.shape[0]
            y = jnp.concatenate([acts[n].reshape(rows, -1) for n in b_out],
                                axis=1)
            leaves = [new_s[nm][k].reshape(-1).astype(jnp.float32)
                      for nm in stage if nm in new_s
                      for k in state_shapes[nm]]
            sflat_new = (jnp.pad(
                jnp.concatenate(leaves),
                (0, smax - sum(l.shape[0] for l in leaves)))
                if leaves else sflat)
            return (jnp.pad(y, ((0, 0), (0, amax - y.shape[1]))),
                    sflat_new, cflat)

        return branch

    # ------------------------------------------------------------- the step
    def _build_step(self, b_mb: int):
        net = self.net
        conf = net.conf
        S, M, axis = self.S, self.M, self.axis
        stage_in, head_in = self._boundary_shapes(b_mb)

        def width(named_shapes):
            return sum(int(np.prod(shp[1:])) for _, shp in named_shapes)

        head_in_size = width(head_in)
        amax = max([width(si) for si in stage_in] + [head_in_size])
        last_real = max(i for i, st in enumerate(self.stages) if st)
        out_lists = []
        for s in range(S):
            if s == last_real:
                out_lists.append(self.head_in_names)
            elif s < last_real:
                out_lists.append(self.boundaries[s + 1])
            else:
                out_lists.append(None)  # identity pass-through
        layer_stage_nodes = [[n for n in st
                              if conf.nodes[n].kind == "layer"]
                             for st in self.stages]
        seg_shapes = {n: {k: (v.shape, v.dtype)
                          for k, v in net.params[n].items()}
                      for st in layer_stage_nodes for n in st}
        state_shapes = {n: {k: (v.shape, v.dtype)
                            for k, v in net.states[n].items()}
                        for st in layer_stage_nodes for n in st}
        pmax = max(1, max(sum(int(np.prod(seg_shapes[n][k][0]))
                              for n in st for k in seg_shapes[n])
                          for st in layer_stage_nodes))
        smax = max([1] + [sum(int(np.prod(state_shapes[n][k][0]))
                             for n in st for k in state_shapes[n])
                          for st in layer_stage_nodes])
        self._amax = amax
        branches = [self._make_branch(st, self.boundaries[s], out_lists[s],
                                      amax, seg_shapes, state_shapes, smax)
                    for s, st in enumerate(self.stages)]

        def pack_bufs(params):
            rows = []
            for st in layer_stage_nodes:
                leaves = [params[n][k].reshape(-1).astype(jnp.float32)
                          for n in st
                          for k in conf.nodes[n].layer.param_order()]
                row = jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
                rows.append(jnp.pad(row, (0, pmax - row.shape[0])))
            return jnp.stack(rows)

        def pack_states(states):
            rows = []
            for st in layer_stage_nodes:
                leaves = [states[n][k].reshape(-1).astype(jnp.float32)
                          for n in st for k in state_shapes[n]]
                row = jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
                rows.append(jnp.pad(row, (0, smax - row.shape[0])))
            return jnp.stack(rows)

        def unpack_states(sbuf):
            out = dict(net.states)
            for s, st in enumerate(layer_stage_nodes):
                soff = 0
                for n in st:
                    layer_s = {}
                    for name, (shp, dt) in state_shapes[n].items():
                        k = int(np.prod(shp))
                        layer_s[name] = (sbuf[s, soff:soff + k]
                                         .reshape(shp).astype(dt))
                        soff += k
                    out[n] = layer_s
            return out

        pipe = _make_ring(self.mesh, axis, self.dp_axis, S, M, branches)

        tx = net._tx
        training = conf.training
        layer_list = [conf.nodes[n].layer for n in net._layer_nodes]
        # static slicing metadata: where each head input lives in the
        # final boundary buffer
        head_slices = {}
        hoff = 0
        for n, shp in head_in:
            sz = int(np.prod(shp[1:]))
            head_slices[n] = (hoff, sz, shp[1:])
            hoff += sz

        def loss_of(params, sbuf, cbuf, xs, labels, rng):
            outs, new_sbuf, new_cbuf = pipe(pack_bufs(params), sbuf, cbuf,
                                            xs, rng)
            flat = outs[..., :head_in_size].reshape(M * b_mb, head_in_size)
            data_loss = 0.0
            for o in self.out_names:
                node = conf.nodes[o]
                off, sz, shp = head_slices[node.inputs[0]]
                h = flat[:, off:off + sz].reshape((M * b_mb,) + shp)
                if node.preprocessor is not None:
                    h = node.preprocessor.transform(h, None)
                lab = labels[o] if isinstance(labels, dict) else labels
                # tied head (TiedRnnOutputLayer): the container's one
                # tying seam injects the tied node's embedding matrix
                # from the FULL params tree — the head's gradient flows
                # into the embedding alongside the ring path's own use
                data_loss = data_loss + node.layer.compute_loss(
                    net._layer_params(params, o), h, lab, mask=None)
            # l1_l2_penalty wants a LIST aligned with layer_list (the
            # graph loss path does the same, nn/graph.py:296-299)
            reg = l1_l2_penalty([params[n] for n in net._layer_nodes],
                                layer_list)
            return data_loss + reg, (new_sbuf, new_cbuf)

        sentinel = getattr(net, "_sentinel", None)
        if sentinel is not None:
            from deeplearning4j_tpu.resilience.sentinel import guard_update

        def step(params, opt_state, states, cbuf, xs, labels, rng):
            sbuf = pack_states(states)
            (loss, (new_sbuf, new_cbuf)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, sbuf, cbuf, xs, labels, rng)
            new_params, new_opt = compute_updates(
                tx, grads, opt_state, params, layer_list, training)
            if sentinel is None:
                return (new_params, new_opt, unpack_states(new_sbuf),
                        new_cbuf, loss)
            # non-finite guard incl. the carry buffer (see the MLN
            # pipeline step above)
            sel, bad = guard_update(
                loss, grads, (params, opt_state, states, cbuf),
                (new_params, new_opt, unpack_states(new_sbuf), new_cbuf))
            return sel[0], sel[1], sel[2], sel[3], loss, bad

        return jax.jit(step, donate_argnums=(0, 1, 2, 3))


