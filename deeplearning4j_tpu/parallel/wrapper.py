"""ParallelWrapper: parameter-averaging compatibility trainer.

Ref: deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java:343-466
— N device-affine model clones, round-robin minibatch dispatch, barrier
join, params (and optionally updater state) averaged every
``averagingFrequency`` iterations (:412-455); Builder defaults workers =
#devices, prefetch 16 (:468-476).

TPU-native redesign: the N "worker clones" are ONE stacked param pytree with
a leading worker axis, sharded over the mesh's 'data' axis; the per-worker
fit is ``jax.vmap`` of the train step (so all workers run in the same XLA
program, one per device); averaging is a mean over the worker axis — the
barrier/thread machinery disappears. Semantics (including the
averaging-updater-state quirk) match the reference so its convergence tests
port; for the *correct* synchronous mode use ParallelTrainer instead
(every-step gradient all-reduce == averaging_frequency=1 with lower
variance, see SURVEY §5.8).
"""

from __future__ import annotations

from typing import List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import AsyncDataSetIterator, DataSetIterator
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updater import (
    PrecisionPolicy, cast_floats, compute_updates, precision_value_and_grad,
)
from deeplearning4j_tpu.parallel.mesh import (
    MeshContext, WeightUpdateSharding,
)
from deeplearning4j_tpu.profiling import get_tracer


class ParallelWrapper:
    """``weight_update_sharding="zero1"``: the stacked per-worker
    params/updater-state/model-state trees are explicitly placed with
    the worker axis sharded over the mesh's 'data' axis, so each device
    holds ONLY its own worker's replica (and in particular 1/N of the
    stacked optax state) instead of leaving the N-way stacks' layout to
    XLA — the wrapper-shaped analog of ZeRO-1, where the per-worker
    updater state is the natural shard. Workers must divide evenly by
    the data axis. Semantics are unchanged (placement only).
    ``"zero2"`` is accepted with the same placement: the wrapper's
    vmapped step never materializes a cross-worker reduced gradient in
    the first place (each device computes and consumes only its own
    worker's gradient, transiently), so the zero2 gradient-sharding
    guarantee is native here and the two modes coincide.

    ``precision`` (``"bf16"`` / a ``PrecisionPolicy`` / None to inherit
    ``net.conf.training.precision``): each worker's forward/backward
    runs in the compute dtype against its fp32 master replica — cast
    seams identical to ``ParallelTrainer``'s, applied per worker inside
    the vmap.

    ``tuned`` (a ``TunedConfig`` from ``deeplearning4j_tpu.autotune``):
    fills the mesh, workers (= the tuned dp width),
    ``weight_update_sharding`` and ``precision`` when those are left at
    their defaults; the tuned ``gradient_accumulation`` maps onto
    ``averaging_frequency`` (the knob it descends from — see the module
    docstring). Explicit kwargs win."""

    def __init__(self, net: MultiLayerNetwork, workers: Optional[int] = None,
                 prefetch_buffer: int = 16, averaging_frequency: int = 1,
                 average_updaters: bool = True,
                 mesh: Optional[MeshContext] = None,
                 report_score_after_averaging: bool = True,
                 weight_update_sharding=None,
                 precision=None,
                 tuned=None):
        if tuned is not None:
            if mesh is None:
                mesh = tuned.mesh_context()
            if workers is None:
                workers = tuned.dp
            if averaging_frequency == 1:
                averaging_frequency = tuned.gradient_accumulation
            if weight_update_sharding is None:
                weight_update_sharding = tuned.weight_update_sharding
            if precision is None:
                precision = tuned.precision
        net._check_init()
        self.net = net
        self.mesh = mesh or MeshContext.create()
        self.workers = workers or self.mesh.n_data
        self.prefetch_buffer = prefetch_buffer
        self.averaging_frequency = max(1, averaging_frequency)
        self.average_updaters = average_updaters
        self.report_score_after_averaging = report_score_after_averaging
        self.weight_update_sharding = WeightUpdateSharding.parse(
            weight_update_sharding)
        self.precision = PrecisionPolicy.parse(
            precision if precision is not None
            else getattr(net.conf.training, "precision", None),
            loss_scale=getattr(net.conf.training, "loss_scale", None))
        if self.weight_update_sharding.enabled:
            self.mesh.validate_weight_update_sharding(
                self.weight_update_sharding)
            dp = self.mesh.zero1_shards(self.weight_update_sharding.axis)
            if self.workers % dp != 0:
                raise ValueError(
                    f"zero1: {self.workers} workers cannot shard evenly "
                    f"over the {dp}-way "
                    f"{self.weight_update_sharding.axis!r} axis")
        # stack per-worker replicas: worker axis sharded over 'data'
        n = self.workers
        self._stacked_params = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), net.params)
        self._stacked_opt = jax.tree.map(
            lambda x: (jnp.broadcast_to(jnp.asarray(x)[None],
                                        (n,) + jnp.shape(x))
                       if hasattr(x, "shape") or np.isscalar(x) else x),
            net.opt_state)
        self._stacked_states = jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (n,) + x.shape), net.states)
        if self.weight_update_sharding.enabled:
            put = lambda t: jax.tree.map(self._worker_shard, t)
            self._stacked_params = put(self._stacked_params)
            self._stacked_opt = put(self._stacked_opt)
            self._stacked_states = put(self._stacked_states)
        self._vstep = None
        self._iter_since_avg = 0

    def _worker_shard(self, x):
        """Place one stacked leaf with its worker axis over 'data'."""
        if not hasattr(x, "ndim") or x.ndim < 1:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P
        spec = P(self.weight_update_sharding.axis,
                 *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(self.mesh.mesh, spec))

    # -------------------------------------------------------------- the step
    def _build_vmapped_step(self):
        net = self.net
        training = net.conf.training
        tx = net._tx
        sentinel = getattr(net, "_sentinel", None)
        if sentinel is not None:
            from deeplearning4j_tpu.resilience.sentinel import guard_update

        policy = self.precision
        mixed = policy.mixed

        def one_worker(params, opt_state, states, feats, labels, rng):
            if mixed:
                feats = cast_floats(feats, policy.compute_dtype)

            def loss_fn(p, st, f, l, r):
                return net._loss_fn(p, st, f, l, None, None,
                                    rng=r, train=True)

            # fp32 policy: plain value_and_grad (the exact pre-policy
            # program); mixed: params cast to the compute dtype at the
            # boundary, loss + grads returned across the fp32 seam
            (loss, new_states), grads = precision_value_and_grad(
                loss_fn, policy)(params, states, feats, labels, rng)
            new_params, new_opt = compute_updates(
                tx, grads, opt_state, params, net.layers, training)
            if sentinel is None:
                return new_params, new_opt, new_states, loss, ()
            # per-worker non-finite guard: a diverged worker keeps its
            # previous replica (and would re-sync at the next averaging)
            sel, bad = guard_update(
                loss, grads, (params, opt_state, states),
                (new_params, new_opt, new_states))
            return sel[0], sel[1], sel[2], loss, bad

        vstep = jax.vmap(one_worker)
        zero1 = self.weight_update_sharding.enabled
        if zero1:
            from jax.sharding import NamedSharding, PartitionSpec as P
            z_axis = self.weight_update_sharding.axis
            mesh = self.mesh.mesh

            def pin_workers(tree):
                """Keep the worker axis 'data'-sharded through the
                donated step — without the constraint XLA is free to
                re-replicate the stacks on output and the 1/N updater
                footprint evaporates after the first update."""
                def pin(x):
                    if not hasattr(x, "ndim") or x.ndim < 1:
                        return x
                    spec = P(z_axis, *([None] * (x.ndim - 1)))
                    return jax.lax.with_sharding_constraint(
                        x, NamedSharding(mesh, spec))
                return jax.tree.map(pin, tree)

        def step(sp, so, ss, feats, labels, rngs, do_average):
            sp, so, ss, losses, bads = vstep(sp, so, ss, feats, labels,
                                             rngs)

            def avg(tree, avg_ints: bool):
                def mean_bcast(x):
                    if not hasattr(x, "shape") or x.ndim == 0:
                        return x
                    if jnp.issubdtype(x.dtype, jnp.integer):
                        return x  # step counters etc. stay per-worker
                    m = jnp.mean(x, axis=0)
                    return jnp.broadcast_to(m[None], x.shape)
                return jax.tree.map(mean_bcast, tree)

            sp2 = jax.lax.cond(do_average, lambda t: avg(t, False),
                               lambda t: t, sp)
            if self.average_updaters:
                so2 = jax.lax.cond(do_average, lambda t: avg(t, True),
                                   lambda t: t, so)
            else:
                so2 = so
            ss2 = jax.lax.cond(do_average, lambda t: avg(t, False),
                               lambda t: t, ss)
            if zero1:
                sp2, so2, ss2 = (pin_workers(sp2), pin_workers(so2),
                                 pin_workers(ss2))
            return sp2, so2, ss2, losses, bads

        # _parallel_iteration overwrites the three stacked-state args with
        # the step's returns; donating them halves peak HBM per update
        return jax.jit(step, donate_argnums=(0, 1, 2))

    # ------------------------------------------------------- shardcheck
    def step_program(self, batch: DataSet):
        """Capture the compiled all-worker vmapped step program for one
        global ``batch`` (analysis/shardcheck) — one AOT compile, no
        execution."""
        from deeplearning4j_tpu.analysis.shardcheck import lower_step_program
        self._ensure_vstep()
        n = batch.num_examples()
        if n % self.workers:
            raise ValueError(
                f"global batch of {n} examples not divisible by "
                f"workers={self.workers}")
        batches = batch.batch_by(n // self.workers)
        feats = jnp.stack([jnp.asarray(b.features) for b in batches])
        labels = jnp.stack([jnp.asarray(b.labels) for b in batches])
        rngs = jax.random.split(jax.random.PRNGKey(0), self.workers)
        return lower_step_program(
            self._vstep, self._stacked_params, self._stacked_opt,
            self._stacked_states, feats, labels, rngs, jnp.asarray(True))

    def shardcheck(self, batch: DataSet, **overrides):
        """Statically verify the wrapper's compiled step: donation
        (SC005), host transfers (SC006), precision boundaries (SC004),
        collective census (SC002). The wrapper has no reduce-scatter
        contract — its vmapped step never materializes a cross-worker
        reduced gradient — so the zero-mode rules run as 'off'."""
        from deeplearning4j_tpu.analysis.shardcheck import (
            check_step_program, param_leaf_sizes,
        )
        ctx = dict(weight_update_sharding="off", dp=self.mesh.n_data,
                   gradient_accumulation=1, precision=self.precision,
                   expect_donation=True,
                   # parameter averaging is not the dp gradient
                   # exchange the SC007 ring model predicts — skip it
                   check_cost=False,
                   param_leaf_sizes=param_leaf_sizes(self._stacked_params))
        ctx.update(overrides)
        return check_step_program(self.step_program(batch), **ctx)

    # ------------------------------------------------------------------- fit
    def _ensure_vstep(self) -> None:
        if (self._vstep is None
                or getattr(self, "_vstep_sentinel", None)
                is not getattr(self.net, "_sentinel", None)):
            # sentinel changed since the last build: the guarded step is
            # a different program — rebuild
            self._vstep_sentinel = getattr(self.net, "_sentinel", None)
            self._vstep = self._build_vmapped_step()

    def fit_batch(self, batch: DataSet) -> float:
        """One parallel iteration on ONE global minibatch, split evenly
        across the workers — the per-batch seam FaultTolerantTrainer
        drives (``fit`` remains the reference's round-robin path). The
        global batch must divide evenly by ``workers``: padding the
        tail by reuse here would silently double-train examples every
        step. Syncs worker-0 state back into the wrapped net afterward
        so a mid-run checkpoint sees current weights."""
        self._ensure_vstep()
        n = batch.num_examples()
        if n % self.workers:
            raise ValueError(
                f"global batch of {n} examples not divisible by "
                f"workers={self.workers}")
        self._parallel_iteration(batch.batch_by(n // self.workers))
        self._sync_to_net()
        return self.net.score_value

    def fit(self, iterator: Union[DataSetIterator, DataSet],
            epochs: int = 1) -> "ParallelWrapper":
        """Round-robin dispatch of minibatches to workers; average every
        ``averaging_frequency`` parallel iterations (ref: fit():343-466)."""
        self._ensure_vstep()
        if isinstance(iterator, DataSet):
            from deeplearning4j_tpu.datasets.iterator import ListDataSetIterator
            iterator = ListDataSetIterator(
                iterator.batch_by(max(1, iterator.num_examples() // self.workers)))
        if hasattr(iterator, "attach"):
            # streaming input pipeline: keep batches HOST-side — the
            # wrapper stacks ``workers`` batches along a new leading
            # axis before placement, so per-batch device staging would
            # only force a gather-restack round trip
            iterator.attach(place=False)
        it = (AsyncDataSetIterator(iterator, queue_size=self.prefetch_buffer)
              if iterator.async_supported() else iterator)
        net = self.net
        for _ in range(epochs):
            pending: List[DataSet] = []
            for batch in it:
                pending.append(batch)
                if len(pending) < self.workers:
                    continue
                self._parallel_iteration(pending)
                pending = []
            if pending:
                # pad the final incomplete dispatch by reusing batches
                # (the reference simply skips the barrier for missing workers;
                # reuse keeps shapes static for jit)
                while len(pending) < self.workers:
                    pending.append(pending[-1])
                self._parallel_iteration(pending)
            net.epoch_count += 1
        self._sync_to_net()
        return self

    def _parallel_iteration(self, batches: List[DataSet]) -> None:
        net = self.net
        tracer = get_tracer()
        # global-tracer span (profiling/): the vmapped all-worker step —
        # the open-span stack names this phase if a dispatch ever hangs
        with tracer.span("parallel_iteration", workers=self.workers):
            feats = jnp.stack([jnp.asarray(b.features) for b in batches])
            labels = jnp.stack([jnp.asarray(b.labels) for b in batches])
            net._rng, k = jax.random.split(net._rng)
            rngs = jax.random.split(k, self.workers)
            self._iter_since_avg += 1
            do_avg = jnp.asarray(
                self._iter_since_avg >= self.averaging_frequency)
            (self._stacked_params, self._stacked_opt, self._stacked_states,
             losses, bads) = self._vstep(
                 self._stacked_params, self._stacked_opt,
                 self._stacked_states, feats, labels, rngs, do_avg)
            if bool(do_avg):
                self._iter_since_avg = 0
        net.iteration_count += 1
        if hasattr(net, "_observe_sentinel"):
            # per-worker flag vector; the sentinel any()s it on drain
            net._observe_sentinel(None if isinstance(bads, tuple) else bads)
        net.last_grads = None  # vmapped worker step doesn't collect grads
        net.score_value = float(jnp.mean(losses))
        net.last_batch_size = sum(b.num_examples() for b in batches)
        with tracer.span("listener"):
            for listener in net.listeners:
                listener.iteration_done(net, net.iteration_count,
                                        net.score_value)

    def _sync_to_net(self) -> None:
        """Write worker-0 (post-averaging) state back into the wrapped net,
        as the reference copies averaged params into the source model."""
        self.net.params = jax.tree.map(lambda x: x[0], self._stacked_params)
        self.net.states = jax.tree.map(lambda x: x[0], self._stacked_states)
        self.net.opt_state = jax.tree.map(
            lambda x: x[0] if hasattr(x, "shape") and jnp.ndim(x) > 0 else x,
            self._stacked_opt)
