"""Multi-host (multi-process) SPMD support.

The reference's multi-node tier is Spark parameter averaging
(ref: spark/dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster.java:
358-420 — driver splits the RDD, executors fit, tree-aggregate averages).
TPU-native, the cluster program IS the single jitted step: every host runs
the same program, `jax.distributed` wires the processes into one global
device mesh, per-host input pipelines feed process-local batch shards, and
XLA's collectives ride ICI within a slice / DCN across slices.

Usage (one call per process, before any jax computation):

    from deeplearning4j_tpu.parallel import multihost
    multihost.initialize(coordinator="host0:1234",
                         num_processes=8, process_id=k)   # TPU pods: no-op
    ctx = MeshContext.create()          # global mesh over all processes
    trainer = ParallelTrainer(net, ctx) # feed process-LOCAL batches

On TPU pods jax.distributed auto-detects everything, so ``initialize()``
with no args is correct there too.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

_initialized = False


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids=None) -> None:
    """Bring this process into the global runtime
    (wraps jax.distributed.initialize; safe to call once per process).

    The Spark-era analog is the driver/executor bootstrap; here every
    process is a peer and process 0 hosts the coordination service.
    """
    global _initialized
    if _initialized:
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def local_batch_slice(global_batch: int) -> slice:
    """This host's slice of a [0, global_batch) range — the per-host input
    shard (the reference's RDD split -> executor partition mapping)."""
    n = jax.process_count()
    if global_batch % n != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {n}")
    per = global_batch // n
    k = jax.process_index()
    return slice(k * per, (k + 1) * per)


def global_array(local_data, sharding):
    """Assemble a GLOBAL jax.Array from this process's LOCAL batch shard
    (jax.make_array_from_process_local_data) — the host-boundary crossing
    the Spark tier did with broadcast/collect, done zero-copy per host."""
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_data))


def shard_sources(sources):
    """THIS host's disjoint strided shard of a dataset source list —
    shard ``process_index()`` of ``process_count()`` (the per-host input
    contract: no two hosts ever read the same bytes). Single-process:
    identity."""
    from deeplearning4j_tpu.datasets.pipeline import (
        shard_sources as _shard)
    return _shard(sources, jax.process_count(), jax.process_index())


def input_pipeline(sources, mesh=None, **kwargs):
    """Per-host sharded :class:`~deeplearning4j_tpu.datasets.pipeline.
    StreamingInputPipeline`: this process reads source shard
    ``process_index()`` of ``process_count()`` and — when ``mesh`` is a
    ``MeshContext`` (or left None and the pipeline is handed to
    ``ParallelTrainer.fit``, which attaches its own) — stages each batch
    as this host's slice of the GLOBAL sharded batch array
    (``make_array_from_process_local_data``). Feed the result to
    ``data_parallel_trainer(...).fit`` as-is; every host runs the same
    call on the same source list."""
    from deeplearning4j_tpu.datasets.pipeline import StreamingInputPipeline
    kwargs.setdefault("num_shards", jax.process_count())
    kwargs.setdefault("shard_index", jax.process_index())
    return StreamingInputPipeline(sources, mesh=mesh, **kwargs)


def data_parallel_trainer(net, n_model: int = 1,
                          gradient_accumulation: int = 1,
                          weight_update_sharding=None, **kwargs):
    """One-call multihost trainer: build the global mesh over every
    process's devices and wrap ``net`` in a ``ParallelTrainer``.

    ``weight_update_sharding="zero1"`` shards the weight update and the
    optax state 1/dp across the WHOLE data axis (all chips of all
    processes): each process's addressable shard of Adam's m+v is only
    ``local_devices/global_devices`` of the replicated footprint, and
    the sharded checkpoint format persists exactly those addressable
    shards per process — updater-state writes scale out with the pod
    instead of funneling through one host.

    Call ``initialize()`` first (TPU pods: with no args). Every process
    then feeds process-LOCAL batch shards to ``fit_batch`` as usual.
    """
    from deeplearning4j_tpu.parallel.mesh import MeshContext
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
    ctx = MeshContext.create(n_model=n_model)
    return ParallelTrainer(
        net, ctx, gradient_accumulation=gradient_accumulation,
        weight_update_sharding=weight_update_sharding, **kwargs)
