"""Multi-host (multi-process) SPMD support.

The reference's multi-node tier is Spark parameter averaging
(ref: spark/dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster.java:
358-420 — driver splits the RDD, executors fit, tree-aggregate averages).
TPU-native, the cluster program IS the single jitted step: every host runs
the same program, `jax.distributed` wires the processes into one global
device mesh, per-host input pipelines feed process-local batch shards, and
XLA's collectives ride ICI within a slice / DCN across slices.

Usage (one call per process, before any jax computation):

    from deeplearning4j_tpu.parallel import multihost
    multihost.initialize(coordinator="host0:1234",
                         num_processes=8, process_id=k)   # TPU pods: no-op
    ctx = MeshContext.create()          # global mesh over all processes
    trainer = ParallelTrainer(net, ctx) # feed process-LOCAL batches

On TPU pods jax.distributed auto-detects everything, so ``initialize()``
with no args is correct there too.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import List, Optional, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)

_initialized = False

#: runtime liveness windows used in ELASTIC mode. The coordination
#: service's own health checking is all-or-nothing: a missed heartbeat
#: propagates a fatal error to every task (jax's default callback
#: terminates the process — the opposite of surviving a preemption).
#: Elastic mode therefore dials the runtime's windows up to "never"
#: and supplies its own liveness layer (resilience/elastic.py heartbeat
#: files + step-barrier timeouts), which can tell a slow host from a
#: dead one and react without killing the fleet.
_ELASTIC_HEARTBEAT_INTERVAL_S = 3600
_ELASTIC_MAX_MISSING_HEARTBEATS = 1000

#: statuses delivered to the benign missed-heartbeat callback (elastic
#: mode); resilience/elastic.py reads these as one more failure signal
_runtime_faults: List[str] = []
_runtime_faults_lock = threading.Lock()


def _on_runtime_fault(status) -> None:
    # replaces jax's default callback (which LOG(FATAL)s the process)
    with _runtime_faults_lock:
        _runtime_faults.append(str(status))
    logger.warning("distributed runtime fault (benign in elastic mode): %s",
                   status)


def runtime_fault_count() -> int:
    """Distributed-runtime faults seen by the elastic client's benign
    missed-heartbeat callback (0 outside elastic mode)."""
    with _runtime_faults_lock:
        return len(_runtime_faults)


def _ensure_cpu_collectives() -> None:
    """On the CPU platform, cross-process computations need a real
    collectives backend — without one XLA rejects every multi-process
    program ("Multiprocess computations aren't implemented on the CPU
    backend"). Select gloo before the backend initializes; harmless on
    TPU/GPU (flag only consulted by the CPU client factory)."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or \
            str(jax.config.jax_platforms or "").startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jaxlib without gloo: keep prior behavior
            logger.warning("gloo CPU collectives unavailable; multi-process "
                           "CPU computations will not run")
        # NOTE: do NOT disable XLA's thunk runtime here to dodge the
        # gloo slot race (see gloo_collectives_active): the legacy CPU
        # runtime turns a gloo all-reduce failing on a dead peer into a
        # FATAL check — the SURVIVOR aborts with its killed peer, which
        # breaks elastic recovery. The thunk runtime leaves that
        # collective hanging, which the elastic layer's abandonable
        # step thread + bounded barrier waits are built to detect.


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids=None,
               elastic: bool = False,
               host_service: Optional[bool] = None) -> None:
    """Bring this process into the global runtime
    (wraps jax.distributed.initialize; safe to call once per process).

    The Spark-era analog is the driver/executor bootstrap; here every
    process is a peer and process 0 hosts the coordination service.

    ``elastic=True`` builds the distributed runtime for preemption
    tolerance (the contract ``resilience/elastic.py`` needs): the
    coordination client is constructed with a benign missed-heartbeat
    callback instead of jax's default process-terminating one, with
    ``shutdown_on_destruction`` off (a survivor must not run the
    shutdown barrier against dead peers at exit), and with liveness
    windows long enough that the runtime never declares a peer dead on
    its own — host-failure detection belongs to the elastic layer's
    heartbeat files + step-barrier timeouts, which can actually react.
    Elastic mode requires explicit coordinator/num_processes/process_id
    (no TPU-pod auto-detection yet).

    ``host_service`` (elastic mode only) controls whether THIS process
    hosts the runtime's coordination service. Default (None): process 0
    hosts it, the classic wiring — sufficient when rank 0's loss is
    handled by restart. Pass ``host_service=False`` on every process
    and run the service EXTERNALLY (``serve_coordination`` /
    ``python -m deeplearning4j_tpu.parallel.multihost serve <port>
    <n>``) for full rank-0 survivability: jaxlib's coordination client
    polls the service for errors from a background thread, and losing
    the service mid-poll ABORTS the surviving client process
    (observed: ``coordination_service_agent ... Polled an error`` ->
    ``std::bad_cast`` terminate) — no Python-level knob can catch it,
    so the service must simply outlive every training host. An
    external service owned by the scheduler/driver does exactly that;
    after it, losing ANY training host — rank 0 included — is
    detected and survived by the elastic layer's own lease/heartbeat
    protocol.
    """
    global _initialized
    if _initialized:
        return
    _ensure_cpu_collectives()
    if host_service is not None and not elastic:
        raise ValueError(
            "host_service is an elastic-mode knob (external coordination "
            "service); without elastic=True jax.distributed.initialize "
            "would still make process 0 host its own service and the two "
            "would fight over the coordinator port — pass elastic=True, "
            "or drop host_service")
    if elastic:
        if coordinator is None or num_processes is None or process_id is None:
            raise ValueError(
                "elastic initialize needs explicit coordinator, "
                "num_processes and process_id (auto-detection would hand "
                "the runtime back its fatal health checking)")
        if local_device_ids is not None:
            raise ValueError(
                "local_device_ids is not supported with elastic=True "
                "(the direct client bootstrap does not thread device "
                "visibility); pin devices via CUDA_VISIBLE_DEVICES / "
                "JAX flags instead")
        _initialize_elastic(coordinator, num_processes, process_id,
                            host_service=host_service)
        _initialized = True
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(**kwargs)
    _initialized = True


def _initialize_elastic(coordinator: str, num_processes: int,
                        process_id: int,
                        host_service: Optional[bool] = None) -> None:
    """The preemption-tolerant bootstrap: same wiring as
    jax.distributed.initialize, but the client is built directly so the
    failure-handling knobs jax does not expose can be set. Process 0
    hosts the runtime's coordination service, but that service is NOT
    the liveness authority: with the benign callback + hour-scale
    windows below, a peer losing the service-hosting process (rank 0
    included) keeps running — its stuck collectives are detected by the
    elastic layer's own heartbeat files + bounded step-barrier waits,
    and the lease-based rendezvous protocol (resilience/elastic.py)
    elects the lowest surviving rank as the new coordinator. After a
    restart the outer scheduler renumbers survivors, so whichever
    process is the NEW rank 0 hosts a fresh service — the service
    follows the lease, never the other way around."""
    from jax._src import distributed as jdist
    from jax._src import xla_bridge
    from jax._src.lib import xla_extension

    if xla_bridge.backends_are_initialized():
        raise RuntimeError("multihost.initialize(elastic=True) must be "
                           "called before any JAX computation")
    gs = jdist.global_state
    if gs.client is not None:
        raise RuntimeError("distributed runtime already initialized")
    if host_service is None:
        host_service = process_id == 0
    if host_service:
        port = coordinator.rsplit(":", 1)[1]
        gs.service = xla_extension.get_distributed_runtime_service(
            f"[::]:{port}", num_processes,
            heartbeat_interval=_ELASTIC_HEARTBEAT_INTERVAL_S,
            max_missing_heartbeats=_ELASTIC_MAX_MISSING_HEARTBEATS)
    gs.client = xla_extension.get_distributed_runtime_client(
        coordinator, process_id, init_timeout=300,
        heartbeat_interval=_ELASTIC_HEARTBEAT_INTERVAL_S,
        max_missing_heartbeats=_ELASTIC_MAX_MISSING_HEARTBEATS,
        missed_heartbeat_callback=_on_runtime_fault,
        shutdown_on_destruction=False, use_compression=True)
    gs.client.connect()
    gs.process_id = process_id
    gs.num_processes = num_processes
    gs.coordinator_address = coordinator


# ---------------------------------------------------------------------------
# effective topology — the resize seam
# ---------------------------------------------------------------------------
# After an elastic resize the surviving world differs from what
# jax.process_count() reports (the runtime's view is frozen at
# initialize time). Everything that reasons about the per-host data/
# checkpoint contract — local_batch_slice, shard_sources, the sharded
# checkpoint writer — goes through these accessors so the elastic layer
# can install the post-resize world without re-initializing jax.

_topology_override: Optional[Tuple[int, int]] = None  # (count, index)

#: the current rendezvous epoch (resilience/elastic.py's lease-based
#: group-membership counter: +1 per resize, shrink OR grow). Stamped
#: into every checkpoint cursor/manifest via CheckpointManager.topology
#: so a restore can tell which incarnation of the fleet cut it; 0
#: outside elastic runs.
_rendezvous_epoch: int = 0


def set_rendezvous_epoch(epoch: int) -> None:
    """Install the current rendezvous epoch (called by ElasticTrainer
    at bootstrap and on every lease transition — election or scale-up
    admission). Checkpoint topology records pick it up from here."""
    global _rendezvous_epoch
    _rendezvous_epoch = int(epoch)


def rendezvous_epoch() -> int:
    """The lease-based coordination layer's current epoch (0 when not
    training elastically)."""
    return _rendezvous_epoch


def set_topology_override(count: int, index: int) -> None:
    """Install the post-resize world: ``count`` surviving processes,
    this one at rank ``index``. Called by ElasticTrainer after a host
    loss; also useful for tests. ``clear_topology_override`` restores
    the runtime's own view."""
    global _topology_override
    if not 0 <= index < count:
        raise ValueError(f"rank {index} outside world of {count}")
    _topology_override = (int(count), int(index))


def clear_topology_override() -> None:
    global _topology_override
    _topology_override = None


def effective_process_count() -> int:
    """Surviving-world process count (== jax.process_count() until an
    elastic resize installs an override)."""
    if _topology_override is not None:
        return _topology_override[0]
    return jax.process_count()


def gloo_collectives_active() -> bool:
    """True when cross-process collectives run over the gloo CPU
    backend (the path ``_ensure_cpu_collectives`` selects).

    Gloo reuses one set of per-executable collective tags, so two
    async in-flight runs of the SAME compiled step — jax dispatch
    returns before the param-update all-reduce lands — can collide on
    a TCP pair and abort the whole process
    (``gloo::EnforceNotMet: op.preamble.length <= op.nbytes``).
    Callers stepping in a loop on this path must drain each step
    (``jax.block_until_ready`` on params + updater state) before
    dispatching the next; on TPU/GPU this is unnecessary and the
    helper returns False so pipelining is preserved."""
    if effective_process_count() <= 1:
        return False
    return (os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
            or str(jax.config.jax_platforms or "").startswith("cpu"))


def effective_process_index() -> int:
    """This process's rank in the surviving world."""
    if _topology_override is not None:
        return _topology_override[1]
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def local_batch_slice(global_batch: int) -> slice:
    """This host's slice of a [0, global_batch) range — the per-host input
    shard (the reference's RDD split -> executor partition mapping).
    Honors the elastic topology override: after a resize the survivors
    split the same global batch among themselves."""
    n = effective_process_count()
    if global_batch % n != 0:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {n}")
    per = global_batch // n
    k = effective_process_index()
    return slice(k * per, (k + 1) * per)


def global_array(local_data, sharding):
    """Assemble a GLOBAL jax.Array from this process's LOCAL batch shard
    (jax.make_array_from_process_local_data) — the host-boundary crossing
    the Spark tier did with broadcast/collect, done zero-copy per host."""
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_data))


def shard_sources(sources):
    """THIS host's disjoint strided shard of a dataset source list —
    shard ``effective_process_index()`` of ``effective_process_count()``
    (the per-host input contract: no two hosts ever read the same
    bytes; after an elastic resize the survivors re-partition the same
    source list). Single-process: identity."""
    from deeplearning4j_tpu.datasets.pipeline import (
        shard_sources as _shard)
    return _shard(sources, effective_process_count(),
                  effective_process_index())


def input_pipeline(sources, mesh=None, **kwargs):
    """Per-host sharded :class:`~deeplearning4j_tpu.datasets.pipeline.
    StreamingInputPipeline`: this process reads source shard
    ``process_index()`` of ``process_count()`` and — when ``mesh`` is a
    ``MeshContext`` (or left None and the pipeline is handed to
    ``ParallelTrainer.fit``, which attaches its own) — stages each batch
    as this host's slice of the GLOBAL sharded batch array
    (``make_array_from_process_local_data``). Feed the result to
    ``data_parallel_trainer(...).fit`` as-is; every host runs the same
    call on the same source list."""
    from deeplearning4j_tpu.datasets.pipeline import StreamingInputPipeline
    kwargs.setdefault("num_shards", effective_process_count())
    kwargs.setdefault("shard_index", effective_process_index())
    return StreamingInputPipeline(sources, mesh=mesh, **kwargs)


def serve_coordination(port: int, num_processes: int) -> None:
    """Run the distributed runtime's coordination service in a process
    of its OWN (no training, no devices): the external-service half of
    rank-0-survivable elastic training. Every training process then
    calls ``initialize(..., elastic=True, host_service=False)`` —
    whichever training host dies, the service (and with it the
    surviving clients' error-poll streams) stays up, so survival is
    decided entirely by the lease/heartbeat protocol. Liveness windows
    are the elastic ones (effectively never), because host-failure
    detection belongs to resilience/elastic.py. Prints ``READY`` once
    listening; blocks until terminated (the scheduler/driver owns the
    lifecycle and kills it after the job)."""
    import sys
    import time as _time

    from jax._src.lib import xla_extension
    service = xla_extension.get_distributed_runtime_service(
        f"[::]:{int(port)}", int(num_processes),
        heartbeat_interval=_ELASTIC_HEARTBEAT_INTERVAL_S,
        max_missing_heartbeats=_ELASTIC_MAX_MISSING_HEARTBEATS)
    print(f"READY coordination service on port {port} for "
          f"{num_processes} processes", flush=True)
    try:
        while True:
            _time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        service.shutdown()
        print("coordination service shut down", file=sys.stderr, flush=True)


def data_parallel_trainer(net, n_model: int = 1,
                          gradient_accumulation: int = 1,
                          weight_update_sharding=None,
                          precision=None, tuned=None, **kwargs):
    """One-call multihost trainer: build the global mesh over every
    process's devices and wrap ``net`` in a ``ParallelTrainer``.

    ``weight_update_sharding="zero1"`` shards the weight update and the
    optax state 1/dp across the WHOLE data axis (all chips of all
    processes): each process's addressable shard of Adam's m+v is only
    ``local_devices/global_devices`` of the replicated footprint, and
    the sharded checkpoint format persists exactly those addressable
    shards per process — updater-state writes scale out with the pod
    instead of funneling through one host. ``"zero2"`` additionally
    keeps the GRADIENTS in that 1/dp layout from the reduce-scatter
    onward (no full-size reduced gradient per replica), so gradient
    HBM scales out with the pod too.

    ``precision="bf16"`` (or a ``PrecisionPolicy``) runs every
    process's forward/backward in bfloat16 against fp32 master weights
    — same cast seams as ``ParallelTrainer``; composes with every
    weight-update-sharding mode.

    ``tuned`` (a ``TunedConfig`` from ``deeplearning4j_tpu.autotune``):
    run at the autotuner's chosen configuration — supplies
    ``n_model`` (its tp width) plus the accumulation / sharding /
    precision knobs left at their defaults, over the GLOBAL device
    mesh. Explicit kwargs win, exactly as on ``ParallelTrainer``.

    Call ``initialize()`` first (TPU pods: with no args). Every process
    then feeds process-LOCAL batch shards to ``fit_batch`` as usual.
    """
    from deeplearning4j_tpu.parallel.mesh import MeshContext
    from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
    if tuned is not None:
        if tuned.pp > 1:
            # the flat dp x tp (x sp) mesh this helper builds cannot
            # carry a pipeline schedule — running anyway would silently
            # train a DIFFERENT layout than the TunedConfig promises
            raise ValueError(
                f"TunedConfig plans pp={tuned.pp}; "
                "multihost.data_parallel_trainer builds a flat mesh — "
                "build a PipelineTrainer from tuned.candidate instead")
        if n_model == 1:
            n_model = tuned.tp
    ctx = MeshContext.create(n_model=n_model,
                             n_seq=tuned.sp if tuned is not None else 1)
    if tuned is not None and len(ctx.mesh.devices.flat) \
            != tuned.device_count:
        logger.warning(
            "TunedConfig was searched for %d device(s) but the global "
            "mesh has %d — the tuned knobs still apply, but re-running "
            "autotune() at this fleet size may choose differently",
            tuned.device_count, len(ctx.mesh.devices.flat))
    return ParallelTrainer(
        net, ctx, gradient_accumulation=gradient_accumulation,
        weight_update_sharding=weight_update_sharding,
        precision=precision, tuned=tuned, **kwargs)


if __name__ == "__main__":   # pragma: no cover — thin sidecar CLI
    # python -m deeplearning4j_tpu.parallel.multihost serve <port> <nprocs>
    import sys as _sys
    if len(_sys.argv) == 4 and _sys.argv[1] == "serve":
        serve_coordination(int(_sys.argv[2]), int(_sys.argv[3]))
    else:
        _sys.exit("usage: python -m deeplearning4j_tpu.parallel.multihost "
                  "serve <port> <num_processes>")
