"""Expert parallelism: mixture-of-experts FFN with sharded experts.

No counterpart in the reference (SURVEY §2.3); part of the TPU build's
first-class scale-out. Mesh-TensorFlow-style dense dispatch: top-1 gating
produces a dispatch tensor, token->expert routing is an einsum, and with
the expert axis of the stacked expert weights sharded over mesh axis
``ep``, XLA lowers the dispatch/combine einsums to all-to-all over ICI —
no hand-written collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers.base import (
    Array, BaseLayerConf, Params, register_layer,
)
from deeplearning4j_tpu.ops.activations import get_activation


def moe_dispatch(gates: Array, capacity: int):
    """Top-1 dispatch/combine tensors (Switch-style).

    gates: [N, E] softmax scores. Returns (dispatch [N, E, C] one-hot,
    combine [N, E, C] gate-weighted, aux_loss scalar).
    """
    N, E = gates.shape
    expert_idx = jnp.argmax(gates, axis=-1)                       # [N]
    onehot = jax.nn.one_hot(expert_idx, E, dtype=gates.dtype)     # [N, E]
    # position of each token within its expert's buffer
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot             # [N, E]
    keep = (pos < capacity).astype(gates.dtype) * onehot
    pos_clipped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
    pos_onehot = jax.nn.one_hot(pos_clipped, capacity, dtype=gates.dtype)
    dispatch = keep[..., None] * pos_onehot                       # [N, E, C]
    gate_val = jnp.sum(gates * onehot, axis=-1, keepdims=True)    # [N, 1]
    combine = dispatch * gate_val[..., None]
    # Switch load-balancing loss: E * sum_e (fraction_tokens_e * mean_gate_e)
    frac = jnp.mean(onehot, axis=0)
    mean_gate = jnp.mean(gates, axis=0)
    aux = E * jnp.sum(frac * mean_gate)
    return dispatch, combine, aux


def moe_ffn(params: Params, x: Array, activation: str = "relu",
            capacity_factor: float = 1.25):
    """x: [N, F] tokens. params: Wg [F, E]; W1 [E, F, H]; b1 [E, H];
    W2 [E, H, F]; b2 [E, F]. Returns ([N, F], aux_loss)."""
    N, F = x.shape
    E = params["Wg"].shape[-1]
    capacity = max(1, int(capacity_factor * N / E))
    gates = jax.nn.softmax(x @ params["Wg"], axis=-1)
    dispatch, combine, aux = moe_dispatch(gates, capacity)
    # token -> expert buffers (XLA: all_to_all when E is sharded over 'ep')
    expert_in = jnp.einsum("nec,nf->ecf", dispatch, x)            # [E, C, F]
    act = get_activation(activation)
    h = act(jnp.einsum("ecf,efh->ech", expert_in, params["W1"])
            + params["b1"][:, None, :])
    expert_out = (jnp.einsum("ech,ehf->ecf", h, params["W2"])
                  + params["b2"][:, None, :])                     # [E, C, F]
    out = jnp.einsum("nec,ecf->nf", combine, expert_out)          # [N, F]
    return out, aux


@register_layer
@dataclass
class MoELayer(BaseLayerConf):
    """Mixture-of-experts FFN layer over [B, F] (or [B, T, F] flattened to
    tokens). Stacked expert weights carry a leading expert axis — shard it
    over an 'ep' mesh axis for expert parallelism."""
    n_experts: int = 8
    hidden: int = 0           # expert FFN hidden width; default 4*F
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2

    def set_n_in(self, in_type: InputType) -> None:
        self.n_in = in_type.size if in_type.kind == "rnn" else in_type.flat_size()
        if not self.hidden:
            self.hidden = 4 * self.n_in

    def infer_output_type(self, in_type: InputType) -> InputType:
        return in_type

    def param_order(self) -> List[str]:
        return ["Wg", "W1", "b1", "W2", "b2"]

    def init_params(self, rng, dtype=jnp.float32) -> Params:
        F, E, H = self.n_in, self.n_experts, self.hidden
        ks = jax.random.split(rng, 3)
        return {
            "Wg": self._init_w(ks[0], (F, E), F, E, dtype),
            "W1": self._init_w(ks[1], (E, F, H), F, H, dtype),
            "b1": jnp.zeros((E, H), dtype),
            "W2": self._init_w(ks[2], (E, H, F), H, F, dtype),
            "b2": jnp.zeros((E, F), dtype),
        }

    def apply(self, params, x, *, state, train, rng, mask=None):
        shape = x.shape
        tokens = x.reshape(-1, shape[-1])
        out, aux = moe_ffn(params, tokens, self.activation or "relu",
                           self.capacity_factor)
        # aux loss surfaces through state so the container can add it
        new_state = dict(state)
        new_state["aux_loss"] = aux * self.aux_loss_weight
        return out.reshape(shape), new_state

    def init_state(self):
        return {"aux_loss": jnp.zeros(())}
