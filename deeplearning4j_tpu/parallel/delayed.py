"""Delayed-synchronization data parallelism (the DP-2 parameter-server
analog).

Ref: deeplearning4j-scaleout-parallelwrapper-parameter-server/.../
ParameterServerParallelWrapper.java:289-345 — workers train against a
parameter server, pushing gradients and pulling (possibly stale) params on
a cadence instead of synchronizing every step. SURVEY §2.3 maps that tier
to "local accumulation + delayed all-reduce" for slow interconnects (the
multi-pod DCN tier, where a param-sized collective every step is the
bottleneck).

TPU-native design: params stay REPLICATED; each worker's gradients
accumulate into a per-worker buffer whose leading axis is sharded over the
'data' mesh axis — the accumulation is purely local (no collective). Every
``sync_frequency`` steps the buffer is averaged over the worker axis (the
ONE param-sized all-reduce) and a single optimizer update is applied.
Between syncs workers compute gradients at the stale (last-synced) params
— exactly the staleness the PS tier tolerates — and the updater state only
advances at sync points, so it never sees unsynchronized gradients.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator, DataSetIterator,
)
from deeplearning4j_tpu.nn.updater import compute_updates
from deeplearning4j_tpu.parallel.mesh import MeshContext


class DelayedSyncTrainer:
    """k-step delayed-sync data-parallel trainer (MLN or graph)."""

    def __init__(self, net, mesh: Optional[MeshContext] = None,
                 sync_frequency: int = 4):
        net._check_init()
        self.net = net
        self.mesh = mesh or MeshContext.create()
        self.sync_frequency = max(1, sync_frequency)
        self.workers = self.mesh.n_data
        self._is_graph = not hasattr(net, "layers")
        self._layers = (
            [net.conf.nodes[n].layer for n in net._layer_nodes]
            if self._is_graph else net.layers)
        rep = self.mesh.replicated()
        net.params = jax.tree.map(lambda x: jax.device_put(x, rep),
                                  net.params)
        net.states = jax.tree.map(lambda x: jax.device_put(x, rep),
                                  net.states)
        # preserve accumulated optimizer state (see ParallelTrainer)
        net.opt_state = jax.tree.map(
            lambda x: jax.device_put(x, rep) if hasattr(x, "shape") else x,
            net.opt_state)
        # per-worker gradient accumulator, worker axis sharded over 'data'
        # — accumulation never crosses devices. Each process contributes
        # its local slice of the worker axis (shard_batch assembles the
        # global array in the multi-process case).
        W = self.workers
        w_local = W // max(jax.process_count(), 1)
        self._gbuf = jax.tree.map(
            lambda x: self.mesh.shard_batch(
                jnp.zeros((w_local if jax.process_count() > 1 else W,)
                          + x.shape, x.dtype)),
            net.params)
        self._since_sync = 0
        self._step = None

    def _build_step(self):
        net = self.net
        training = net.conf.training
        tx = net._tx
        layers = self._layers
        k = self.sync_frequency

        def loss_fn(p, states, feats, labels, fmask, lmask, rng):
            return net._loss_fn(p, states, feats, labels, fmask, lmask,
                                rng=rng, train=True)

        def step(params, opt_state, states, gbuf, feats, labels, fmask,
                 lmask, rngs, do_sync):
            def one(f, l, fm, lm, r):
                (loss, st2), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, states, f, l, fm, lm, r)
                return g, loss, st2

            # per-worker grads: batch worker-axis is 'data'-sharded, so
            # this vmap runs one worker per device shard, NO collective
            grads, losses, states2 = jax.vmap(one)(feats, labels, fmask,
                                                   lmask, rngs)
            gbuf = jax.tree.map(lambda a, b: a + b, gbuf, grads)
            # states (BN stats etc.) are small — average every step
            new_states = jax.tree.map(
                lambda x: (jnp.mean(x, axis=0)
                           if jnp.issubdtype(x.dtype, jnp.floating)
                           else x[0]),
                states2)

            def sync(args):
                p, o, buf = args
                # the ONE param-sized all-reduce per k steps: mean over
                # the sharded worker axis, averaged over the k local steps
                g = jax.tree.map(lambda x: jnp.mean(x, axis=0) / k, buf)
                p2, o2 = compute_updates(tx, g, o, p, layers, training)
                return p2, o2, jax.tree.map(jnp.zeros_like, buf)

            params, opt_state, gbuf = jax.lax.cond(
                do_sync, sync, lambda a: a, (params, opt_state, gbuf))
            return params, opt_state, new_states, gbuf, jnp.mean(losses)

        return jax.jit(step, donate_argnums=(0, 1, 2, 3))

    # ------------------------------------------------------------------- fit
    def fit_batch(self, batch) -> float:
        if self._step is None:
            self._step = self._build_step()
        net = self.net
        W = self.workers
        if self._is_graph:
            inputs, labels, fmask, lmask = net._split(batch)
        else:
            inputs = jnp.asarray(batch.features)
            labels = jnp.asarray(batch.labels)
            fmask = (None if batch.features_mask is None
                     else jnp.asarray(batch.features_mask))
            lmask = (None if batch.labels_mask is None
                     else jnp.asarray(batch.labels_mask))

        # multi-process: each host holds 1/process_count of the worker
        # axis; reshape with the LOCAL worker count and let shard_batch
        # assemble the global [W, ...] array
        # (jax.make_array_from_process_local_data, as in multihost.py)
        n_proc = max(jax.process_count(), 1)
        if W % n_proc != 0:
            raise ValueError(f"{W} workers not divisible by {n_proc} "
                             "processes")
        w_local = W // n_proc

        def to_workers(x):
            B = x.shape[0]  # process-local batch
            if B % w_local != 0:
                raise ValueError(f"local batch {B} not divisible by "
                                 f"{w_local} local workers")
            x = x.reshape((w_local, B // w_local) + x.shape[1:])
            return self.mesh.shard_batch(x)

        feats = jax.tree.map(to_workers, inputs)
        labels = jax.tree.map(to_workers, labels)
        fmask = jax.tree.map(to_workers, fmask)
        lmask = jax.tree.map(to_workers, lmask)
        net._rng, key = jax.random.split(net._rng)
        rngs = jax.random.split(key, W)
        self._since_sync += 1
        do_sync = self._since_sync >= self.sync_frequency
        net.params, net.opt_state, net.states, self._gbuf, loss = \
            self._step(net.params, net.opt_state, net.states, self._gbuf,
                       feats, labels, fmask, lmask, rngs,
                       jnp.asarray(do_sync))
        if do_sync:
            self._since_sync = 0
        net.last_batch_size = batch.num_examples()
        net.last_grads = None  # delayed-sync step doesn't collect grads
        net.score_value = loss
        net.iteration_count += 1
        for listener in net.listeners:
            listener.iteration_done(net, net.iteration_count,
                                    net.score_value)
        return net._score_raw

    def fit(self, data: Union[DataSet, DataSetIterator], epochs: int = 1,
            use_async: bool = True) -> "DelayedSyncTrainer":
        if isinstance(data, DataSet):
            for _ in range(epochs):
                self.fit_batch(data)
            return self
        it = (AsyncDataSetIterator(data)
              if use_async and data.async_supported() else data)
        for _ in range(epochs):
            for b in it:
                self.fit_batch(b)
            self.net.epoch_count += 1
        return self

    def flush(self) -> None:
        """Force a synchronization now (end-of-training drain): applies
        whatever gradient is buffered, scaled by the actual number of
        accumulated steps."""
        if self._since_sync == 0:
            return
        n = self._since_sync
        g = jax.tree.map(lambda x: jnp.mean(x, axis=0) / n, self._gbuf)
        self.net.params, self.net.opt_state = compute_updates(
            self.net._tx, g, self.net.opt_state, self.net.params,
            self._layers, self.net.conf.training)
        self._gbuf = jax.tree.map(jnp.zeros_like, self._gbuf)
        self._since_sync = 0
