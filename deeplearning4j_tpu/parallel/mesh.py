"""Device-mesh management + sharding rules.

The scaling-model design: pick a Mesh with named axes
('data', 'model'), annotate params/batches with NamedShardings, let XLA
insert the collectives (psum for gradients over 'data', all-gather /
reduce-scatter for 'model'-sharded matmuls), profile, iterate. Replaces the
reference's AffinityManager device pinning (ParallelWrapper.java:348) and
every explicit parameter-blob exchange.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass
class MeshContext:
    """A named mesh plus the policy mapping framework state onto it."""
    mesh: Mesh
    data_axis: str = "data"
    model_axis: Optional[str] = "model"
    # shard a param's last axis over `model` only when it's at least this big
    min_shard_size: int = 1024

    @staticmethod
    def create(n_data: Optional[int] = None, n_model: int = 1,
               devices: Optional[Sequence] = None) -> "MeshContext":
        devices = list(devices if devices is not None else jax.devices())
        if n_data is None:
            n_data = len(devices) // n_model
        if n_data * n_model != len(devices):
            devices = devices[:n_data * n_model]
        arr = np.array(devices).reshape(n_data, n_model)
        mesh = Mesh(arr, axis_names=("data", "model"))
        return MeshContext(mesh=mesh,
                           model_axis=None if n_model == 1 else "model")

    @property
    def n_data(self) -> int:
        return self.mesh.shape[self.data_axis]

    @property
    def n_model(self) -> int:
        return self.mesh.shape.get("model", 1) if self.model_axis else 1

    # ------------------------------------------------------------- shardings
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, ndim: int) -> NamedSharding:
        """Shard the leading (batch) axis over 'data'."""
        return NamedSharding(self.mesh, P(self.data_axis,
                                          *([None] * (ndim - 1))))

    def param_spec(self, name: str, shape: Tuple[int, ...]) -> P:
        """Tensor-parallel policy: shard the output/feature (last) axis of
        large kernels over 'model'; replicate everything else. Matches the
        megatron-style column-parallel layout for dense/conv kernels."""
        if (self.model_axis is not None and len(shape) >= 2
                and shape[-1] % self.n_model == 0
                and int(np.prod(shape)) >= self.min_shard_size):
            return P(*([None] * (len(shape) - 1)), self.model_axis)
        return P()

    def param_sharding(self, name: str, shape) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(name, tuple(shape)))

    def shard_params(self, params):
        """device_put a param pytree according to the policy."""
        def put(path, x):
            name = "/".join(str(p) for p in path)
            return jax.device_put(x, self.param_sharding(name, x.shape))
        return jax.tree_util.tree_map_with_path(put, params)

    def shard_batch(self, *arrays):
        """Place batch arrays sharded over 'data'.

        Single-process: device_put of the (full) host batch. Multi-process:
        each host passes its process-LOCAL batch shard and the global array
        is assembled without any host ever holding the full batch
        (jax.make_array_from_process_local_data) — the per-host input
        sharding the reference's Spark tier did by RDD partitioning.
        """
        multi = jax.process_count() > 1
        out = []
        for a in arrays:
            if a is None:
                out.append(None)
            elif multi:
                out.append(jax.make_array_from_process_local_data(
                    self.batch_sharding(np.ndim(a)), np.asarray(a)))
            else:
                out.append(jax.device_put(a, self.batch_sharding(np.ndim(a))))
        return tuple(out) if len(out) > 1 else out[0]
