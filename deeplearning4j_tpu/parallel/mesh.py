"""Device-mesh management + sharding rules.

The scaling-model design: pick a Mesh with named axes
('data', 'model'), annotate params/batches with NamedShardings, let XLA
insert the collectives (psum for gradients over 'data', all-gather /
reduce-scatter for 'model'-sharded matmuls), profile, iterate. Replaces the
reference's AffinityManager device pinning (ParallelWrapper.java:348) and
every explicit parameter-blob exchange.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# ---------------------------------------------------------------------------
# weight-update sharding (ZeRO-1) config + layout helpers
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class WeightUpdateSharding:
    """How the data-parallel trainers lay out the *weight update*.

    ``off``   — the classic replicated layout: every replica holds full
    params AND the full optax updater state, gradients are all-reduced,
    and every chip applies the identical update (Adam's m+v cost 2x
    param HBM per replica for nothing).

    ``zero1`` — ZeRO-1 / "Automatic Cross-Replica Sharding of Weight
    Update in Data-Parallel Training" (arxiv 2004.13336): each optax
    state leaf is kept as a flattened, pad-to-divisible ``(dp, chunk)``
    view sharded 1/dp over ``axis``; the compiled step reduce-scatters
    gradients into that layout, applies the update to the local shard
    only, and all-gathers the updated params. Updater-state HBM drops by
    ``dp``x and, under ``gradient_accumulation=k``, per-update cross-chip
    traffic drops from ``2.P.k`` (an all-reduce per microbatch) to
    ``~P.(k+1)`` (a reduce-scatter per microbatch + one param gather).
    The transformation is an execution-layout change only — loss/param
    trajectories are exactly those of the replicated layout.

    ``zero2`` — ZeRO-2, the paper's next rung: same updater-state layout
    as ``zero1``, but the GRADIENTS also live only as the flattened
    ``(dp, chunk)`` shards from the reduce-scatter onward. ``zero1``
    anchors the reduced gradient replicated first (the exact
    replicated-mode program) before constraining the sharded view;
    ``zero2`` drops that anchor on the per-update path, so the compiled
    program never requires a full-size reduced gradient per replica —
    the accumulation buffer, mask/clip/optax math, and the divergence
    sentinel's grad-norm (a psum of shard norms) all run on the 1/dp
    views, gradient HBM drops by ``dp``x, and the only full-size
    collective left per update is the param all-gather. (Inside a
    ``gradient_accumulation`` scan the per-microbatch anchor is kept —
    GSPMD otherwise repartitions the scan body and parity dies; the
    sharded accumulator carries that path's 1/dp gradient memory.)
    Still an execution-layout change only: fp32 trajectories stay
    bitwise those of the replicated layout.
    """

    mode: str = "off"    # "off" | "zero1" | "zero2"
    axis: str = "data"

    MODES = ("off", "zero1", "zero2")

    def __post_init__(self):
        if self.mode not in self.MODES:
            raise ValueError(
                f"weight_update_sharding mode must be one of {self.MODES}, "
                f"got {self.mode!r}")

    @property
    def enabled(self) -> bool:
        """True when the weight update runs on the sharded ``(dp, chunk)``
        layout (zero1 and zero2 share all of that machinery)."""
        return self.mode in ("zero1", "zero2")

    @property
    def zero2(self) -> bool:
        """True when gradients live ONLY as shards (no replicated
        anchor) — the zero2 refinement on top of the shared layout."""
        return self.mode == "zero2"

    @staticmethod
    def parse(value: Union["WeightUpdateSharding", str, None]
              ) -> "WeightUpdateSharding":
        """Accept None / "off" / "zero1" / "zero2" / an instance — the
        form every trainer constructor takes."""
        if value is None:
            return WeightUpdateSharding()
        if isinstance(value, WeightUpdateSharding):
            return value
        return WeightUpdateSharding(mode=str(value))


def zero1_chunk(size: int, n: int) -> int:
    """Per-shard element count for a flattened leaf of ``size`` split
    ``n`` ways (pad-to-divisible)."""
    return -(-int(size) // max(1, n))


def zero1_shard_leaf(x, n: int):
    """Flattened pad-to-divisible ``(n, chunk)`` view of one leaf — the
    layout each optax state leaf (and the in-step gradient/param views)
    live in under zero1. Works traced and untraced."""
    flat = jnp.ravel(x)
    chunk = zero1_chunk(flat.size, n)
    pad = chunk * n - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(n, chunk)


def zero1_unshard_leaf(y, shape: Tuple[int, ...]):
    """Inverse of :func:`zero1_shard_leaf`: drop the padding tail and
    restore the original shape. (The padding-waste math lives in
    graphcheck's GC011 rule, which must stay importable without jax.)"""
    size = int(np.prod(shape)) if shape else 1
    return y.reshape(-1)[:size].reshape(shape)


@dataclass
class MeshContext:
    """A named mesh plus the policy mapping framework state onto it."""
    mesh: Mesh
    data_axis: str = "data"
    model_axis: Optional[str] = "model"
    seq_axis: Optional[str] = None   # 'sp' when sequence parallelism is on
    # shard a param's last axis over `model` only when it's at least this big
    min_shard_size: int = 1024

    @staticmethod
    def create(n_data: Optional[int] = None, n_model: int = 1,
               n_seq: int = 1,
               devices: Optional[Sequence] = None) -> "MeshContext":
        """``n_seq > 1`` adds an 'sp' mesh axis: SelfAttentionLayer routes
        through ring attention over it when trained by ParallelTrainer
        (VERDICT r3 #5; SURVEY §5.7 long-context extension)."""
        devices = list(devices if devices is not None else jax.devices())
        if n_data is None:
            n_data = len(devices) // (n_model * n_seq)
        need = n_data * n_model * n_seq
        if need != len(devices):
            devices = devices[:need]
        if n_seq > 1:
            arr = np.array(devices).reshape(n_data, n_model, n_seq)
            mesh = Mesh(arr, axis_names=("data", "model", "sp"))
        else:
            arr = np.array(devices).reshape(n_data, n_model)
            mesh = Mesh(arr, axis_names=("data", "model"))
        return MeshContext(mesh=mesh,
                           model_axis=None if n_model == 1 else "model",
                           seq_axis="sp" if n_seq > 1 else None)

    @property
    def n_data(self) -> int:
        return self.mesh.shape[self.data_axis]

    @property
    def n_model(self) -> int:
        return self.mesh.shape.get("model", 1) if self.model_axis else 1

    # ------------------------------------------------------------- shardings
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def zero1_sharding(self, axis: Optional[str] = None) -> NamedSharding:
        """Sharding of the flattened ``(dp, chunk)`` weight-update views:
        row i (one chunk of every leaf) lives on data-replica i only."""
        return NamedSharding(self.mesh, P(axis or self.data_axis, None))

    def zero1_shards(self, axis: Optional[str] = None) -> int:
        """Number of weight-update shards = size of the sharding axis."""
        return int(self.mesh.shape[axis or self.data_axis])

    def validate_weight_update_sharding(
            self, wus: "WeightUpdateSharding") -> None:
        """Raise early (trainer construction, not trace time) when the
        mesh cannot carry the requested weight-update layout."""
        if not wus.enabled:
            return
        if wus.axis not in self.mesh.axis_names:
            raise ValueError(
                f"weight_update_sharding axis {wus.axis!r} is not a mesh "
                f"axis (have {tuple(self.mesh.axis_names)})")
        if self.mesh.shape[wus.axis] < 2:
            raise ValueError(
                f"{wus.mode} weight-update sharding needs at least 2 "
                f"replicas on axis {wus.axis!r} (mesh has "
                f"{self.mesh.shape[wus.axis]}) — with dp=1 there is "
                "nothing to shard; use mode='off'")
        if self.n_model > 1:
            raise ValueError(
                f"{wus.mode} weight-update sharding composes with pure "
                "data parallelism only; this mesh tensor-shards params "
                f"over 'model' ({self.n_model} ways) — the updater state "
                "of a model-sharded kernel is already distributed")

    def batch_sharding(self, ndim: int,
                       shape: Optional[Tuple[int, ...]] = None
                       ) -> NamedSharding:
        """Shard the leading (batch) axis over 'data'; with a seq axis,
        rank-3 [B, T, F] batches whose T divides the axis also shard T
        over 'sp' so ring attention gets its sequence shards without an
        SPMD full rematerialization (non-divisible T falls back to
        data-only sharding — the attention layer declines the ring path
        for those shapes anyway)."""
        if (self.seq_axis is not None and ndim == 3
                and (shape is None
                     or shape[1] % self.mesh.shape[self.seq_axis] == 0)):
            return NamedSharding(self.mesh,
                                 P(self.data_axis, self.seq_axis, None))
        return NamedSharding(self.mesh, P(self.data_axis,
                                          *([None] * (ndim - 1))))

    def param_spec(self, name: str, shape: Tuple[int, ...]) -> P:
        """Tensor-parallel policy: shard the output/feature (last) axis of
        large kernels over 'model'; replicate everything else. Matches the
        megatron-style column-parallel layout for dense/conv kernels."""
        if (self.model_axis is not None and len(shape) >= 2
                and shape[-1] % self.n_model == 0
                and int(np.prod(shape)) >= self.min_shard_size):
            return P(*([None] * (len(shape) - 1)), self.model_axis)
        return P()

    def param_sharding(self, name: str, shape) -> NamedSharding:
        return NamedSharding(self.mesh, self.param_spec(name, tuple(shape)))

    def shard_params(self, params):
        """device_put a param pytree according to the policy."""
        def put(path, x):
            name = "/".join(str(p) for p in path)
            return jax.device_put(x, self.param_sharding(name, x.shape))
        return jax.tree_util.tree_map_with_path(put, params)

    def shard_batch(self, *arrays):
        """Place batch arrays sharded over 'data'.

        Single-process: device_put of the (full) host batch. Multi-process:
        each host passes its process-LOCAL batch shard and the global array
        is assembled without any host ever holding the full batch
        (jax.make_array_from_process_local_data) — the per-host input
        sharding the reference's Spark tier did by RDD partitioning.
        """
        multi = jax.process_count() > 1
        out = []
        for a in arrays:
            if a is None:
                out.append(None)
            elif (isinstance(a, jax.Array) and a.sharding
                    == self.batch_sharding(np.ndim(a), np.shape(a))):
                # already placed in this mesh's batch layout (the input
                # pipeline's device stage via attach(mesh=...)): pass
                # through. Re-placing would be a wasted no-op
                # single-process and a CRASH multi-process
                # (np.asarray on a global array whose shards live on
                # other hosts' devices).
                out.append(a)
            elif multi:
                # local T == global T (only the batch axis is split across
                # processes), so the shape-based sp-divisibility check holds
                out.append(jax.make_array_from_process_local_data(
                    self.batch_sharding(np.ndim(a), np.shape(a)),
                    np.asarray(a)))
            else:
                out.append(jax.device_put(
                    a, self.batch_sharding(np.ndim(a), np.shape(a))))
        return tuple(out) if len(out) > 1 else out[0]


# ---------------------------------------------------------------------------
# active sequence-parallel context (the seam SelfAttentionLayer reads)
# ---------------------------------------------------------------------------

_ACTIVE_SEQ_CTX: list = []


class sequence_parallel_scope:
    """While active, SelfAttentionLayer.apply routes attention through
    ring_attention_sharded over the context's 'sp' mesh axis. A no-op for
    meshes without a seq axis. ParallelTrainer enters this scope around
    its jitted step, so the routing decision is made at trace time and
    single-device paths (parity references, inference) stay unrouted."""

    def __init__(self, ctx: "MeshContext"):
        self._ctx = ctx if getattr(ctx, "seq_axis", None) else None

    def __enter__(self):
        if self._ctx is not None:
            _ACTIVE_SEQ_CTX.append(self._ctx)
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            _ACTIVE_SEQ_CTX.pop()
        return False


def active_sequence_context() -> Optional["MeshContext"]:
    """The MeshContext of the innermost sequence_parallel_scope (its
    seq_axis is always set), or None outside any scope."""
    return _ACTIVE_SEQ_CTX[-1] if _ACTIVE_SEQ_CTX else None
