"""Distributed training over a jax.sharding.Mesh.

Replaces the reference's three data-parallel strategies (SURVEY §2.3):

- ParallelWrapper (threads + param averaging,
  ref: deeplearning4j-scaleout-parallelwrapper/.../ParallelWrapper.java)
- ParameterServerParallelWrapper (Aeron UDP push/pull,
  ref: ...-parameter-server/.../ParameterServerParallelWrapper.java)
- Spark ParameterAveragingTrainingMaster
  (ref: spark/dl4j-spark/.../paramavg/ParameterAveragingTrainingMaster.java)

with ONE SPMD trainer: shardings over a device mesh, XLA-inserted
collectives riding ICI (all three reference tiers collapse into mesh-axis
choices; multi-host/multi-slice = the same program over DCN-connected
meshes). A parameter-averaging compatibility mode reproduces the
reference's average-every-k semantics for parity testing.
"""

from deeplearning4j_tpu.nn.updater import PrecisionPolicy  # noqa: F401
from deeplearning4j_tpu.parallel import checkpoint  # noqa: F401
from deeplearning4j_tpu.parallel import multihost  # noqa: F401
from deeplearning4j_tpu.parallel.delayed import DelayedSyncTrainer  # noqa: F401
from deeplearning4j_tpu.parallel.mesh import (  # noqa: F401
    MeshContext, WeightUpdateSharding,
)
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer  # noqa: F401
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper  # noqa: F401
