"""Sharded SPMD trainer.

The reference's "TrainingMaster role becomes the SPMD program itself"
(SURVEY §2.3 DP-3): one jitted train step whose inputs carry NamedShardings
— batch over the 'data' axis, params replicated or 'model'-sharded — and
XLA inserts the gradient all-reduce over ICI (the explicit
Nd4j.averageAndPropagate / Aeron push-pull / Spark aggregate all disappear).

Gradient accumulation maps the reference's ``averagingFrequency`` knob
(ParallelWrapper.java:412): accumulate k local microbatch gradients between
parameter updates. Under synchronous all-reduce the reference's
updater-state averaging becomes a no-op (state is replicated & consistent)
— a correctness improvement noted in SURVEY §5.8.
"""

from __future__ import annotations

import time
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import AsyncDataSetIterator, DataSetIterator
from deeplearning4j_tpu.nn.netcommon import (
    ScanFitMixin, emit_scan_burst, make_scan_fit,
)
from deeplearning4j_tpu.nn.updater import (
    PrecisionPolicy, cast_floats, compute_updates, compute_updates_sharded,
    gather_updater_state, precision_value_and_grad, shard_updater_state,
)
from deeplearning4j_tpu.optimize.training_stats import (
    TrainingStats, maybe_phase,
)
from deeplearning4j_tpu.parallel.mesh import (
    MeshContext, WeightUpdateSharding, sequence_parallel_scope,
    zero1_shard_leaf,
)
from deeplearning4j_tpu.profiling import get_tracer


class ParallelTrainer:
    """Data/tensor-parallel trainer for a MultiLayerNetwork or
    ComputationGraph.

    The model's params are resharded onto the mesh; each ``fit`` step feeds a
    global batch (sharded over 'data') through ONE jitted step compiled for
    the mesh. Collectives ride ICI automatically.

    ``weight_update_sharding="zero1"`` (see
    :class:`~deeplearning4j_tpu.parallel.mesh.WeightUpdateSharding`)
    shards the weight update ZeRO-1 style: optax state leaves live as
    flattened ``(dp, chunk)`` views 1/dp per replica, gradients are
    reduce-scattered into that layout (under ``gradient_accumulation``
    the inner scan accumulates directly into the sharded view — each
    microbatch ships a reduce-scatter instead of a full all-reduce, and
    only ONE param-sized gather rides the update), the update is
    applied to the local shard only, and the updated params are
    all-gathered. The loss/param trajectory is exactly the replicated
    layout's — only the execution layout changes. While the trainer is
    attached, ``net.opt_state`` holds the SHARDED views (sharded
    checkpoints round-trip them natively); call :meth:`gather_opt_state`
    before handing the net to the zip serializer or a non-zero1 trainer.

    ``weight_update_sharding="zero2"`` goes one rung further: on the
    per-update path the reduced gradient exists ONLY as the flattened
    ``(dp, chunk)`` shards — zero1's replicated gradient anchor is
    dropped, so the program never requires a full-size reduced gradient
    per replica, gradient HBM drops 1/dp alongside the updater state,
    and the only full-size collective left per update is the param
    all-gather. Inside the ``gradient_accumulation`` scan the
    per-microbatch anchor is retained (GSPMD repartitions the scan body
    without it and bitwise parity dies — see ``to_shards``); the
    sharded ACCUMULATOR carries the scan path's 1/dp gradient memory.
    Same fp32 bitwise-parity guarantee as zero1
    (``tools/zero2_smoke.py``).

    ``precision`` (a :class:`~deeplearning4j_tpu.nn.updater.
    PrecisionPolicy`, a preset name like ``"bf16"``, or None to inherit
    ``net.conf.training.precision``): under a mixed policy the step
    casts params and float batch features to the compute dtype at its
    boundary, runs forward/backward in half precision, and keeps the
    fp32 master weights + every post-gradient op (loss, clip, optax,
    divergence sentinel) in fp32 — composing with every
    weight-update-sharding mode. The fp32 default gates all casts out.

    ``tuned`` (a :class:`~deeplearning4j_tpu.autotune.config.
    TunedConfig`): construct at the autotuner's chosen configuration —
    fills the mesh (when none is given) and any of
    ``gradient_accumulation`` / ``weight_update_sharding`` /
    ``precision`` left at their defaults. Explicit kwargs win, so a
    tuned config can be partially overridden. Probe parity
    (``tools/autotune_smoke.py``) gates that this path trains bitwise
    identically to hand-building the same knobs.
    """

    def __init__(self, net, mesh: Optional[MeshContext] = None,
                 gradient_accumulation: int = 1,
                 donate_params: bool = True,
                 collect_training_stats: bool = False,
                 weight_update_sharding=None,
                 precision=None,
                 tuned=None):
        if tuned is not None:
            if mesh is None:
                mesh = tuned.mesh_context()
            if gradient_accumulation == 1:
                gradient_accumulation = tuned.gradient_accumulation
            if weight_update_sharding is None:
                weight_update_sharding = tuned.weight_update_sharding
            if precision is None:
                precision = tuned.precision
        self.net = net
        self.mesh = mesh or MeshContext.create()
        self.gradient_accumulation = max(1, gradient_accumulation)
        self.weight_update_sharding = WeightUpdateSharding.parse(
            weight_update_sharding)
        self.mesh.validate_weight_update_sharding(
            self.weight_update_sharding)
        training_conf = net.conf.training
        self.precision = PrecisionPolicy.parse(
            precision if precision is not None
            else getattr(training_conf, "precision", None),
            loss_scale=getattr(training_conf, "loss_scale", None))
        self._step = None
        self._donate = donate_params
        # per-phase telemetry, ref ParameterAveragingTrainingMasterStats
        # (Spark tier's collectTrainingStats flag). Syncs the device every
        # step when on — accurate step timing is not free.
        self.training_stats = (TrainingStats()
                               if collect_training_stats else None)
        net._check_init()
        self._is_graph = not hasattr(net, "layers")
        self._layers = (
            [net.conf.nodes[n].layer for n in net._layer_nodes]
            if self._is_graph else net.layers)
        # reshard model state onto the mesh
        net.params = self.mesh.shard_params(net.params)
        net.states = jax.tree.map(
            lambda x: jax.device_put(x, self.mesh.replicated()), net.states)
        # PRESERVE accumulated optimizer state (Adam moments etc.) when
        # wrapping an already-trained net — re-initializing would spike
        # the loss on resume. Replicated mode: leaves land replicated and
        # the first donated step re-lays them out to whatever XLA
        # computes. zero1: leaves are flattened+padded and placed 1/dp
        # over the data axis — the layout they keep for the whole run.
        self._opt_template = None
        if self.weight_update_sharding.enabled:
            net.opt_state, self._opt_template = shard_updater_state(
                net.opt_state, self.mesh,
                self.weight_update_sharding.axis)
        else:
            rep = self.mesh.replicated()
            net.opt_state = jax.tree.map(
                lambda x: jax.device_put(x, rep) if hasattr(x, "shape")
                else x, net.opt_state)

    # ------------------------------------------------------------- the step
    def _build_step(self):
        net = self.net
        training = net.conf.training
        tx = net._tx
        accum = self.gradient_accumulation
        sentinel = getattr(net, "_sentinel", None)
        if sentinel is not None:
            from deeplearning4j_tpu.resilience.sentinel import guard_update

        layers = self._layers
        sharded = self.weight_update_sharding.enabled
        zero2 = self.weight_update_sharding.zero2
        mesh_ctx = self.mesh
        z_axis = self.weight_update_sharding.axis
        policy = self.precision
        mixed = policy.mixed
        if sharded:
            dp = mesh_ctx.zero1_shards(z_axis)
            z_sharding = mesh_ctx.zero1_sharding(z_axis)
            rep_sharding = mesh_ctx.replicated()
            # COMPOSITION WORKAROUND (flushed out by the GPT LM, ISSUE
            # 14): on a mesh that ALSO carries an 'sp' axis, the
            # with_sharding_constraint(zero1_shard_leaf(g), P(dp, None))
            # op makes GSPMD double-apply the sp-axis psum to gradient
            # leaves whose grad is a pure reduction over the (data, sp)-
            # sharded batch (measured on CPU dp=2 x sp=2, jax 0.4.37:
            # a loss-head bias gradient comes back exactly sp-times too
            # large; every other leaf bitwise-identical; the replicated
            # anchor alone and the unconstrained (dp, chunk) reshape are
            # both correct — ONLY the explicit shard constraint
            # miscompiles). Under sp, keep the anchored (dp, chunk)
            # VIEW but skip the layout constraint: values stay exactly
            # the replicated program's (the bitwise spine holds,
            # tools/lm_smoke.py gates it); the in-step gradient may
            # stay replicated instead of reduce-scattered — a layout
            # pessimization on sp meshes, never a correctness change.
            sp_mesh = mesh_ctx.seq_axis is not None

            def pin_replicated(tree):
                return jax.tree.map(
                    lambda t: jax.lax.with_sharding_constraint(
                        t, rep_sharding), tree)

            def to_shards(g, in_scan: bool = False):
                """Full-shape gradient tree -> flattened (dp, chunk)
                views sharded over the data axis. Under zero1 a
                replicated anchor first pins the forward/backward
                partitioning to the exact replicated-mode program (loss
                parity stays bitwise); the shard constraint then lets
                XLA fold the gradient all-reduce + shard slice into a
                reduce-scatter. Under zero2 the anchor is DROPPED from
                the per-update path: the sharded view is the
                gradients' only constraint, so the reduce-scatter is
                their native layout and the program never requires a
                full-size reduced gradient per replica — gradient HBM
                drops with the axis. INSIDE the ga scan the anchor is
                kept for every mode: without it GSPMD repartitions the
                scan body itself (measured on CPU dp=2 — the local
                forward/loss reductions reassociate, and in one
                observed layout the forward matmuls all-gather sharded
                weights), which breaks the bitwise gate; the sharded
                ACCUMULATOR already holds the scan path's 1/dp
                gradient-memory win, and the anchored per-microbatch
                sum stays transient.
                """
                if in_scan or not zero2 or sp_mesh:
                    g = pin_replicated(g)
                if sp_mesh:
                    # see sp_mesh above: anchored view, no constraint
                    return jax.tree.map(
                        lambda t: zero1_shard_leaf(t, dp), g)
                return jax.tree.map(
                    lambda t: jax.lax.with_sharding_constraint(
                        zero1_shard_leaf(t, dp), z_sharding), g)

        # both containers' _loss_fn share the positional signature
        # (params, states, inputs, labels, masks, label_masks) — inputs/
        # labels/masks are arrays for MLN, name-keyed dicts for a graph
        def loss_fn(p, states, feats, labels, fmask, lmask, rng):
            return net._loss_fn(p, states, feats, labels, fmask, lmask,
                                rng=rng, train=True)

        # fp32 policy: the plain jax.value_and_grad — the exact
        # pre-policy program. Mixed: params/features cast to the compute
        # dtype at the step boundary, loss + grads handed back in fp32.
        vag = precision_value_and_grad(loss_fn, policy)

        def step(params, opt_state, states, feats, labels, fmask, lmask, rng):
            if mixed:
                feats = cast_floats(feats, policy.compute_dtype)
                fmask = cast_floats(fmask, policy.compute_dtype)
            if accum == 1:
                (loss, new_states), grads = vag(params, states, feats,
                                                labels, fmask, lmask, rng)
                if sharded:
                    grads = to_shards(grads)
            else:
                # microbatch split along the batch axis inside the step:
                # local accumulation between synchronizations = the
                # averagingFrequency semantics, without ever materializing
                # per-worker model copies
                def micro(carry, mb):
                    g_acc, l_acc, st = carry
                    f, l, fm, lm, r = mb
                    (loss, st2), g = vag(params, st, f, l, fm, lm, r)
                    if sharded:
                        # accumulate straight into the sharded layout:
                        # cross-chip traffic per microbatch becomes one
                        # reduce-scatter of g instead of a full
                        # all-reduce, and the accumulator itself holds
                        # only 1/dp per chip
                        g = to_shards(g, in_scan=True)
                    g_acc = jax.tree.map(lambda a, b: a + b, g_acc, g)
                    return (g_acc, l_acc + loss, st2), None

                leaves = jax.tree_util.tree_leaves(feats)
                B = leaves[0].shape[0]
                if B % accum != 0:
                    raise ValueError(
                        f"batch size {B} not divisible by "
                        f"gradient_accumulation={accum}")
                mb_size = B // accum

                def split(x):
                    return jax.tree.map(
                        lambda a: a.reshape((accum, mb_size) + a.shape[1:]),
                        x)

                rngs = jax.random.split(rng, accum)
                zero_g = jax.tree.map(jnp.zeros_like, params)
                if sharded:
                    zero_g = to_shards(zero_g, in_scan=True)
                (grads, loss, new_states), _ = jax.lax.scan(
                    micro, (zero_g, jnp.zeros(()), states),
                    (split(feats), split(labels), split(fmask),
                     split(lmask), rngs))
                grads = jax.tree.map(lambda g: g / accum, grads)
                loss = loss / accum
            if sharded:
                new_params, new_opt = compute_updates_sharded(
                    tx, grads, opt_state, params, layers, training,
                    mesh_ctx, z_axis)
            else:
                new_params, new_opt = compute_updates(
                    tx, grads, opt_state, params, layers, training)
            if sentinel is None:
                return new_params, new_opt, new_states, loss
            # non-finite guard: a diverged update never lands (old state
            # selected in-program — no host sync). Under zero1/zero2
            # `grads` are the sharded (dp, chunk) views, so the guard's
            # grad-norm reduction is a psum of local-shard norms — same
            # flag value, no extra gather. Under a mixed policy both
            # loss and grads crossed the fp32 seam before reaching it.
            sel, bad = guard_update(
                loss, grads, (params, opt_state, states),
                (new_params, new_opt, new_states))
            return sel[0], sel[1], sel[2], loss, bad

        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    # ---------------------------------------------------- shared step prep
    def _ensure_step(self) -> None:
        """(Re)build the cached jitted step — shared by fit_batch and
        step_program so the analyzed program is EXACTLY the one fit
        runs, including the sentinel-change rebuild."""
        net = self.net
        if (self.weight_update_sharding.enabled
                and self._opt_template is None):
            # a gather_opt_state() between fits put the replicated
            # layout back on the net — restore the sharded contract the
            # compiled zero1 step runs on
            net.opt_state, self._opt_template = shard_updater_state(
                net.opt_state, self.mesh, self.weight_update_sharding.axis)
        if (self._step is None
                or getattr(self, "_step_sentinel", None)
                is not getattr(net, "_sentinel", None)):
            # a sentinel attached/detached after the first build: the
            # guarded step is a different program — rebuild
            self._step_sentinel = getattr(net, "_sentinel", None)
            self._step = self._build_step()

    def _shard_batch_args(self, batch):
        """Place one batch in the step's NamedSharding layout —
        (feats, labels, fmask, lmask), the per-batch half of the step's
        argument list. One copy, so fit and shardcheck cannot drift."""
        net = self.net
        if self._is_graph:
            # name-keyed dicts (DataSet or MultiDataSet), every leaf
            # sharded over the data axis
            inputs, lbls, masks, lmasks_d = net._split(batch)
            shard = lambda t: jax.tree.map(self.mesh.shard_batch, t)
            return (shard(inputs), shard(lbls), shard(masks),
                    shard(lmasks_d))
        feats, labels = self.mesh.shard_batch(
            jnp.asarray(batch.features), jnp.asarray(batch.labels))
        fmask = lmask = None
        if batch.features_mask is not None:
            fmask = self.mesh.shard_batch(jnp.asarray(batch.features_mask))
        if batch.labels_mask is not None:
            lmask = self.mesh.shard_batch(jnp.asarray(batch.labels_mask))
        return feats, labels, fmask, lmask

    # ------------------------------------------------------- shardcheck
    def step_program(self, batch):
        """Capture THIS trainer's compiled per-batch step program for
        ``batch`` (analysis/shardcheck) — one AOT compile, no
        execution, donated buffers untouched."""
        from deeplearning4j_tpu.analysis.shardcheck import lower_step_program
        net = self.net
        self._ensure_step()
        feats, labels, fmask, lmask = self._shard_batch_args(batch)
        with sequence_parallel_scope(self.mesh):
            return lower_step_program(
                self._step, net.params, net.opt_state, net.states, feats,
                labels, fmask, lmask, jax.random.PRNGKey(0))

    def shardcheck_context(self) -> dict:
        """The layout context ``analysis/shardcheck`` validates this
        trainer's program against — what the program CLAIMS to be."""
        from deeplearning4j_tpu.analysis.shardcheck import param_leaf_sizes
        return dict(
            weight_update_sharding=self.weight_update_sharding.mode,
            dp=self.mesh.n_data,
            gradient_accumulation=self.gradient_accumulation,
            sp=(self.mesh.mesh.shape[self.mesh.seq_axis]
                if self.mesh.seq_axis else 1),
            precision=self.precision,
            expect_donation=self._donate,
            param_leaf_sizes=param_leaf_sizes(self.net.params))

    def shardcheck(self, batch, **overrides):
        """Statically verify the compiled step honors this trainer's
        declared layout: reduce-scatter form under zero1/zero2 (SC001),
        collective census (SC002), ga-scan anchor (SC003), precision
        boundaries (SC004), donation (SC005), no host transfers
        (SC006), comm-bytes calibration (SC007). Returns findings; runs
        on CPU in seconds with no training step executed."""
        from deeplearning4j_tpu.analysis.shardcheck import check_step_program
        ctx = self.shardcheck_context()
        ctx.update(overrides)
        return check_step_program(self.step_program(batch), **ctx)

    def gather_opt_state(self):
        """Restore ``net.opt_state`` to its original (replicated) layout
        and return it. Under zero1 the net holds the flattened sharded
        views while this trainer is attached; gather before handing the
        net to the zip serializer, a non-zero1 trainer, or single-device
        inference-with-resume. A no-op in replicated mode."""
        if self._opt_template is not None:
            self.net.opt_state = gather_updater_state(
                self.net.opt_state, self._opt_template)
            self._opt_template = None
        return self.net.opt_state

    # ------------------------------------------------------------------- fit
    def fit_batch(self, batch) -> float:
        net = self.net
        self._ensure_step()
        stats = self.training_stats
        # global-tracer spans (profiling/): host-side timeline of the
        # same phases the stats flag times — unconditional because the
        # tracer is cheap and the open-span stack is the hang diagnosis.
        # `with` (not bare begin/end): a raising step must close the
        # span AND note it on the tracer's error stack, or one caught
        # exception would leak an open span into every later diagnosis
        tracer = get_tracer()
        with tracer.span("shard"):
            t_shard = time.perf_counter() if stats else 0.0
            feats, labels, fmask, lmask = self._shard_batch_args(batch)
            if stats:
                # sync the async device_put so transfer time lands in
                # 'shard', not 'step' — over a remote tunnel that
                # distinction is the whole point of the phase
                jax.block_until_ready((feats, labels))
                stats.record("shard", time.perf_counter() - t_shard)
                t_step = time.perf_counter()
        with tracer.span("step"):
            net._rng, step_rng = jax.random.split(net._rng)
            # the scope routes SelfAttentionLayer through ring attention
            # over the mesh's 'sp' axis at trace time (no-op without one)
            with sequence_parallel_scope(self.mesh):
                out = self._step(
                    net.params, net.opt_state, net.states, feats, labels,
                    fmask, lmask, step_rng)
                net.params, net.opt_state, net.states, loss = out[:4]
            if stats:
                jax.block_until_ready(loss)
                stats.record("step", time.perf_counter() - t_step)
        net.last_batch_size = batch.num_examples()
        net.last_grads = None  # SPMD step doesn't collect gradients
        # raw device scalar: converting here would sync the SPMD pipeline
        # every step (see MultiLayerNetwork.score_value)
        net.score_value = loss
        net.iteration_count += 1
        if hasattr(net, "_observe_sentinel"):
            net._observe_sentinel(out[4] if len(out) > 4 else None)
        with tracer.span("listener"), maybe_phase(stats, "listener"):
            for listener in net.listeners:
                listener.iteration_done(net, net.iteration_count,
                                        net.score_value)
        return net._score_raw

    def fit(self, data: Union[DataSet, DataSetIterator], epochs: int = 1,
            use_async: bool = True,
            scan_window: int = 1) -> "ParallelTrainer":
        """``scan_window > 1``: see fit_batches_scan."""
        if isinstance(data, DataSet):
            for _ in range(epochs):
                self.fit_batch(data)
            return self
        if hasattr(data, "attach"):
            # streaming input pipeline: bind its device stage to THIS
            # mesh so batches arrive pre-placed in the step's
            # NamedSharding batch layout (the in-step shard_batch then
            # finds them already placed and moves nothing) — instead of
            # landing replicated and resharding every step. The scan
            # path stacks a window of batches HOST-side before placing
            # the stack, so per-batch device staging would only force a
            # D2H round trip (and crash multi-process, where pulling a
            # global array back to one host is illegal) — keep those
            # host-side.
            data.attach(mesh=self.mesh,
                        place=False if scan_window > 1 else None)
        it = (AsyncDataSetIterator(data)
              if use_async and data.async_supported() else data)
        stats = self.training_stats
        for _ in range(epochs):
            src = stats.timed_iter(it) if stats else it
            if scan_window > 1:
                # reuse the containers' windowing loop (only needs
                # fit_batches_scan / fit_batch from self)
                ScanFitMixin._fit_epoch_scan(self, src, scan_window)
            else:
                for batch in src:
                    self.fit_batch(batch)
            self.net.epoch_count += 1
        return self

    # ---------------------------------------------------------- scan windows
    def fit_batches_scan(self, batches):
        """N SPMD optimization steps as ONE jitted lax.scan program over
        the mesh (the single-device fit_batches_scan, sharded): stacked
        batches are placed with the leading window axis replicated and
        the batch axis sharded over 'data', so the scan body runs the
        same NamedSharding step the per-batch path compiles. Falls back
        to the fit_batch loop for masked/ragged/MultiDataSet windows."""
        net = self.net
        batches = list(batches)
        if not batches:
            return np.zeros((0,), np.float32)
        scannable = (
            not self._is_graph
            # sentinel policies need per-step flags (see netcommon's
            # fit_batches_scan) — fall back to the fit_batch loop
            and getattr(net, "_sentinel", None) is None
            and all(isinstance(b, DataSet)
                    and b.features_mask is None and b.labels_mask is None
                    for b in batches)
            and len({(np.shape(b.features), np.shape(b.labels))
                     for b in batches}) == 1)
        if not scannable:
            return np.asarray([float(self.fit_batch(b))
                               for b in batches], np.float32)
        if (self.weight_update_sharding.enabled
                and self._opt_template is None):
            net.opt_state, self._opt_template = shard_updater_state(
                net.opt_state, self.mesh, self.weight_update_sharding.axis)
        if self._step is None:
            self._step = self._build_step()
        cached = getattr(self, "_scan_step", None)
        if cached is None or cached[0] is not self._step:
            self._scan_step = (self._step, make_scan_fit(
                self._step,
                donate_argnums=(0, 1, 2) if self._donate else ()))
        scan_fn = self._scan_step[1]

        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self.mesh.mesh
        data_axis = self.mesh.data_axis

        def place(arrs):
            stacked = np.stack([np.asarray(a) for a in arrs])
            # reuse the per-batch sharding policy (incl. its sp-axis
            # rule) with the window axis prepended — reimplementing the
            # divisibility decision here would let the two paths drift
            batch_spec = self.mesh.batch_sharding(
                stacked.ndim - 1, stacked.shape[1:]).spec
            spec = P(None, *batch_spec)
            return jax.device_put(stacked, NamedSharding(mesh, spec))

        stats = self.training_stats
        tracer = get_tracer()
        with tracer.span("shard", window=len(batches)):
            t_shard = time.perf_counter() if stats else 0.0
            feats = place([b.features for b in batches])
            labels = place([b.labels for b in batches])
            if stats:
                jax.block_until_ready((feats, labels))
                stats.record("shard", time.perf_counter() - t_shard)
                t_step = time.perf_counter()
        with tracer.span("scan_step", window=len(batches)):
            t0 = time.perf_counter()
            net._rng, r = jax.random.split(net._rng)
            with sequence_parallel_scope(self.mesh):
                net.params, net.opt_state, net.states, losses = scan_fn(
                    net.params, net.opt_state, net.states, feats, labels, r)
            if stats:
                jax.block_until_ready(losses)
                stats.record("step", time.perf_counter() - t_step)
        net.last_batch_size = batches[-1].num_examples()
        net.last_grads = None
        if net.listeners:
            emit_scan_burst(net, losses, len(batches), t0, stats=stats)
        else:
            net.iteration_count += len(batches)
        net.score_value = losses[-1]
        return losses
