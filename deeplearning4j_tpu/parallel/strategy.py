"""Pluggable training-strategy SPI.

Ref: spark/dl4j-spark/.../api/TrainingMaster.java:29-220 +
TrainingWorker.java + TrainingHook.java — the reference exposes a strategy
interface so synchronization schemes other than parameter averaging could
plug in (param averaging is its only impl). Here the registry maps
strategy names onto the two TPU-native schemes, plus hook points
(ref: TrainingHook pre/post-update) invoked around each step:

- ``"allreduce"``       -> ParallelTrainer — synchronous gradient
  all-reduce over the mesh (the correct default; optimizer state stays
  replicated & consistent, SURVEY §5.8)
- ``"param_averaging"`` -> ParallelWrapper — the reference's
  average-every-k-iterations semantics, kept for convergence parity

``create_trainer(strategy, net, ...)`` is the factory
(ref: SparkDl4jMultiLayer taking a TrainingMaster instance).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.parallel.mesh import MeshContext
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

TRAINING_STRATEGIES: Dict[str, Callable] = {}


def register_strategy(name: str):
    def deco(factory: Callable) -> Callable:
        TRAINING_STRATEGIES[name.lower()] = factory
        return factory
    return deco


class TrainingHook:
    """Pre/post-update hook (ref: api/TrainingHook.java — preUpdate /
    postUpdate around each worker fit)."""

    def pre_update(self, batch, trainer) -> None:
        pass

    def post_update(self, batch, trainer) -> None:
        pass


class _HookedTrainer:
    """Wraps any trainer's fit_batch with TrainingHook dispatch."""

    def __init__(self, trainer, hooks: List[TrainingHook]):
        self._trainer = trainer
        self._hooks = hooks

    def __getattr__(self, name):
        return getattr(self._trainer, name)

    def fit_batch(self, batch):
        for h in self._hooks:
            h.pre_update(batch, self._trainer)
        out = self._trainer.fit_batch(batch)
        for h in self._hooks:
            h.post_update(batch, self._trainer)
        return out


@register_strategy("allreduce")
def _allreduce(net, mesh: Optional[MeshContext] = None, **kw):
    return ParallelTrainer(net, mesh, **kw)


@register_strategy("param_averaging")
def _param_averaging(net, mesh: Optional[MeshContext] = None, **kw):
    return ParallelWrapper(net, mesh=mesh, **kw)


@register_strategy("pipeline")
def _pipeline(net, mesh: Optional[MeshContext] = None, **kw):
    """GPipe pipeline parallelism: MLN body partitioned into S contiguous
    stages over the mesh's 'pp' axis, heterogeneous activation shapes via
    flat padded ring buffers (see parallel/pipeline.PipelineTrainer)."""
    from deeplearning4j_tpu.parallel.pipeline import (
        GraphPipelineTrainer, PipelineTrainer)
    if hasattr(net, "layers"):
        return PipelineTrainer(net, mesh=mesh, **kw)
    return GraphPipelineTrainer(net, mesh=mesh, **kw)


@register_strategy("delayed_sync")
def _delayed_sync(net, mesh: Optional[MeshContext] = None, **kw):
    """DP-2 parameter-server analog: local gradient accumulation with a
    param-sized all-reduce only every sync_frequency steps (ref:
    ParameterServerParallelWrapper.java:289-345; SURVEY §2.3 DP-2)."""
    from deeplearning4j_tpu.parallel.delayed import DelayedSyncTrainer
    return DelayedSyncTrainer(net, mesh=mesh, **kw)


def create_trainer(strategy: str, net, mesh: Optional[MeshContext] = None,
                   hooks: Optional[List[TrainingHook]] = None, **kw):
    """Factory over the strategy registry (ref: TrainingMaster SPI)."""
    key = strategy.lower()
    if key not in TRAINING_STRATEGIES:
        raise ValueError(f"Unknown training strategy {strategy!r}; "
                         f"available: {sorted(TRAINING_STRATEGIES)}")
    trainer = TRAINING_STRATEGIES[key](net, mesh, **kw)
    if hooks:
        return _HookedTrainer(trainer, list(hooks))
    return trainer
