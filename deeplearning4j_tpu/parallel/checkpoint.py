"""Sharded checkpointing for mesh-distributed params/optimizer state.

The reference's checkpoint is a single flat buffer inside a zip
(ref: util/ModelSerializer.java:79-110) — fine for one host, wrong for a
pod: gathering TB-scale sharded params to one host serializes the job on a
single HBM->host link. Here each PROCESS writes only its addressable
shards; restore reassembles and re-places arrays onto the (possibly
different) target mesh. This is the role orbax plays in large JAX
deployments, hand-rolled to keep the format inspectable:

    <dir>/
      manifest.json      — leaf paths, shapes, dtypes, PartitionSpecs,
                           mesh axis names/sizes, process count
      shards_p<K>.npz    — process K's addressable shards, keyed
                           "<leaf>|<shard-linear-index>"

Restore modes:
- ``restore_sharded(dir, mesh_ctx)``   -> pytree placed on mesh per the
  SAVED specs (mapped onto the target mesh's axes).
- ``restore_sharded(dir, None)``       -> host numpy pytree (fully
  assembled), for single-host use or inspection.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any, Dict, Optional, Union

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import MeshContext

MANIFEST = "manifest.json"


def _leaf_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _index_to_slices(index, shape):
    """jax shard .index (tuple of slices) -> JSON-able [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded(ckpt_dir: Union[str, Path], pytree: Any,
                 mesh_ctx: Optional[MeshContext] = None) -> None:
    """Write this process's addressable shards + (on process 0) the manifest.

    Works for host numpy / single-device arrays too (one "shard" covering
    the full array), so the same call site serves laptop and pod.
    """
    ckpt_dir = Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    proc = jax.process_index()
    nproc = jax.process_count()

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(pytree)
    manifest: Dict[str, Any] = {
        "format": "deeplearning4j_tpu/sharded-checkpoint",
        "version": 1,
        "process_count": nproc,
        "treedef": None,  # reconstructed from leaf paths on restore
        "leaves": {},
    }
    shard_arrays: Dict[str, np.ndarray] = {}
    for path, leaf in leaves_with_paths:
        key = _leaf_key(path)
        shape = tuple(np.shape(leaf))
        dtype = str(np.asarray(leaf).dtype if not hasattr(leaf, "dtype")
                    else leaf.dtype)
        spec = None
        shards_meta = []
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            sh = leaf.sharding
            if isinstance(sh, NamedSharding):
                spec = [list(p) if isinstance(p, tuple) else p
                        for p in sh.spec]
            for i, shard in enumerate(leaf.addressable_shards):
                skey = f"{key}|{i}"
                shard_arrays[skey] = np.asarray(shard.data)
                shards_meta.append({
                    "file": f"shards_p{proc}.npz", "key": skey,
                    "index": _index_to_slices(shard.index, shape)})
        else:
            skey = f"{key}|0"
            shard_arrays[skey] = np.asarray(leaf)
            shards_meta.append({
                "file": f"shards_p{proc}.npz", "key": skey,
                "index": _index_to_slices(
                    tuple(slice(None) for _ in shape), shape)})
        manifest["leaves"][key] = {
            "shape": list(shape), "dtype": dtype, "spec": spec,
            "shards": shards_meta,
        }
    np.savez(ckpt_dir / f"shards_p{proc}.npz", **shard_arrays)

    if nproc > 1:
        # every process contributes its shard metadata; process files are
        # disjoint, so merge via per-process manifests
        with open(ckpt_dir / f"manifest_p{proc}.json", "w") as f:
            json.dump(manifest, f)
    if proc == 0:
        with open(ckpt_dir / MANIFEST, "w") as f:
            json.dump(manifest, f, indent=1)


def _merge_manifests(ckpt_dir: Path) -> dict:
    with open(ckpt_dir / MANIFEST) as f:
        manifest = json.load(f)
    if manifest.get("process_count", 1) > 1:
        for pf in sorted(ckpt_dir.glob("manifest_p*.json")):
            with open(pf) as f:
                part = json.load(f)
            for key, meta in part["leaves"].items():
                known = {(s["file"], s["key"])
                         for s in manifest["leaves"][key]["shards"]}
                for s in meta["shards"]:
                    if (s["file"], s["key"]) not in known:
                        manifest["leaves"][key]["shards"].append(s)
    return manifest


def _assemble(ckpt_dir: Path, meta: dict, npz_cache: Dict[str, Any]) -> np.ndarray:
    out = np.zeros(tuple(meta["shape"]), dtype=meta["dtype"])
    covered = np.zeros(tuple(meta["shape"]), dtype=bool) if meta["shape"] else None
    for s in meta["shards"]:
        if s["file"] not in npz_cache:
            npz_cache[s["file"]] = np.load(ckpt_dir / s["file"])
        data = npz_cache[s["file"]][s["key"]]
        idx = tuple(slice(a, b) for a, b in s["index"])
        out[idx] = data
        if covered is not None:
            covered[idx] = True
    if covered is not None and not covered.all():
        raise IOError(
            f"Checkpoint shard coverage incomplete for a leaf of shape "
            f"{meta['shape']} — missing process shard files?")
    return out


def restore_sharded(ckpt_dir: Union[str, Path],
                    mesh_ctx: Optional[MeshContext] = None) -> Dict[str, Any]:
    """Read a sharded checkpoint into a nested-dict pytree.

    With ``mesh_ctx``, each leaf is device_put with its SAVED PartitionSpec
    on the target mesh (axis names must exist there; unknown axes fall back
    to replication). Without, returns host numpy arrays.
    """
    ckpt_dir = Path(ckpt_dir)
    manifest = _merge_manifests(ckpt_dir)
    npz_cache: Dict[str, Any] = {}
    flat: Dict[str, np.ndarray] = {}
    for key, meta in manifest["leaves"].items():
        arr = _assemble(ckpt_dir, meta, npz_cache)
        if mesh_ctx is not None:
            spec_elems = []
            axes = set(mesh_ctx.mesh.axis_names)
            for p in (meta["spec"] or []):
                if isinstance(p, list):
                    p = tuple(x for x in p if x in axes) or None
                elif p is not None and p not in axes:
                    p = None
                spec_elems.append(p)
            sharding = NamedSharding(mesh_ctx.mesh, P(*spec_elems))
            arr = jax.device_put(arr, sharding)
        flat[key] = arr
    # rebuild nesting from '/'-joined leaf paths
    tree: Dict[str, Any] = {}
    for key, arr in flat.items():
        parts = key.split("/")
        cur = tree
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = arr
    return tree


def restore_sharded_into(ckpt_dir: Union[str, Path], template: Any,
                         mesh_ctx: Optional[MeshContext] = None) -> Any:
    """Restore into the exact structure of ``template`` (lists stay lists,
    custom pytree nodes stay themselves) — leaf lookup by flattened path.
    Shapes must match the saved checkpoint."""
    ckpt_dir = Path(ckpt_dir)
    manifest = _merge_manifests(ckpt_dir)
    npz_cache: Dict[str, Any] = {}
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = _leaf_key(path)
        if key not in manifest["leaves"]:
            raise KeyError(f"Checkpoint has no leaf {key!r}")
        meta = manifest["leaves"][key]
        if tuple(meta["shape"]) != tuple(np.shape(leaf)):
            raise ValueError(
                f"Leaf {key!r}: checkpoint shape {tuple(meta['shape'])} != "
                f"template shape {tuple(np.shape(leaf))}")
        arr = _assemble(ckpt_dir, meta, npz_cache)
        if mesh_ctx is not None:
            axes = set(mesh_ctx.mesh.axis_names)
            spec_elems = []
            for p in (meta["spec"] or []):
                if isinstance(p, list):
                    p = tuple(x for x in p if x in axes) or None
                elif p is not None and p not in axes:
                    p = None
                spec_elems.append(p)
            arr = jax.device_put(arr, NamedSharding(mesh_ctx.mesh,
                                                    P(*spec_elems)))
        elif isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            arr = jax.device_put(arr, leaf.sharding)
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
