"""Sharded checkpointing for mesh-distributed params/optimizer state.

The reference's checkpoint is a single flat buffer inside a zip
(ref: util/ModelSerializer.java:79-110) — fine for one host, wrong for a
pod: gathering TB-scale sharded params to one host serializes the job on a
single HBM->host link. Here each PROCESS writes only its addressable
shards; restore reassembles and re-places arrays onto the (possibly
different) target mesh. This is the role orbax plays in large JAX
deployments, hand-rolled to keep the format inspectable:

    <dir>/
      manifest.json      — leaf paths, shapes, dtypes, PartitionSpecs,
                           mesh axis names/sizes, process count
      shards_p<K>.npz    — process K's addressable shards, keyed
                           "<leaf>|<shard-linear-index>"
      done_p<K>.json     — process K's commit vote: its shard-file CRC
                           (multi-process saves only)
      COMMIT             — written LAST, by process 0 only, after every
                           per-process shard file has landed; carries
                           the CRC-32 of each shard file

Crash safety: every file is committed atomically (tmp + fsync +
rename, ``resilience/atomic.py``), and the ``COMMIT`` marker makes the
whole multi-file checkpoint transactional — ``restore_sharded`` refuses
a directory without it, so a reader can never assemble a half-written
step. Shard-file CRCs are verified on restore; a bit-flipped or
truncated shard raises ``CheckpointError`` naming the file. (The
manifests are small atomically-replaced JSON validated by parse +
shard-coverage checks, so they carry no CRC — which also keeps them
hand-editable for recovery surgery.)

Restore modes:
- ``restore_sharded(dir, mesh_ctx)``   -> pytree placed on mesh per the
  SAVED specs (mapped onto the target mesh's axes).
- ``restore_sharded(dir, None)``       -> host numpy pytree (fully
  assembled), for single-host use or inspection.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Dict, Optional, Union

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import MeshContext
from deeplearning4j_tpu.resilience.atomic import (CheckpointError,
                                                  atomic_path,
                                                  atomic_write_bytes,
                                                  crc32_file)

MANIFEST = "manifest.json"
COMMIT = "COMMIT"


def _leaf_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _index_to_slices(index, shape):
    """jax shard .index (tuple of slices) -> JSON-able [[start, stop], ...]."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_sharded(ckpt_dir: Union[str, Path], pytree: Any,
                 mesh_ctx: Optional[MeshContext] = None,
                 commit_timeout: float = 120.0,
                 topology: Optional[dict] = None) -> None:
    """Write this process's addressable shards + (on process 0) the
    manifest and, once every process's shards have landed, the COMMIT
    marker. A reader polling the directory sees the checkpoint appear
    atomically: no COMMIT, no checkpoint.

    Works for host numpy / single-device arrays too (one "shard" covering
    the full array), so the same call site serves laptop and pod.

    The process count/rank come from ``multihost.effective_*`` so an
    elastic resize (fewer survivors than ``jax.process_count()``) writes
    a checkpoint in the surviving world's format. ``topology`` (dp
    width, weight-update-sharding mode, process count — what
    CheckpointManager records) is stored in the manifest so a restore at
    a different width can be detected up front, not as a shape mismatch
    mid-assembly.
    """
    from deeplearning4j_tpu.parallel import multihost
    ckpt_dir = Path(ckpt_dir)
    ckpt_dir.mkdir(parents=True, exist_ok=True)
    proc = multihost.effective_process_index()
    nproc = multihost.effective_process_count()
    # stale artifacts from a previous save into this directory would
    # corrupt the commit protocol: a stale COMMIT marks the half-written
    # new step valid, and a stale done_p<K> vote lets process 0 commit
    # before process K's new shards land. Every process drops ITS OWN
    # stale vote; process 0 drops the COMMIT. (Reusing one directory
    # across save rounds still assumes the callers enter save_sharded
    # together, as an SPMD program does; CheckpointManager sidesteps the
    # whole class by writing each step into a fresh directory.)
    (ckpt_dir / f"done_p{proc}.json").unlink(missing_ok=True)
    (ckpt_dir / f"manifest_p{proc}.json").unlink(missing_ok=True)
    if proc == 0:
        (ckpt_dir / COMMIT).unlink(missing_ok=True)
        # votes/manifests of ranks beyond the CURRENT world are stale
        # remnants of a wider pre-resize world reusing this directory —
        # their owners are gone and will never refresh them, so they
        # must not feed the commit protocol or the manifest merge
        for stale in list(ckpt_dir.glob("done_p*.json")) + \
                list(ckpt_dir.glob("manifest_p*.json")):
            try:
                k = int(stale.name.split("_p")[1].split(".")[0])
            except (IndexError, ValueError):
                continue
            if k >= nproc:
                stale.unlink(missing_ok=True)

    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(pytree)
    manifest: Dict[str, Any] = {
        "format": "deeplearning4j_tpu/sharded-checkpoint",
        "version": 2,
        "process_count": nproc,
        "treedef": None,  # reconstructed from leaf paths on restore
        "leaves": {},
    }
    if topology is not None:
        manifest["topology"] = dict(topology)
    shard_arrays: Dict[str, np.ndarray] = {}
    for path, leaf in leaves_with_paths:
        key = _leaf_key(path)
        shape = tuple(np.shape(leaf))
        dtype = str(np.asarray(leaf).dtype if not hasattr(leaf, "dtype")
                    else leaf.dtype)
        spec = None
        shards_meta = []
        if isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            sh = leaf.sharding
            if isinstance(sh, NamedSharding):
                spec = [list(p) if isinstance(p, tuple) else p
                        for p in sh.spec]
            for i, shard in enumerate(leaf.addressable_shards):
                skey = f"{key}|{i}"
                shard_arrays[skey] = np.asarray(shard.data)
                shards_meta.append({
                    "file": f"shards_p{proc}.npz", "key": skey,
                    "index": _index_to_slices(shard.index, shape)})
        else:
            skey = f"{key}|0"
            shard_arrays[skey] = np.asarray(leaf)
            shards_meta.append({
                "file": f"shards_p{proc}.npz", "key": skey,
                "index": _index_to_slices(
                    tuple(slice(None) for _ in shape), shape)})
        manifest["leaves"][key] = {
            "shape": list(shape), "dtype": dtype, "spec": spec,
            "shards": shards_meta,
        }
    shard_name = f"shards_p{proc}.npz"
    # stream the npz straight to the tmp file (an in-memory staging
    # buffer would transiently double host RAM at pod scale), CRC it
    # from disk, then commit atomically
    with atomic_path(ckpt_dir / shard_name) as tmp:
        with open(tmp, "wb") as f:
            np.savez(f, **shard_arrays)
        shard_crc = crc32_file(tmp)

    if nproc > 1:
        # every process contributes its shard metadata; process files are
        # disjoint, so merge via per-process manifests
        atomic_write_bytes(ckpt_dir / f"manifest_p{proc}.json",
                           json.dumps(manifest).encode())
        # commit vote: "my shard file is fully on disk, CRC attached"
        atomic_write_bytes(ckpt_dir / f"done_p{proc}.json",
                           json.dumps({"file": shard_name,
                                       "crc32": shard_crc}).encode())
    if proc == 0:
        atomic_write_bytes(ckpt_dir / MANIFEST,
                           json.dumps(manifest, indent=1).encode())
        files = {shard_name: shard_crc}
        if nproc > 1:
            deadline = time.monotonic() + commit_timeout
            missing = set(range(1, nproc))
            while missing:
                for k in sorted(missing):
                    dp = ckpt_dir / f"done_p{k}.json"
                    if dp.exists():
                        vote = json.loads(dp.read_text())
                        files[vote["file"]] = vote["crc32"]
                        missing.discard(k)
                if not missing:
                    break
                if time.monotonic() > deadline:
                    raise CheckpointError(
                        f"checkpoint {ckpt_dir}: processes {sorted(missing)} "
                        f"never landed their shards within "
                        f"{commit_timeout:.0f}s — NOT committing a "
                        "partial checkpoint")
                time.sleep(0.05)
        # the transaction point: COMMIT appears only over a complete set
        atomic_write_bytes(
            ckpt_dir / COMMIT,
            json.dumps({"version": 1, "process_count": nproc,
                        "files": files}).encode())


def verify_sharded(ckpt_dir: Union[str, Path]) -> dict:
    """Integrity gate for a sharded checkpoint directory: COMMIT marker
    present, every committed shard file present with a matching CRC-32,
    manifest parseable. Raises ``CheckpointError`` naming the first bad
    file; returns the parsed COMMIT record."""
    ckpt_dir = Path(ckpt_dir)
    mpath = ckpt_dir / MANIFEST
    commit_path = ckpt_dir / COMMIT
    if not commit_path.exists():
        # version-1 checkpoints predate the COMMIT protocol: a complete
        # old checkpoint (manifest present, version < 2) must stay
        # restorable — only its coverage check defends it, as before
        try:
            manifest = json.loads(mpath.read_text())
        except (OSError, ValueError):
            manifest = None
        if manifest is not None and manifest.get("version", 1) < 2:
            import logging
            logging.getLogger(__name__).warning(
                "checkpoint %s is a pre-COMMIT (v1) sharded checkpoint; "
                "restoring without checksum verification", ckpt_dir)
            return {"version": 0, "files": {}}
        raise CheckpointError(
            f"checkpoint {ckpt_dir}: missing {COMMIT} marker — the save "
            "never completed (torn multi-process write)")
    try:
        commit = json.loads(commit_path.read_text())
    except (OSError, ValueError) as e:
        raise CheckpointError(
            f"checkpoint {ckpt_dir}: {COMMIT} marker unreadable: "
            f"{e}") from e
    for fname, want in commit.get("files", {}).items():
        fp = ckpt_dir / fname
        if not fp.exists():
            raise CheckpointError(
                f"checkpoint {ckpt_dir}: committed shard file {fname!r} "
                "is missing")
        got = crc32_file(fp)
        if got != want:
            raise CheckpointError(
                f"checkpoint {ckpt_dir}: shard file {fname!r} checksum "
                f"mismatch (got {got:#010x}, COMMIT {want:#010x}) — "
                "truncated or bit-flipped write")
    if not mpath.exists():
        raise CheckpointError(
            f"checkpoint {ckpt_dir}: missing {MANIFEST}")
    try:
        json.loads(mpath.read_text())
    except ValueError as e:
        raise CheckpointError(
            f"checkpoint {ckpt_dir}: {MANIFEST} is corrupt: {e}") from e
    return commit


def _merge_manifests(ckpt_dir: Path, verify: bool = True) -> dict:
    if verify:
        verify_sharded(ckpt_dir)
    with open(ckpt_dir / MANIFEST) as f:
        manifest = json.load(f)
    if manifest.get("process_count", 1) > 1:
        for pf in sorted(ckpt_dir.glob("manifest_p*.json")):
            with open(pf) as f:
                part = json.load(f)
            for key, meta in part["leaves"].items():
                known = {(s["file"], s["key"])
                         for s in manifest["leaves"][key]["shards"]}
                for s in meta["shards"]:
                    if (s["file"], s["key"]) not in known:
                        manifest["leaves"][key]["shards"].append(s)
    return manifest


def _load_npz(ckpt_dir: Path, fname: str):
    try:
        return np.load(ckpt_dir / fname)
    except (OSError, ValueError) as e:
        raise CheckpointError(
            f"checkpoint {ckpt_dir}: shard file {fname!r} is "
            f"unreadable: {e}") from e


def _assemble(ckpt_dir: Path, meta: dict, npz_cache: Dict[str, Any]) -> np.ndarray:
    out = np.zeros(tuple(meta["shape"]), dtype=meta["dtype"])
    covered = np.zeros(tuple(meta["shape"]), dtype=bool) if meta["shape"] else None
    for s in meta["shards"]:
        if s["file"] not in npz_cache:
            npz_cache[s["file"]] = _load_npz(ckpt_dir, s["file"])
        data = npz_cache[s["file"]][s["key"]]
        idx = tuple(slice(a, b) for a, b in s["index"])
        out[idx] = data
        if covered is not None:
            covered[idx] = True
    if covered is not None and not covered.all():
        raise IOError(
            f"Checkpoint shard coverage incomplete for a leaf of shape "
            f"{meta['shape']} — missing process shard files?")
    return out


def restore_sharded(ckpt_dir: Union[str, Path],
                    mesh_ctx: Optional[MeshContext] = None,
                    verify: bool = True) -> Dict[str, Any]:
    """Read a sharded checkpoint into a nested-dict pytree.

    With ``mesh_ctx``, each leaf is device_put with its SAVED PartitionSpec
    on the target mesh (axis names must exist there; unknown axes fall back
    to replication). Without, returns host numpy arrays.

    Verifies the COMMIT marker + shard checksums first: a half-written
    or corrupted step raises ``CheckpointError`` instead of assembling
    garbage params. ``verify=False`` skips the full-CRC pass when the
    caller just ran ``verify_sharded`` itself (CheckpointManager does).
    """
    ckpt_dir = Path(ckpt_dir)
    manifest = _merge_manifests(ckpt_dir, verify=verify)
    npz_cache: Dict[str, Any] = {}
    flat: Dict[str, np.ndarray] = {}
    for key, meta in manifest["leaves"].items():
        arr = _assemble(ckpt_dir, meta, npz_cache)
        if mesh_ctx is not None:
            spec_elems = []
            axes = set(mesh_ctx.mesh.axis_names)
            for p in (meta["spec"] or []):
                if isinstance(p, list):
                    p = tuple(x for x in p if x in axes) or None
                elif p is not None and p not in axes:
                    p = None
                spec_elems.append(p)
            sharding = NamedSharding(mesh_ctx.mesh, P(*spec_elems))
            arr = jax.device_put(arr, sharding)
        flat[key] = arr
    # rebuild nesting from '/'-joined leaf paths
    tree: Dict[str, Any] = {}
    for key, arr in flat.items():
        parts = key.split("/")
        cur = tree
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = arr
    return tree


def read_topology(ckpt_dir: Union[str, Path]) -> Optional[dict]:
    """The mesh topology recorded at save time ({"dp", "
    weight_update_sharding", "process_count"} — see
    CheckpointManager.save), or None for checkpoints that predate the
    record. Cheap (manifest parse only, no verification) — the
    up-front width-change check reads this before any assembly."""
    try:
        with open(Path(ckpt_dir) / MANIFEST) as f:
            return json.load(f).get("topology")
    except (OSError, ValueError):
        return None


def _reshard_flat_leaf(key: str, arr: np.ndarray, shape, dtype) -> np.ndarray:
    """Target-mesh reshard of one zero1 weight-update leaf: the saved
    leaf is the flattened pad-to-divisible ``(dp_old, chunk)`` view
    (``mesh.zero1_shard_leaf``); the template wants the ORIGINAL
    ``shape``. Dropping the padding tail and reshaping is exact — the
    values are bitwise those of a replicated ``gather_updater_state``
    of the original, so a restore at any new width (the new trainer
    re-flattens to ``(dp_new, chunk')``) changes layout only."""
    size = int(np.prod(shape)) if shape else 1
    if arr.ndim != 2 or arr.size < size or arr.size - size >= arr.shape[0] \
            or np.dtype(arr.dtype) != np.dtype(dtype):
        raise CheckpointError(
            f"leaf {key!r}: checkpoint shape {tuple(arr.shape)} is not a "
            f"zero1 (dp, chunk) view of template shape {tuple(shape)} — "
            "cannot reshard across this width change")
    return arr.reshape(-1)[:size].reshape(shape)


def restore_sharded_into(ckpt_dir: Union[str, Path], template: Any,
                         mesh_ctx: Optional[MeshContext] = None,
                         verify: bool = True,
                         reshard_zero1: bool = False) -> Any:
    """Restore into the exact structure of ``template`` (lists stay lists,
    custom pytree nodes stay themselves) — leaf lookup by flattened path.
    Shapes must match the saved checkpoint. ``verify=False``: see
    ``restore_sharded``.

    ``reshard_zero1=True`` is the target-mesh reshard path for restores
    across a data-parallel width change: a leaf whose checkpoint shape
    is a zero1 ``(dp_old, chunk)`` flattened view of the template's
    (full) shape is un-padded back to that shape and placed REPLICATED
    on ``mesh_ctx`` (not with its saved 1/dp spec — the old axis extent
    no longer exists); the new-width trainer re-flattens it to
    ``(dp_new, chunk')`` when it attaches. Any other shape mismatch
    still raises.
    """
    ckpt_dir = Path(ckpt_dir)
    manifest = _merge_manifests(ckpt_dir, verify=verify)
    npz_cache: Dict[str, Any] = {}
    leaves_with_paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for path, leaf in leaves_with_paths:
        key = _leaf_key(path)
        if key not in manifest["leaves"]:
            raise KeyError(f"Checkpoint has no leaf {key!r}")
        meta = manifest["leaves"][key]
        if tuple(meta["shape"]) != tuple(np.shape(leaf)):
            if not reshard_zero1:
                raise ValueError(
                    f"Leaf {key!r}: checkpoint shape {tuple(meta['shape'])} "
                    f"!= template shape {tuple(np.shape(leaf))}")
            arr = _reshard_flat_leaf(
                key, _assemble(ckpt_dir, meta, npz_cache),
                tuple(np.shape(leaf)),
                np.asarray(leaf).dtype if not hasattr(leaf, "dtype")
                else leaf.dtype)
            if mesh_ctx is not None:
                arr = jax.device_put(
                    arr, NamedSharding(mesh_ctx.mesh, P()))
            new_leaves.append(arr)
            continue
        arr = _assemble(ckpt_dir, meta, npz_cache)
        if mesh_ctx is not None:
            axes = set(mesh_ctx.mesh.axis_names)
            spec_elems = []
            for p in (meta["spec"] or []):
                if isinstance(p, list):
                    p = tuple(x for x in p if x in axes) or None
                elif p is not None and p not in axes:
                    p = None
                spec_elems.append(p)
            arr = jax.device_put(arr, NamedSharding(mesh_ctx.mesh,
                                                    P(*spec_elems)))
        elif isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            arr = jax.device_put(arr, leaf.sharding)
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)
