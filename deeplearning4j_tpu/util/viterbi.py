"""Viterbi decoding (ref: deeplearning4j-nn/.../util/Viterbi.java).

Two entry points:

- ``viterbi_decode(emission_logprobs, transition_logprobs)`` — general
  max-sum decoding over a lattice, vectorized over states per step.
- ``Viterbi`` — the reference's noisy-channel label smoother: observed
  labels are assumed correct with probability ``p_correct`` and states
  persist with probability ``meta_stability``; ``decode`` returns the most
  likely true label sequence (Viterbi.java:30-120 semantics).
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import numpy as np


def viterbi_decode(emission_logprobs: np.ndarray,
                   transition_logprobs: np.ndarray,
                   initial_logprobs: np.ndarray = None
                   ) -> Tuple[float, np.ndarray]:
    """Most likely state path. emission: [T, S]; transition: [S, S]
    (transition[i, j] = logp(j at t+1 | i at t)). Returns (path logprob,
    state indices [T])."""
    em = np.asarray(emission_logprobs, np.float64)
    tr = np.asarray(transition_logprobs, np.float64)
    T, S = em.shape
    if initial_logprobs is None:
        initial_logprobs = np.full(S, -math.log(S))
    delta = initial_logprobs + em[0]
    back = np.zeros((T, S), np.int64)
    for t in range(1, T):
        cand = delta[:, None] + tr          # [S_prev, S_next]
        back[t] = cand.argmax(axis=0)
        delta = cand.max(axis=0) + em[t]
    path = np.zeros(T, np.int64)
    path[-1] = int(delta.argmax())
    for t in range(T - 2, -1, -1):
        path[t] = back[t + 1][path[t + 1]]
    return float(delta.max()), path


class Viterbi:
    """Noisy-channel smoothing of a predicted label sequence."""

    def __init__(self, possible_labels: Sequence[float],
                 meta_stability: float = 0.9, p_correct: float = 0.99):
        self.possible_labels = np.asarray(possible_labels)
        self.states = len(self.possible_labels)
        self.meta_stability = meta_stability
        self.p_correct = p_correct

    def decode(self, labels: np.ndarray,
               binary_label_matrix: bool = None) -> Tuple[float, np.ndarray]:
        """labels: either a one-hot matrix [T, S] or an index vector [T].
        Returns (sequence logprob, smoothed label values)."""
        labels = np.asarray(labels)
        if binary_label_matrix is None:
            binary_label_matrix = labels.ndim == 2
        if binary_label_matrix:
            obs = labels.argmax(axis=1)
        else:
            # label VALUES -> state indices (possible_labels need not be 0..S-1)
            value_to_state = {v: i for i, v in
                              enumerate(self.possible_labels.tolist())}
            try:
                obs = np.array([value_to_state[v] for v in labels.tolist()])
            except KeyError as e:
                raise ValueError(
                    f"Label {e.args[0]!r} not in possible_labels "
                    f"{self.possible_labels.tolist()}") from None
        T = len(obs)
        S = self.states
        # emission: observed label correct w.p. p_correct
        p_wrong = (1.0 - self.p_correct) / max(S - 1, 1)
        em = np.full((T, S), math.log(p_wrong))
        em[np.arange(T), obs] = math.log(self.p_correct)
        # transition: stay w.p. meta_stability
        p_switch = (1.0 - self.meta_stability) / max(S - 1, 1)
        tr = np.full((S, S), math.log(p_switch))
        np.fill_diagonal(tr, math.log(self.meta_stability))
        logp, path = viterbi_decode(em, tr)
        return logp, self.possible_labels[path]
