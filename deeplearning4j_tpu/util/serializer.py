"""Model checkpointing.

Ref: util/ModelSerializer.java:79-110 — the reference writes a **zip** with
``configuration.json`` (full conf DSL), ``coefficients.bin`` (the single
flattened param buffer) and ``updaterState.bin`` (flattened optimizer
state). We keep the same three-part logical format:

- ``configuration.json`` — MultiLayerConfiguration JSON round-trip
- ``coefficients.bin``   — float32 little-endian flat param vector in the
  documented layer/param order (``MultiLayerNetwork.params_flat``)
- ``updaterState.bin``   — flattened optax state leaves (+ a JSON manifest
  of leaf shapes/dtypes so the pytree is reconstructable)

For sharded multi-host checkpoints use parallel/checkpoint.py (orbax); this
zip format is the single-host interchange format matching the reference.
"""

from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np


class ModelSerializer:
    CONFIG_NAME = "configuration.json"
    COEFFICIENTS_NAME = "coefficients.bin"
    UPDATER_NAME = "updaterState.bin"
    UPDATER_MANIFEST = "updaterState.json"

    @staticmethod
    def write_model(net, path: Union[str, Path], save_updater: bool = True) -> None:
        """(ref: ModelSerializer.writeModel:79-110)"""
        path = Path(path)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr(ModelSerializer.CONFIG_NAME, net.conf.to_json())
            flat = net.params_flat().astype("<f4")
            z.writestr(ModelSerializer.COEFFICIENTS_NAME, flat.tobytes())
            # layer states (BN running stats) — the reference stores these as
            # params; we keep them as a separate npz member
            state_buf = io.BytesIO()
            state_arrays = {}
            for i, s in enumerate(net.states or []):
                for k, v in s.items():
                    state_arrays[f"{i}:{k}"] = np.asarray(v)
            np.savez(state_buf, **state_arrays)
            z.writestr("layerStates.npz", state_buf.getvalue())
            if save_updater and net.opt_state is not None:
                leaves = jax.tree_util.tree_leaves(net.opt_state)
                arr_leaves = [np.asarray(l) for l in leaves
                              if hasattr(l, "shape")]
                manifest = [{"shape": list(a.shape), "dtype": str(a.dtype)}
                            for a in arr_leaves]
                flat_state = (np.concatenate([a.astype("<f4").ravel()
                                              for a in arr_leaves])
                              if arr_leaves else np.zeros(0, "<f4"))
                z.writestr(ModelSerializer.UPDATER_NAME, flat_state.tobytes())
                z.writestr(ModelSerializer.UPDATER_MANIFEST,
                           json.dumps(manifest))

    @staticmethod
    def restore_multi_layer_network(path: Union[str, Path],
                                    load_updater: bool = True):
        """(ref: ModelSerializer.restoreMultiLayerNetwork)"""
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        path = Path(path)
        with zipfile.ZipFile(path, "r") as z:
            conf = MultiLayerConfiguration.from_json(
                z.read(ModelSerializer.CONFIG_NAME).decode())
            net = MultiLayerNetwork(conf)
            net.init()
            flat = np.frombuffer(
                z.read(ModelSerializer.COEFFICIENTS_NAME), dtype="<f4")
            net.set_params_flat(flat)
            if "layerStates.npz" in z.namelist():
                with z.open("layerStates.npz") as f:
                    data = np.load(io.BytesIO(f.read()))
                    for key in data.files:
                        i_s, name = key.split(":", 1)
                        net.states[int(i_s)][name] = jnp.asarray(data[key])
            if (load_updater
                    and ModelSerializer.UPDATER_NAME in z.namelist()):
                manifest = json.loads(
                    z.read(ModelSerializer.UPDATER_MANIFEST).decode())
                blob = np.frombuffer(z.read(ModelSerializer.UPDATER_NAME),
                                     dtype="<f4")
                leaves, treedef = jax.tree_util.tree_flatten(net.opt_state)
                pos = 0
                mi = 0
                new_leaves = []
                for leaf in leaves:
                    if hasattr(leaf, "shape"):
                        spec = manifest[mi]
                        n = int(np.prod(spec["shape"])) if spec["shape"] else 1
                        arr = blob[pos:pos + n].reshape(spec["shape"])
                        new_leaves.append(jnp.asarray(arr, spec["dtype"]))
                        pos += n
                        mi += 1
                    else:
                        new_leaves.append(leaf)
                net.opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return net
