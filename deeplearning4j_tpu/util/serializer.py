"""Model checkpointing.

Ref: util/ModelSerializer.java:79-110 — the reference writes a **zip** with
``configuration.json`` (full conf DSL), ``coefficients.bin`` (the single
flattened param buffer) and ``updaterState.bin`` (flattened optimizer
state); ``restoreMultiLayerNetwork`` / ``restoreComputationGraph`` cover
both containers. We keep the same three-part logical format:

- ``configuration.json`` — MultiLayerConfiguration OR
  ComputationGraphConfiguration JSON round-trip (discriminated by the
  embedded ``format`` tag)
- ``coefficients.bin``   — float32 little-endian flat param vector in the
  documented layer/param order (``params_flat`` on either container)
- ``updaterState.bin``   — flattened optax state leaves (+ a JSON manifest
  of leaf shapes/dtypes so the pytree is reconstructable)

For sharded multi-host checkpoints use
``deeplearning4j_tpu.parallel.checkpoint`` (per-process shard files); this
zip format is the single-host interchange format matching the reference.
"""

from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np


class ModelSerializer:
    CONFIG_NAME = "configuration.json"
    COEFFICIENTS_NAME = "coefficients.bin"
    UPDATER_NAME = "updaterState.bin"
    UPDATER_MANIFEST = "updaterState.json"

    @staticmethod
    def write_model(net, path: Union[str, Path], save_updater: bool = True) -> None:
        """(ref: ModelSerializer.writeModel:79-110)"""
        path = Path(path)
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr(ModelSerializer.CONFIG_NAME, net.conf.to_json())
            flat = net.params_flat().astype("<f4")
            z.writestr(ModelSerializer.COEFFICIENTS_NAME, flat.tobytes())
            # layer states (BN running stats) — the reference stores these as
            # params; we keep them as a separate npz member. MLN states are a
            # list (key = layer index); CG states a dict (key = node name).
            state_buf = io.BytesIO()
            state_arrays = {}
            state_items = (net.states.items() if isinstance(net.states, dict)
                           else enumerate(net.states or []))
            for i, s in state_items:
                for k, v in s.items():
                    state_arrays[f"{i}:{k}"] = np.asarray(v)
            np.savez(state_buf, **state_arrays)
            z.writestr("layerStates.npz", state_buf.getvalue())
            if save_updater and net.opt_state is not None:
                leaves = jax.tree_util.tree_leaves(net.opt_state)
                arr_leaves = [np.asarray(l) for l in leaves
                              if hasattr(l, "shape")]
                manifest = [{"shape": list(a.shape), "dtype": str(a.dtype)}
                            for a in arr_leaves]
                flat_state = (np.concatenate([a.astype("<f4").ravel()
                                              for a in arr_leaves])
                              if arr_leaves else np.zeros(0, "<f4"))
                z.writestr(ModelSerializer.UPDATER_NAME, flat_state.tobytes())
                z.writestr(ModelSerializer.UPDATER_MANIFEST,
                           json.dumps(manifest))

    @staticmethod
    def _restore_into(z: zipfile.ZipFile, net, load_updater: bool):
        """Shared param/state/updater restore for both containers."""
        flat = np.frombuffer(
            z.read(ModelSerializer.COEFFICIENTS_NAME), dtype="<f4")
        net.set_params_flat(flat)
        if "layerStates.npz" in z.namelist():
            with z.open("layerStates.npz") as f:
                data = np.load(io.BytesIO(f.read()))
                for key in data.files:
                    i_s, name = key.split(":", 1)
                    idx = i_s if isinstance(net.states, dict) else int(i_s)
                    net.states[idx][name] = jnp.asarray(data[key])
        if load_updater and ModelSerializer.UPDATER_NAME in z.namelist():
            manifest = json.loads(
                z.read(ModelSerializer.UPDATER_MANIFEST).decode())
            blob = np.frombuffer(z.read(ModelSerializer.UPDATER_NAME),
                                 dtype="<f4")
            leaves, treedef = jax.tree_util.tree_flatten(net.opt_state)
            pos = 0
            mi = 0
            new_leaves = []
            for leaf in leaves:
                if hasattr(leaf, "shape"):
                    spec = manifest[mi]
                    n = int(np.prod(spec["shape"])) if spec["shape"] else 1
                    arr = blob[pos:pos + n].reshape(spec["shape"])
                    new_leaves.append(jnp.asarray(arr, spec["dtype"]))
                    pos += n
                    mi += 1
                else:
                    new_leaves.append(leaf)
            net.opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return net

    @staticmethod
    def _config_json(path: Union[str, Path]) -> dict:
        with zipfile.ZipFile(Path(path), "r") as z:
            return json.loads(z.read(ModelSerializer.CONFIG_NAME).decode())

    @staticmethod
    def restore_multi_layer_network(path: Union[str, Path],
                                    load_updater: bool = True):
        """(ref: ModelSerializer.restoreMultiLayerNetwork)"""
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        with zipfile.ZipFile(Path(path), "r") as z:
            cfg = json.loads(z.read(ModelSerializer.CONFIG_NAME).decode())
            if "ComputationGraph" in cfg.get("format", ""):
                raise ValueError(
                    "Archive holds a ComputationGraph; use "
                    "restore_computation_graph")
            conf = MultiLayerConfiguration.from_dict(cfg)
            net = MultiLayerNetwork(conf)
            net.init()
            return ModelSerializer._restore_into(z, net, load_updater)

    @staticmethod
    def restore_computation_graph(path: Union[str, Path],
                                  load_updater: bool = True):
        """(ref: ModelSerializer.restoreComputationGraph:79-110 — the
        reference's single entry covers both containers; here a dedicated
        restore using the CG conf + topological param order from
        nn/graph.py params_flat)."""
        from deeplearning4j_tpu.nn.conf.graph_builder import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        with zipfile.ZipFile(Path(path), "r") as z:
            cfg = json.loads(z.read(ModelSerializer.CONFIG_NAME).decode())
            if "ComputationGraph" not in cfg.get("format", ""):
                raise ValueError(
                    "Archive holds a MultiLayerNetwork; use "
                    "restore_multi_layer_network")
            conf = ComputationGraphConfiguration.from_dict(cfg)
            net = ComputationGraph(conf)
            net.init()
            return ModelSerializer._restore_into(z, net, load_updater)

    @staticmethod
    def restore_model(path: Union[str, Path], load_updater: bool = True):
        """Container-agnostic restore, discriminating on the config's
        ``format`` tag (mirrors the reference's restore helpers that accept
        either archive kind)."""
        cfg = ModelSerializer._config_json(path)
        if "ComputationGraph" in cfg.get("format", ""):
            return ModelSerializer.restore_computation_graph(
                path, load_updater)
        return ModelSerializer.restore_multi_layer_network(path, load_updater)
