"""Model checkpointing.

Ref: util/ModelSerializer.java:79-110 — the reference writes a **zip** with
``configuration.json`` (full conf DSL), ``coefficients.bin`` (the single
flattened param buffer) and ``updaterState.bin`` (flattened optimizer
state); ``restoreMultiLayerNetwork`` / ``restoreComputationGraph`` cover
both containers. We keep the same three-part logical format:

- ``configuration.json`` — MultiLayerConfiguration OR
  ComputationGraphConfiguration JSON round-trip (discriminated by the
  embedded ``format`` tag)
- ``coefficients.bin``   — float32 little-endian flat param vector in the
  documented layer/param order (``params_flat`` on either container)
- ``updaterState.bin``   — flattened optax state leaves, each in its
  NATIVE dtype (+ a JSON manifest of leaf shapes/dtypes so the pytree is
  reconstructable). Earlier archives forced every leaf through ``<f4``,
  silently corrupting int32 step counters past 2^24 and degrading
  non-f32 moments; the v2 manifest (``{"version": 2, ...}``) marks
  native storage, and a bare-list manifest is restored with the legacy
  all-f4 decode so old archives keep working.

Crash safety (resilience subsystem): the archive is assembled in memory
and committed with ``atomic_write_bytes`` (tmp + fsync + rename) — a
kill mid-save can never leave a torn file at the final path — and a
``checksums.json`` member records each member's CRC-32 so ``verify``/
restore detect bit-rot and truncated members, raising
``CheckpointError`` naming the bad file instead of returning garbage
params.

For sharded multi-host checkpoints use
``deeplearning4j_tpu.parallel.checkpoint`` (per-process shard files); this
zip format is the single-host interchange format matching the reference.
"""

from __future__ import annotations

import io
import json
import zipfile
import zlib
from pathlib import Path
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.resilience.atomic import (CheckpointError,
                                                  atomic_path, crc32_bytes)


class ModelSerializer:
    CONFIG_NAME = "configuration.json"
    COEFFICIENTS_NAME = "coefficients.bin"
    UPDATER_NAME = "updaterState.bin"
    UPDATER_MANIFEST = "updaterState.json"
    CHECKSUMS_NAME = "checksums.json"

    @staticmethod
    def write_model(net, path: Union[str, Path], save_updater: bool = True) -> None:
        """(ref: ModelSerializer.writeModel:79-110) — atomic commit: the
        previous checkpoint at ``path`` stays intact until the new
        archive is fully on disk."""
        path = Path(path)
        members: dict = {}
        members[ModelSerializer.CONFIG_NAME] = \
            net.conf.to_json().encode()
        flat = net.params_flat().astype("<f4")
        members[ModelSerializer.COEFFICIENTS_NAME] = flat.tobytes()
        # layer states (BN running stats) — the reference stores these as
        # params; we keep them as a separate npz member. MLN states are a
        # list (key = layer index); CG states a dict (key = node name).
        state_buf = io.BytesIO()
        state_arrays = {}
        state_items = (net.states.items() if isinstance(net.states, dict)
                       else enumerate(net.states or []))
        for i, s in state_items:
            for k, v in s.items():
                state_arrays[f"{i}:{k}"] = np.asarray(v)
        np.savez(state_buf, **state_arrays)
        members["layerStates.npz"] = state_buf.getvalue()
        if save_updater and net.opt_state is not None:
            leaves = jax.tree_util.tree_leaves(net.opt_state)
            arr_leaves = [np.ascontiguousarray(np.asarray(l))
                          for l in leaves if hasattr(l, "shape")]
            manifest = {
                "version": 2,  # native-dtype storage (v1 = all <f4)
                "leaves": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                           for a in arr_leaves],
            }
            blob = b"".join(a.tobytes() for a in arr_leaves)
            members[ModelSerializer.UPDATER_NAME] = blob
            members[ModelSerializer.UPDATER_MANIFEST] = \
                json.dumps(manifest).encode()
        checksums = {name: crc32_bytes(data)
                     for name, data in members.items()}
        # zip straight into the tmp file — staging the whole archive in
        # a BytesIO would transiently double host RAM at scale
        with atomic_path(path) as tmp:
            with zipfile.ZipFile(tmp, "w", zipfile.ZIP_DEFLATED) as z:
                for name, data in members.items():
                    z.writestr(name, data)
                z.writestr(ModelSerializer.CHECKSUMS_NAME,
                           json.dumps(checksums))

    # --------------------------------------------------------- verification
    @staticmethod
    def _read_member(z: zipfile.ZipFile, name: str,
                     path: Union[str, Path]) -> bytes:
        """Read one member, mapping every decode failure to a
        CheckpointError that names the member."""
        try:
            return z.read(name)
        except KeyError:
            raise CheckpointError(
                f"checkpoint {path}: missing member {name!r}") from None
        except (zipfile.BadZipFile, zlib.error, OSError) as e:
            raise CheckpointError(
                f"checkpoint {path}: member {name!r} is corrupt "
                f"({e})") from e

    @staticmethod
    def verify(path: Union[str, Path]) -> None:
        """Full integrity check: zip structure, member CRCs (both the
        zip's own and our ``checksums.json``), and the presence of the
        required members. Raises ``CheckpointError`` naming the first
        bad file; returns None when the archive is clean."""
        path = Path(path)
        try:
            with zipfile.ZipFile(path, "r") as z:
                bad = z.testzip()
                if bad is not None:
                    raise CheckpointError(
                        f"checkpoint {path}: member {bad!r} fails its "
                        "CRC (torn or bit-flipped write)")
                names = set(z.namelist())
                for req in (ModelSerializer.CONFIG_NAME,
                            ModelSerializer.COEFFICIENTS_NAME):
                    if req not in names:
                        raise CheckpointError(
                            f"checkpoint {path}: missing member {req!r}")
                if ModelSerializer.CHECKSUMS_NAME in names:
                    sums = json.loads(z.read(
                        ModelSerializer.CHECKSUMS_NAME).decode())
                    for name, want in sums.items():
                        if name not in names:
                            raise CheckpointError(
                                f"checkpoint {path}: missing member "
                                f"{name!r}")
                        got = crc32_bytes(
                            ModelSerializer._read_member(z, name, path))
                        if got != want:
                            raise CheckpointError(
                                f"checkpoint {path}: member {name!r} "
                                f"checksum mismatch (got {got:#010x}, "
                                f"manifest {want:#010x})")
        except CheckpointError:
            # CheckpointError IS an IOError — our own precise diagnoses
            # must not be re-wrapped by the clause below
            raise
        except (zipfile.BadZipFile, OSError) as e:
            raise CheckpointError(
                f"checkpoint {path} is unreadable: {e}") from e

    @staticmethod
    def _restore_into(z: zipfile.ZipFile, net, load_updater: bool,
                      path: Union[str, Path] = "<archive>"):
        """Shared param/state/updater restore for both containers."""
        flat = np.frombuffer(
            ModelSerializer._read_member(
                z, ModelSerializer.COEFFICIENTS_NAME, path), dtype="<f4")
        net.set_params_flat(flat)
        if "layerStates.npz" in z.namelist():
            data = np.load(io.BytesIO(
                ModelSerializer._read_member(z, "layerStates.npz", path)))
            for key in data.files:
                i_s, name = key.split(":", 1)
                idx = i_s if isinstance(net.states, dict) else int(i_s)
                net.states[idx][name] = jnp.asarray(data[key])
        if load_updater and ModelSerializer.UPDATER_NAME in z.namelist():
            manifest = json.loads(ModelSerializer._read_member(
                z, ModelSerializer.UPDATER_MANIFEST, path).decode())
            blob = ModelSerializer._read_member(
                z, ModelSerializer.UPDATER_NAME, path)
            if isinstance(manifest, dict):  # v2: native-dtype leaves
                specs = manifest["leaves"]
                legacy_f4 = False
            else:  # v1 legacy: bare list, every leaf stored as <f4
                specs = manifest
                legacy_f4 = True
                blob_f4 = np.frombuffer(blob, dtype="<f4")
            leaves, treedef = jax.tree_util.tree_flatten(net.opt_state)
            pos = 0
            mi = 0
            new_leaves = []
            for leaf in leaves:
                if hasattr(leaf, "shape"):
                    spec = specs[mi]
                    n = int(np.prod(spec["shape"])) if spec["shape"] else 1
                    if legacy_f4:
                        arr = blob_f4[pos:pos + n].reshape(spec["shape"])
                        new_leaves.append(jnp.asarray(arr, spec["dtype"]))
                        pos += n
                    else:
                        dt = np.dtype(spec["dtype"])
                        nbytes = n * dt.itemsize
                        arr = np.frombuffer(
                            blob[pos:pos + nbytes],
                            dtype=dt).reshape(spec["shape"])
                        new_leaves.append(jnp.asarray(arr))
                        pos += nbytes
                    mi += 1
                else:
                    new_leaves.append(leaf)
            net.opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)
        return net

    @staticmethod
    def restore_weights(path: Union[str, Path], net,
                        load_updater: bool = True, verify: bool = True):
        """Restore params/states/updater from ``path`` into an EXISTING
        initialized container (the FaultTolerantTrainer resume path —
        no re-build, no re-trace). Verifies checksums first;
        ``verify=False`` skips the full-CRC pass when the caller just
        verified (CheckpointManager.latest_valid did)."""
        path = Path(path)
        if verify:
            ModelSerializer.verify(path)
        try:
            with zipfile.ZipFile(path, "r") as z:
                return ModelSerializer._restore_into(z, net, load_updater,
                                                     path=path)
        except CheckpointError:
            raise
        except (zipfile.BadZipFile, OSError) as e:
            raise CheckpointError(
                f"checkpoint {path} is unreadable: {e}") from e

    @staticmethod
    def _config_json(path: Union[str, Path]) -> dict:
        try:
            with zipfile.ZipFile(Path(path), "r") as z:
                return json.loads(ModelSerializer._read_member(
                    z, ModelSerializer.CONFIG_NAME, path).decode())
        except CheckpointError:
            raise  # already precisely diagnosed (and IS an IOError)
        except (zipfile.BadZipFile, OSError) as e:
            raise CheckpointError(
                f"checkpoint {path} is unreadable: {e}") from e

    @staticmethod
    def restore_multi_layer_network(path: Union[str, Path],
                                    load_updater: bool = True):
        """(ref: ModelSerializer.restoreMultiLayerNetwork)"""
        from deeplearning4j_tpu.nn.conf.builder import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        cfg = ModelSerializer._config_json(path)
        if "ComputationGraph" in cfg.get("format", ""):
            raise ValueError(
                "Archive holds a ComputationGraph; use "
                "restore_computation_graph")
        conf = MultiLayerConfiguration.from_dict(cfg)
        net = MultiLayerNetwork(conf)
        net.init()
        return ModelSerializer.restore_weights(path, net, load_updater)

    @staticmethod
    def restore_computation_graph(path: Union[str, Path],
                                  load_updater: bool = True):
        """(ref: ModelSerializer.restoreComputationGraph:79-110 — the
        reference's single entry covers both containers; here a dedicated
        restore using the CG conf + topological param order from
        nn/graph.py params_flat)."""
        from deeplearning4j_tpu.nn.conf.graph_builder import (
            ComputationGraphConfiguration,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        cfg = ModelSerializer._config_json(path)
        if "ComputationGraph" not in cfg.get("format", ""):
            raise ValueError(
                "Archive holds a MultiLayerNetwork; use "
                "restore_multi_layer_network")
        conf = ComputationGraphConfiguration.from_dict(cfg)
        net = ComputationGraph(conf)
        net.init()
        return ModelSerializer.restore_weights(path, net, load_updater)

    @staticmethod
    def restore_model(path: Union[str, Path], load_updater: bool = True):
        """Container-agnostic restore, discriminating on the config's
        ``format`` tag (mirrors the reference's restore helpers that accept
        either archive kind)."""
        cfg = ModelSerializer._config_json(path)
        if "ComputationGraph" in cfg.get("format", ""):
            return ModelSerializer.restore_computation_graph(
                path, load_updater)
        return ModelSerializer.restore_multi_layer_network(path, load_updater)
