"""Time-series shape/mask utilities
(ref: deeplearning4j-nn/.../util/TimeSeriesUtils.java).

Array layout note: the reference stores time series as [B, F, T]
(channels-middle); this framework's convention is [B, T, F] throughout, so
the 3d<->2d reshapes here flatten (B, T) rather than the reference's
permute-then-reshape dance."""

from __future__ import annotations

import numpy as np


def moving_average(to_avg: np.ndarray, n: int) -> np.ndarray:
    """Trailing n-point moving average along the last axis
    (TimeSeriesUtils.java:44 — cumsum formulation, output length T-n+1)."""
    a = np.asarray(to_avg, np.float64)
    csum = np.cumsum(a, axis=-1)
    out = csum[..., n - 1:].copy()
    out[..., 1:] -= csum[..., :-n]
    return out / n


def reshape_3d_to_2d(x: np.ndarray) -> np.ndarray:
    """[B, T, F] -> [B*T, F] (TimeSeriesUtils.java:93)."""
    B, T, F = x.shape
    return x.reshape(B * T, F)


def reshape_2d_to_3d(x: np.ndarray, minibatch_size: int) -> np.ndarray:
    """[B*T, F] -> [B, T, F] (TimeSeriesUtils.java:105)."""
    BT, F = x.shape
    return x.reshape(minibatch_size, BT // minibatch_size, F)


def reshape_time_series_mask_to_vector(mask: np.ndarray) -> np.ndarray:
    """[B, T] mask -> [B*T] (TimeSeriesUtils.java:58)."""
    return np.asarray(mask).reshape(-1)


def reshape_vector_to_time_series_mask(vec: np.ndarray,
                                       minibatch_size: int) -> np.ndarray:
    """[B*T] -> [B, T] (TimeSeriesUtils.java:74)."""
    v = np.asarray(vec).reshape(minibatch_size, -1)
    return v
