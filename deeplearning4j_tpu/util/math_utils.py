"""Math utilities (ref: deeplearning4j-nn/.../util/MathUtils.java — the
statistics/feature-weighting helpers the NLP and evaluation stacks use).
Vectorized numpy instead of the reference's scalar-loop Java."""

from __future__ import annotations

import math
from typing import Sequence

import numpy as np


def normalize(val: float, minimum: float, maximum: float) -> float:
    """Squash to [0,1] given an observed range (MathUtils.java:54)."""
    if maximum == minimum:
        return 0.0
    return (val - minimum) / (maximum - minimum)


def clamp(value: int, minimum: int, maximum: int) -> int:
    return max(minimum, min(value, maximum))


def discretize(value: float, minimum: float, maximum: float,
               bin_count: int) -> int:
    """Map a continuous value to a bin index (MathUtils.java:84:
    ``int(binCount * normalize)`` clamped to [0, binCount - 1])."""
    return clamp(int(bin_count * normalize(value, minimum, maximum)),
                 0, bin_count - 1)


def next_pow_of_2(v: int) -> int:
    """Smallest power of two >= v (MathUtils.java:95)."""
    if v <= 0:
        return 1
    return 1 << (int(v - 1).bit_length())


def sigmoid(x: float) -> float:
    return 1.0 / (1.0 + math.exp(-x))


def log2(a: float) -> float:
    return math.log(a) / math.log(2)


def entropy(probabilities: Sequence[float]) -> float:
    """Shannon entropy in bits."""
    p = np.asarray(probabilities, np.float64)
    p = p[p > 0]
    return float(-(p * np.log2(p)).sum())


def correlation(residuals: Sequence[float],
                target: Sequence[float]) -> float:
    """Pearson correlation (MathUtils.java:149)."""
    r = np.asarray(residuals, np.float64)
    t = np.asarray(target, np.float64)
    rc, tc = r - r.mean(), t - t.mean()
    denom = math.sqrt((rc ** 2).sum() * (tc ** 2).sum())
    return float((rc * tc).sum() / denom) if denom else 0.0


def ss_reg(residuals: Sequence[float], target: Sequence[float]) -> float:
    """Regression sum of squares (MathUtils.java:175)."""
    r = np.asarray(residuals, np.float64)
    t = np.asarray(target, np.float64)
    return float(((r - t.mean()) ** 2).sum())


def ss_error(predicted: Sequence[float], target: Sequence[float]) -> float:
    """Error sum of squares (MathUtils.java:190)."""
    p = np.asarray(predicted, np.float64)
    t = np.asarray(target, np.float64)
    return float(((t - p) ** 2).sum())


def ss_total(residuals: Sequence[float], target: Sequence[float]) -> float:
    t = np.asarray(target, np.float64)
    return float(((t - t.mean()) ** 2).sum())


def determination_coefficient(y1: Sequence[float], y2: Sequence[float],
                              n: int) -> float:
    """R^2 (MathUtils.java:722)."""
    return correlation(y1[:n], y2[:n]) ** 2


def vector_length(vector: Sequence[float]) -> float:
    v = np.asarray(vector, np.float64)
    return float(np.sqrt((v ** 2).sum()))


def sum_of_squares(vector: Sequence[float]) -> float:
    v = np.asarray(vector, np.float64)
    return float((v ** 2).sum())


def variance(vector: Sequence[float]) -> float:
    """Sample variance over n-1 (MathUtils.java:504 semantics)."""
    v = np.asarray(vector, np.float64)
    if len(v) < 2:
        return 0.0
    return float(((v - v.mean()) ** 2).sum() / (len(v) - 1))


def root_means_squared_error(real: Sequence[float],
                             predicted: Sequence[float]) -> float:
    r = np.asarray(real, np.float64)
    p = np.asarray(predicted, np.float64)
    return float(np.sqrt(((r - p) ** 2).mean()))


def euclidean_distance(a: Sequence[float], b: Sequence[float]) -> float:
    return vector_length(np.asarray(a, np.float64) - np.asarray(b, np.float64))


def manhattan_distance(a: Sequence[float], b: Sequence[float]) -> float:
    return float(np.abs(np.asarray(a, np.float64)
                        - np.asarray(b, np.float64)).sum())


# -- tf-idf (used by the bag-of-words vectorizers, MathUtils.java:258-283) --

def idf(total_docs: float, docs_containing: float) -> float:
    """(MathUtils.java idf: log10, not natural log)"""
    if docs_containing == 0 or total_docs == 0:
        return 0.0
    return math.log10(total_docs / docs_containing)


def tf(count: int, document_length: int) -> float:
    return count / document_length if document_length else 0.0


def tfidf(tf_value: float, idf_value: float) -> float:
    return tf_value * idf_value
