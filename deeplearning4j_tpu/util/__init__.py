"""Utilities: model serialization, gradient checking support."""

from deeplearning4j_tpu.util.serializer import ModelSerializer  # noqa: F401
