"""Utilities: model serialization, math/time-series helpers, Viterbi
(ref: deeplearning4j-nn/.../util/)."""

from deeplearning4j_tpu.util.serializer import ModelSerializer  # noqa: F401
from deeplearning4j_tpu.util.viterbi import Viterbi, viterbi_decode  # noqa: F401
from deeplearning4j_tpu.util import math_utils, time_series  # noqa: F401
