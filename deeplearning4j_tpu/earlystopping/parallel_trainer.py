"""Early stopping over the mesh-parallel trainer.

Ref: deeplearning4j-scaleout-parallelwrapper/.../EarlyStoppingParallelTrainer.java
(372 LoC — early stopping driven by a ParallelWrapper underneath; listener
plumbing to pull scores out of the worker pool). Here the "wrapper" is the
SPMD ParallelTrainer, so the early-stopping loop is the single-device one
with the batch step routed through the mesh."""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.earlystopping.config import EarlyStoppingConfiguration
from deeplearning4j_tpu.earlystopping.trainer import EarlyStoppingTrainer
from deeplearning4j_tpu.parallel.mesh import MeshContext
from deeplearning4j_tpu.parallel.trainer import ParallelTrainer


class _ParallelNetAdapter:
    """Presents the (net, trainer) pair through the net-like surface the
    early-stopping loop drives: fit_batch routes through the mesh, score
    and state live on the underlying net."""

    def __init__(self, trainer: ParallelTrainer):
        self._trainer = trainer
        self.net = trainer.net

    def fit_batch(self, batch):
        loss = self._trainer.fit_batch(batch)
        self.net.score_value = float(loss)
        return loss

    def __getattr__(self, name):
        return getattr(self.net, name)

    def __setattr__(self, name, value):
        if name in ("_trainer", "net"):
            object.__setattr__(self, name, value)
        else:
            setattr(self.net, name, value)


class EarlyStoppingParallelTrainer(EarlyStoppingTrainer):
    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_data: DataSetIterator,
                 mesh: Optional[MeshContext] = None,
                 gradient_accumulation: int = 1,
                 collect_training_stats: bool = False,
                 weight_update_sharding=None,
                 precision=None):
        trainer = ParallelTrainer(
            net, mesh, gradient_accumulation=gradient_accumulation,
            collect_training_stats=collect_training_stats,
            weight_update_sharding=weight_update_sharding,
            precision=precision)
        if hasattr(train_data, "attach"):
            # the early-stopping loop iterates train_data directly
            # (never through ParallelTrainer.fit), so bind a streaming
            # pipeline's device stage to the mesh here — same contract
            # as ParallelTrainer.fit: batches arrive pre-placed in the
            # step's NamedSharding layout instead of landing replicated
            # and resharding every step
            train_data.attach(mesh=trainer.mesh)
        super().__init__(config, _ParallelNetAdapter(trainer), train_data)
        self.trainer = trainer

    def shardcheck(self, batch, **overrides):
        """Statically verify the underlying SPMD step's compiled-program
        contracts (analysis/shardcheck) — the early-stopping loop drives
        the same ParallelTrainer step per batch."""
        return self.trainer.shardcheck(batch, **overrides)
