"""Early stopping (ref: deeplearning4j-nn/.../earlystopping/)."""

from deeplearning4j_tpu.earlystopping.config import (  # noqa: F401
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    BestScoreEpochTerminationCondition,
    InvalidScoreIterationTerminationCondition,
    DataSetLossCalculator,
    InMemoryModelSaver,
    LocalFileModelSaver,
)
from deeplearning4j_tpu.earlystopping.config import (  # noqa: F401
    LocalFileModelSaver as LocalFileGraphSaver,
)
from deeplearning4j_tpu.earlystopping.trainer import (  # noqa: F401
    EarlyStoppingGraphTrainer,
    EarlyStoppingListener,
    EarlyStoppingTrainer,
)
from deeplearning4j_tpu.earlystopping.parallel_trainer import (  # noqa: F401
    EarlyStoppingParallelTrainer,
)
