"""Early stopping configuration: termination conditions, score calculators,
model savers.

Ref: earlystopping/EarlyStoppingConfiguration.java + termination/ (epoch &
iteration conditions), scorecalc/DataSetLossCalculator.java, saver/
{InMemoryModelSaver, LocalFileModelSaver}.java.
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

from deeplearning4j_tpu.datasets.iterator import DataSetIterator


# ----------------------------------------------------------- epoch conditions
class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


@dataclass
class MaxEpochsTerminationCondition(EpochTerminationCondition):
    max_epochs: int = 30

    def terminate(self, epoch, score):
        return epoch >= self.max_epochs


@dataclass
class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs with no score improvement
    (ref: termination/ScoreImprovementEpochTerminationCondition.java)."""
    max_epochs_without_improvement: int = 5
    min_improvement: float = 0.0

    def initialize(self):
        self._best: Optional[float] = None
        self._since = 0

    def terminate(self, epoch, score):
        if self._best is None or self._best - score > self.min_improvement:
            self._best = score
            self._since = 0
            return False
        self._since += 1
        return self._since >= self.max_epochs_without_improvement


@dataclass
class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score reaches a target
    (ref: termination/BestScoreEpochTerminationCondition.java)."""
    best_expected_score: float = 0.0
    lesser_better: bool = True  # minimizing loss

    def terminate(self, epoch, score):
        return (score <= self.best_expected_score if self.lesser_better
                else score >= self.best_expected_score)


# -------------------------------------------------------- iteration conditions
class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, score: float) -> bool:
        raise NotImplementedError


@dataclass
class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort if the score explodes past a bound
    (ref: termination/MaxScoreIterationTerminationCondition.java)."""
    max_score: float = 1e9

    def terminate(self, score):
        return score > self.max_score or score != score  # NaN guard


@dataclass
class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    max_seconds: float = 3600.0

    def initialize(self):
        self._start = time.monotonic()

    def terminate(self, score):
        return (time.monotonic() - self._start) > self.max_seconds


@dataclass
class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort on NaN/Inf scores
    (ref: termination/InvalidScoreIterationTerminationCondition.java)."""

    def terminate(self, score):
        return score != score or score in (float("inf"), float("-inf"))


# ------------------------------------------------------------ score calculator
@dataclass
class DataSetLossCalculator:
    """Model score (loss) over a held-out iterator
    (ref: scorecalc/DataSetLossCalculator.java)."""
    iterator: DataSetIterator
    average: bool = True

    def calculate_score(self, net) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        for batch in self.iterator:
            s = net.score(batch)
            b = batch.num_examples()
            total += s * b
            n += b
        return total / max(n, 1) if self.average else total


# --------------------------------------------------------------------- savers
class InMemoryModelSaver:
    """(ref: saver/InMemoryModelSaver.java)"""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score: float):
        self._best = (copy.deepcopy(net.params), copy.deepcopy(net.states), score)

    def save_latest_model(self, net, score: float):
        self._latest = (copy.deepcopy(net.params), copy.deepcopy(net.states), score)

    def get_best_model(self, net):
        if self._best is None:
            return net
        net.params, net.states = (copy.deepcopy(self._best[0]),
                                  copy.deepcopy(self._best[1]))
        return net


class LocalFileModelSaver:
    """Write bestModel.zip / latestModel.zip
    (ref: saver/LocalFileModelSaver.java)."""

    def __init__(self, directory: str):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def save_best_model(self, net, score: float):
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        ModelSerializer.write_model(net, self.dir / "bestModel.zip")

    def save_latest_model(self, net, score: float):
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        ModelSerializer.write_model(net, self.dir / "latestModel.zip")

    def get_best_model(self, net):
        from deeplearning4j_tpu.util.serializer import ModelSerializer
        path = self.dir / "bestModel.zip"
        if path.exists():
            # container-agnostic restore: the archive may hold either a
            # MultiLayerNetwork or a ComputationGraph
            # (EarlyStoppingGraphTrainer / LocalFileGraphSaver)
            return ModelSerializer.restore_model(path)
        return net


# ---------------------------------------------------------------- config+result
@dataclass
class EarlyStoppingConfiguration:
    """(ref: earlystopping/EarlyStoppingConfiguration.java Builder)"""
    epoch_termination_conditions: List[EpochTerminationCondition] = field(
        default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = field(
        default_factory=list)
    score_calculator: Optional[DataSetLossCalculator] = None
    model_saver: object = field(default_factory=InMemoryModelSaver)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclass
class EarlyStoppingResult:
    """(ref: earlystopping/EarlyStoppingResult.java)"""
    termination_reason: str
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: object
