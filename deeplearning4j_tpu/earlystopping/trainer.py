"""Early stopping trainer.

Ref: earlystopping/trainer/EarlyStoppingTrainer.java:34 — epoch loop with
per-iteration abort conditions, periodic held-out scoring, best-model
checkpointing, and a typed result.
"""

from __future__ import annotations

from typing import Optional, Union

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration, EarlyStoppingResult,
)


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_data: DataSetIterator, listener=None):
        self.config = config
        self.net = net
        self.train_data = train_data
        self.listener = listener  # EarlyStoppingListener or None

    def set_listener(self, listener) -> None:
        """(ref: IEarlyStoppingTrainer.setListener)"""
        self.listener = listener

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        net = self.net
        if self.listener is not None:
            self.listener.on_start(cfg, net)
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        score_vs_epoch = {}
        best_score: Optional[float] = None
        best_epoch = -1
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            self.train_data.reset()
            aborted = False
            for batch in self.train_data:
                net.fit_batch(batch)
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(net.score_value):
                        reason = "IterationTerminationCondition"
                        details = f"{type(c).__name__} at score {net.score_value}"
                        aborted = True
                        break
                if aborted:
                    break
            if aborted:
                break
            epoch += 1
            net.epoch_count += 1
            if epoch % cfg.evaluate_every_n_epochs == 0:
                if cfg.score_calculator is not None:
                    score = cfg.score_calculator.calculate_score(net)
                else:
                    score = net.score_value
                score_vs_epoch[epoch] = score
                # per-phase telemetry when driven by a stats-collecting
                # ParallelTrainer (checkpoint = saver/serializer time)
                from deeplearning4j_tpu.optimize.training_stats import (
                    maybe_phase)
                stats = getattr(getattr(net, "_trainer", None),
                                "training_stats", None)
                if best_score is None or score < best_score:
                    best_score = score
                    best_epoch = epoch
                    with maybe_phase(stats, "checkpoint"):
                        cfg.model_saver.save_best_model(net, score)
                if cfg.save_last_model:
                    with maybe_phase(stats, "checkpoint"):
                        cfg.model_saver.save_latest_model(net, score)
            if self.listener is not None:
                self.listener.on_epoch(
                    epoch, score_vs_epoch.get(epoch, net.score_value),
                    cfg, net)
            stop = False
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, score_vs_epoch.get(epoch, net.score_value)):
                    reason = "EpochTerminationCondition"
                    details = f"{type(c).__name__} at epoch {epoch}"
                    stop = True
                    break
            if stop:
                break
        # drain lag-pending divergence flags BEFORE picking the best
        # model: a raise-policy sentinel must not let a run whose last
        # step diverged report a clean result (resilience/sentinel.py)
        sentinel = getattr(net, "_sentinel", None)
        if sentinel is not None:
            sentinel.flush()
        best_model = cfg.model_saver.get_best_model(net)
        result = EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_score is not None else float("nan"),
            score_vs_epoch=score_vs_epoch,
            best_model=best_model,
        )
        if self.listener is not None:
            self.listener.on_completion(result)
        return result


class EarlyStoppingListener:
    """Callbacks around the early-stopping loop
    (ref: listener/EarlyStoppingListener.java — onStart/onEpoch/
    onCompletion)."""

    def on_start(self, config, net) -> None:
        pass

    def on_epoch(self, epoch: int, score: float, config, net) -> None:
        pass

    def on_completion(self, result) -> None:
        pass


class EarlyStoppingGraphTrainer(EarlyStoppingTrainer):
    """Reference-named trainer for ComputationGraph models
    (ref: trainer/EarlyStoppingGraphTrainer.java). The base trainer is
    container-agnostic (fit_batch/score contract), so this is the naming
    alias the reference API promises."""
