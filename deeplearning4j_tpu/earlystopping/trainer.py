"""Early stopping trainer.

Ref: earlystopping/trainer/EarlyStoppingTrainer.java:34 — epoch loop with
per-iteration abort conditions, periodic held-out scoring, best-model
checkpointing, and a typed result.
"""

from __future__ import annotations

from typing import Optional, Union

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator
from deeplearning4j_tpu.earlystopping.config import (
    EarlyStoppingConfiguration, EarlyStoppingResult,
)


class EarlyStoppingTrainer:
    def __init__(self, config: EarlyStoppingConfiguration, net,
                 train_data: DataSetIterator):
        self.config = config
        self.net = net
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        net = self.net
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        score_vs_epoch = {}
        best_score: Optional[float] = None
        best_epoch = -1
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            self.train_data.reset()
            aborted = False
            for batch in self.train_data:
                net.fit_batch(batch)
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(net.score_value):
                        reason = "IterationTerminationCondition"
                        details = f"{type(c).__name__} at score {net.score_value}"
                        aborted = True
                        break
                if aborted:
                    break
            if aborted:
                break
            epoch += 1
            net.epoch_count += 1
            if epoch % cfg.evaluate_every_n_epochs == 0:
                if cfg.score_calculator is not None:
                    score = cfg.score_calculator.calculate_score(net)
                else:
                    score = net.score_value
                score_vs_epoch[epoch] = score
                if best_score is None or score < best_score:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best_model(net, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(net, score)
            stop = False
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, score_vs_epoch.get(epoch, net.score_value)):
                    reason = "EpochTerminationCondition"
                    details = f"{type(c).__name__} at epoch {epoch}"
                    stop = True
                    break
            if stop:
                break
        best_model = cfg.model_saver.get_best_model(net)
        return EarlyStoppingResult(
            termination_reason=reason,
            termination_details=details,
            total_epochs=epoch,
            best_model_epoch=best_epoch,
            best_model_score=best_score if best_score is not None else float("nan"),
            score_vs_epoch=score_vs_epoch,
            best_model=best_model,
        )
