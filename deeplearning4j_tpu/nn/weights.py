"""Weight initialization.

Mirrors the reference's ``WeightInit`` enum + ``WeightInitUtil``
(ref: nn/weights/WeightInit.java:47-48 — DISTRIBUTION, ZERO, SIGMOID_UNIFORM,
UNIFORM, XAVIER, XAVIER_UNIFORM, XAVIER_FAN_IN, XAVIER_LEGACY, RELU,
RELU_UNIFORM) and the distribution confs under nn/conf/distribution/.

``init_weight(rng, shape, fan_in, fan_out, scheme, distribution)`` returns a
jnp array. Fan-in/fan-out are passed explicitly because conv kernels compute
them from receptive-field size, as WeightInitUtil does.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class Distribution:
    """Serializable distribution spec (ref: nn/conf/distribution/*.java)."""
    kind: str  # "normal" | "uniform" | "binomial" | "gaussian"
    mean: float = 0.0
    std: float = 1.0
    lower: float = -1.0
    upper: float = 1.0
    n_trials: int = 1
    prob: float = 0.5

    @staticmethod
    def normal(mean: float = 0.0, std: float = 1.0) -> "Distribution":
        return Distribution(kind="normal", mean=mean, std=std)

    @staticmethod
    def uniform(lower: float, upper: float) -> "Distribution":
        return Distribution(kind="uniform", lower=lower, upper=upper)

    def sample(self, rng: jax.Array, shape) -> jax.Array:
        if self.kind in ("normal", "gaussian"):
            return self.mean + self.std * jax.random.normal(rng, shape)
        if self.kind == "uniform":
            return jax.random.uniform(rng, shape, minval=self.lower, maxval=self.upper)
        if self.kind == "binomial":
            return jax.random.binomial(rng, self.n_trials, self.prob, shape).astype(jnp.float32)
        raise ValueError(self.kind)

    def to_dict(self) -> dict:
        return {"kind": self.kind, "mean": self.mean, "std": self.std,
                "lower": self.lower, "upper": self.upper,
                "n_trials": self.n_trials, "prob": self.prob}

    @staticmethod
    def from_dict(d: dict) -> "Distribution":
        return Distribution(**d)


WEIGHT_INITS = (
    "distribution", "zero", "one", "sigmoid_uniform", "uniform",
    "xavier", "xavier_uniform", "xavier_fan_in", "xavier_legacy",
    "relu", "relu_uniform", "lecun_normal", "normal",
)


def init_weight(
    rng: jax.Array,
    shape,
    fan_in: float,
    fan_out: float,
    scheme: str = "xavier",
    distribution: Optional[Distribution] = None,
    dtype=jnp.float32,
) -> jax.Array:
    """Sample an initial weight tensor (ref: nn/weights/WeightInitUtil.java)."""
    scheme = scheme.lower()
    if scheme == "distribution":
        if distribution is None:
            raise ValueError("weight_init='distribution' requires a Distribution")
        return distribution.sample(rng, shape).astype(dtype)
    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "one":
        return jnp.ones(shape, dtype)
    if scheme == "uniform":
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(rng, shape, minval=-a, maxval=a).astype(dtype)
    if scheme == "sigmoid_uniform":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, minval=-a, maxval=a).astype(dtype)
    if scheme == "xavier":
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return (std * jax.random.normal(rng, shape)).astype(dtype)
    if scheme == "xavier_uniform":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, minval=-a, maxval=a).astype(dtype)
    if scheme == "xavier_fan_in":
        std = math.sqrt(1.0 / fan_in)
        return (std * jax.random.normal(rng, shape)).astype(dtype)
    if scheme == "xavier_legacy":
        std = math.sqrt(1.0 / (fan_in + fan_out))
        return (std * jax.random.normal(rng, shape)).astype(dtype)
    if scheme == "relu":
        std = math.sqrt(2.0 / fan_in)
        return (std * jax.random.normal(rng, shape)).astype(dtype)
    if scheme == "relu_uniform":
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, minval=-a, maxval=a).astype(dtype)
    if scheme == "lecun_normal":
        std = math.sqrt(1.0 / fan_in)
        return (std * jax.random.normal(rng, shape)).astype(dtype)
    if scheme == "normal":
        return (jax.random.normal(rng, shape) / math.sqrt(fan_in)).astype(dtype)
    raise ValueError(f"Unknown weight init {scheme!r}; available: {WEIGHT_INITS}")
