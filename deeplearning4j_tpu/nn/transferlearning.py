"""Transfer learning: rebuild networks from pretrained ones.

Ref: nn/transferlearning/TransferLearning.java:34-129 (Builder),
FineTuneConfiguration.java (global hyperparameter overrides),
TransferLearningHelper.java (freeze + featurize-and-cache).

Capabilities matching the reference Builder:
- ``set_feature_extractor(n)``  — freeze layers [0..n] (FrozenLayer wrapper
  in the reference; the ``frozen`` flag + update mask here)
- ``n_out_replace(i, n_out, weight_init)`` — swap a layer's output width,
  re-initializing it and the following layer's inputs
- ``remove_output_layer`` / ``remove_layers_from_output(k)``
- ``add_layer(layer)``
- ``fine_tune_configuration(...)`` — override updater/lr/etc.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.builder import (
    ListBuilder, MultiLayerConfiguration, NeuralNetConfiguration,
    TrainingConfig, UpdaterConfig,
)
from deeplearning4j_tpu.nn.layers.base import BaseLayerConf
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Hyperparameter overrides applied to the copied conf
    (ref: transferlearning/FineTuneConfiguration.java)."""
    updater: Optional[str] = None
    learning_rate: Optional[float] = None
    seed: Optional[int] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None

    def apply(self, training: TrainingConfig, layers: List[BaseLayerConf]):
        if self.updater is not None:
            training.updater.name = self.updater.lower()
        if self.learning_rate is not None:
            training.updater.learning_rate = self.learning_rate
        if self.seed is not None:
            training.seed = self.seed
        for l in layers:
            if self.l1 is not None:
                l.l1 = self.l1
            if self.l2 is not None:
                l.l2 = self.l2
            if self.dropout is not None:
                l.dropout = self.dropout


class TransferLearning:
    """``TransferLearning.builder(net)`` (ref: TransferLearning.Builder)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            net._check_init()
            self._src = net
            self._conf = copy.deepcopy(net.conf)
            self._params = [dict(p) for p in net.params]
            self._states = [dict(s) for s in net.states]
            self._freeze_until: Optional[int] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._reinit: List[int] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0..layer_index] inclusive
            (ref: Builder.setFeatureExtractor)."""
            self._freeze_until = layer_index
            return self

        def n_out_replace(self, layer_index: int, n_out: int,
                          weight_init: Optional[str] = None):
            """Change layer_index's n_out, re-initializing it and the next
            parameterized layer's inputs (ref: Builder.nOutReplace)."""
            layers = self._conf.layers
            layer = layers[layer_index]
            layer.n_out = n_out
            if weight_init is not None:
                layer.weight_init = weight_init
            self._reinit.append(layer_index)
            # next layer's n_in changes => re-init it too
            for j in range(layer_index + 1, len(layers)):
                nxt = layers[j]
                if nxt.has_params():
                    nxt.n_in = n_out
                    self._reinit.append(j)
                    break
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, k: int):
            for _ in range(k):
                self._conf.layers.pop()
                self._params.pop()
                self._states.pop()
                if self._conf.input_types:
                    self._conf.input_types.pop()
            return self

        def add_layer(self, layer: BaseLayerConf):
            layers = self._conf.layers
            # infer n_in from the previous layer's output type
            prev_out = None
            for prev in reversed(layers):
                t = getattr(prev, "n_out", None)
                if t:
                    prev_out = t
                    break
            from deeplearning4j_tpu.nn.conf.inputs import InputType
            if prev_out is not None:
                in_t = InputType.feed_forward(prev_out)
                layer.set_n_in(in_t)
                if self._conf.input_types:
                    self._conf.input_types.append(in_t)
            from deeplearning4j_tpu.nn.layers.base import GlobalConf
            layer.apply_global_defaults(GlobalConf())
            layers.append(layer)
            self._params.append({})
            self._states.append({})
            self._reinit.append(len(layers) - 1)
            return self

        def build(self) -> MultiLayerNetwork:
            if self._fine_tune is not None:
                self._fine_tune.apply(self._conf.training, self._conf.layers)
            if self._freeze_until is not None:
                for i in range(self._freeze_until + 1):
                    self._conf.layers[i].frozen = True
            net = MultiLayerNetwork(self._conf)
            # re-init changed layers, keep the rest of the pretrained params
            key = jax.random.PRNGKey(self._conf.training.seed)
            keys = jax.random.split(key, max(len(self._conf.layers), 1))
            params = []
            for i, layer in enumerate(self._conf.layers):
                if i in self._reinit or not self._params[i]:
                    params.append(layer.init_params(keys[i])
                                  if layer.has_params() else {})
                else:
                    params.append(self._params[i])
            net.init(params=params)
            for i, s in enumerate(self._states):
                if i not in self._reinit and s:
                    net.states[i] = s
            return net

    @staticmethod
    def builder(net: MultiLayerNetwork) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)


class TransferLearningHelper:
    """Featurize-and-cache training for frozen-bottom networks
    (ref: transferlearning/TransferLearningHelper.java): run inputs through
    the frozen stack once, then train only the unfrozen top on the cached
    features."""

    def __init__(self, net: MultiLayerNetwork):
        net._check_init()
        self.net = net
        frozen = [i for i, l in enumerate(net.layers) if l.frozen]
        self._split = (max(frozen) + 1) if frozen else 0

    def featurize(self, features) -> jnp.ndarray:
        """Activations at the frozen/unfrozen boundary."""
        return self.net._activate_to(self._split, jnp.asarray(features))

    def unfrozen_net(self) -> MultiLayerNetwork:
        """A standalone net of the unfrozen top layers sharing params."""
        conf = copy.deepcopy(self.net.conf)
        conf.layers = conf.layers[self._split:]
        conf.preprocessors = {i - self._split: p
                              for i, p in conf.preprocessors.items()
                              if i >= self._split}
        conf.input_types = conf.input_types[self._split:]
        top = MultiLayerNetwork(conf)
        top.init(params=self.net.params[self._split:])
        top.states = self.net.states[self._split:]
        return top
