"""Transfer learning: rebuild networks from pretrained ones.

Ref: nn/transferlearning/TransferLearning.java:34-129 (Builder),
FineTuneConfiguration.java (global hyperparameter overrides),
TransferLearningHelper.java (freeze + featurize-and-cache).

Capabilities matching the reference Builder:
- ``set_feature_extractor(n)``  — freeze layers [0..n] (FrozenLayer wrapper
  in the reference; the ``frozen`` flag + update mask here)
- ``n_out_replace(i, n_out, weight_init)`` — swap a layer's output width,
  re-initializing it and the following layer's inputs
- ``remove_output_layer`` / ``remove_layers_from_output(k)``
- ``add_layer(layer)``
- ``fine_tune_configuration(...)`` — override updater/lr/etc.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import List, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.builder import (
    ListBuilder, MultiLayerConfiguration, NeuralNetConfiguration,
    TrainingConfig, UpdaterConfig,
)
from deeplearning4j_tpu.nn.layers.base import BaseLayerConf
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclass
class FineTuneConfiguration:
    """Hyperparameter overrides applied to the copied conf
    (ref: transferlearning/FineTuneConfiguration.java)."""
    updater: Optional[str] = None
    learning_rate: Optional[float] = None
    seed: Optional[int] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None

    def apply(self, training: TrainingConfig, layers: List[BaseLayerConf]):
        if self.updater is not None:
            training.updater.name = self.updater.lower()
        if self.learning_rate is not None:
            training.updater.learning_rate = self.learning_rate
        if self.seed is not None:
            training.seed = self.seed
        for l in layers:
            if self.l1 is not None:
                l.l1 = self.l1
            if self.l2 is not None:
                l.l2 = self.l2
            if self.dropout is not None:
                l.dropout = self.dropout


class TransferLearning:
    """``TransferLearning.builder(net)`` (ref: TransferLearning.Builder)."""

    class Builder:
        def __init__(self, net: MultiLayerNetwork):
            net._check_init()
            self._src = net
            self._conf = copy.deepcopy(net.conf)
            self._params = [dict(p) for p in net.params]
            self._states = [dict(s) for s in net.states]
            self._freeze_until: Optional[int] = None
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._reinit: List[int] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, layer_index: int):
            """Freeze layers [0..layer_index] inclusive
            (ref: Builder.setFeatureExtractor)."""
            self._freeze_until = layer_index
            return self

        def n_out_replace(self, layer_index: int, n_out: int,
                          weight_init: Optional[str] = None):
            """Change layer_index's n_out, re-initializing it and the next
            parameterized layer's inputs (ref: Builder.nOutReplace)."""
            layers = self._conf.layers
            layer = layers[layer_index]
            layer.n_out = n_out
            if weight_init is not None:
                layer.weight_init = weight_init
            self._reinit.append(layer_index)
            # next layer's n_in changes => re-init it too
            for j in range(layer_index + 1, len(layers)):
                nxt = layers[j]
                if nxt.has_params():
                    nxt.n_in = n_out
                    self._reinit.append(j)
                    break
            return self

        def remove_output_layer(self):
            return self.remove_layers_from_output(1)

        def remove_layers_from_output(self, k: int):
            for _ in range(k):
                self._conf.layers.pop()
                self._params.pop()
                self._states.pop()
                if self._conf.input_types:
                    self._conf.input_types.pop()
            return self

        def add_layer(self, layer: BaseLayerConf):
            layers = self._conf.layers
            # infer n_in from the previous layer's output type
            prev_out = None
            for prev in reversed(layers):
                t = getattr(prev, "n_out", None)
                if t:
                    prev_out = t
                    break
            from deeplearning4j_tpu.nn.conf.inputs import InputType
            if prev_out is not None:
                in_t = InputType.feed_forward(prev_out)
                layer.set_n_in(in_t)
                if self._conf.input_types:
                    self._conf.input_types.append(in_t)
            from deeplearning4j_tpu.nn.layers.base import GlobalConf
            layer.apply_global_defaults(GlobalConf())
            layers.append(layer)
            self._params.append({})
            self._states.append({})
            self._reinit.append(len(layers) - 1)
            return self

        def build(self) -> MultiLayerNetwork:
            if self._fine_tune is not None:
                self._fine_tune.apply(self._conf.training, self._conf.layers)
            if self._freeze_until is not None:
                for i in range(self._freeze_until + 1):
                    self._conf.layers[i].frozen = True
            net = MultiLayerNetwork(self._conf)
            # re-init changed layers, keep the rest of the pretrained params
            key = jax.random.PRNGKey(self._conf.training.seed)
            keys = jax.random.split(key, max(len(self._conf.layers), 1))
            params = []
            for i, layer in enumerate(self._conf.layers):
                if i in self._reinit or not self._params[i]:
                    params.append(layer.init_params(keys[i])
                                  if layer.has_params() else {})
                else:
                    params.append(self._params[i])
            net.init(params=params)
            for i, s in enumerate(self._states):
                if i not in self._reinit and s:
                    net.states[i] = s
            return net

    class GraphBuilder:
        """Transfer learning on a ComputationGraph
        (ref: TransferLearning.java:34-129 GraphBuilder —
        setFeatureExtractor / nOutReplace / removeVertexAndConnections /
        addLayer / addVertex / setOutputs)."""

        def __init__(self, net):
            net._check_init()
            self._src = net
            self._conf = copy.deepcopy(net.conf)
            self._params = {k: dict(v) for k, v in net.params.items()}
            self._states = {k: dict(v) for k, v in net.states.items()}
            self._fine_tune: Optional[FineTuneConfiguration] = None
            self._freeze_at: List[str] = []
            self._reinit: List[str] = []

        def fine_tune_configuration(self, ftc: FineTuneConfiguration):
            self._fine_tune = ftc
            return self

        def set_feature_extractor(self, *names: str):
            """Freeze the named vertices and everything upstream of them
            (ref: GraphBuilder.setFeatureExtractor)."""
            self._freeze_at = list(names)
            return self

        def n_out_replace(self, layer_name: str, n_out: int,
                          weight_init: Optional[str] = None):
            """Change a layer's n_out and re-initialize it; downstream
            layers whose input widths change re-initialize via the shape
            pass + shape-mismatch detection at build
            (ref: GraphBuilder.nOutReplace)."""
            node = self._conf.nodes[layer_name]
            if node.layer is None:
                raise ValueError(f"{layer_name!r} is not a layer node")
            node.layer.n_out = n_out
            if weight_init is not None:
                node.layer.weight_init = weight_init
            self._reinit.append(layer_name)
            return self

        def remove_vertex_and_connections(self, name: str):
            """Drop a node and every edge referencing it
            (ref: GraphBuilder.removeVertexAndConnections). Consumers of
            the removed node must be rewired (add new layers/outputs)
            before build()."""
            self._conf.nodes.pop(name)
            self._params.pop(name, None)
            self._states.pop(name, None)
            for node in self._conf.nodes.values():
                node.inputs = [i for i in node.inputs if i != name]
            self._conf.network_outputs = [
                o for o in self._conf.network_outputs if o != name]
            return self

        def add_layer(self, name: str, layer: BaseLayerConf, *inputs: str):
            from deeplearning4j_tpu.nn.conf.graph_builder import NodeConf
            from deeplearning4j_tpu.nn.layers.base import GlobalConf
            if name in self._conf.nodes:
                raise ValueError(f"Duplicate node name {name!r}")
            layer.name = name
            layer.apply_global_defaults(GlobalConf())
            self._conf.nodes[name] = NodeConf(name=name, kind="layer",
                                              inputs=list(inputs),
                                              layer=layer)
            self._reinit.append(name)
            return self

        def add_vertex(self, name: str, vertex, *inputs: str):
            from deeplearning4j_tpu.nn.conf.graph_builder import NodeConf
            if name in self._conf.nodes:
                raise ValueError(f"Duplicate node name {name!r}")
            self._conf.nodes[name] = NodeConf(name=name, kind="vertex",
                                              inputs=list(inputs),
                                              vertex=vertex)
            return self

        def set_outputs(self, *names: str):
            for n in names:
                if n not in self._conf.nodes:
                    raise ValueError(f"Unknown output {n!r}")
            self._conf.network_outputs = list(names)
            return self

        def _ancestors(self, names: List[str]) -> set:
            """The named nodes plus everything upstream of them."""
            out = set()
            stack = list(names)
            while stack:
                n = stack.pop()
                if n in out:
                    continue
                out.add(n)
                stack.extend(self._conf.nodes[n].inputs)
            return out

        def build(self):
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            layer_confs = [n.layer for n in self._conf.nodes.values()
                           if n.layer is not None]
            if self._fine_tune is not None:
                self._fine_tune.apply(self._conf.training, layer_confs)
            if self._freeze_at:
                for n in self._ancestors(self._freeze_at):
                    node = self._conf.nodes[n]
                    if node.layer is not None:
                        node.layer.frozen = True
            self._conf._resolve_shapes()  # re-infer n_in after edits
            net = ComputationGraph(self._conf)
            net.init()
            # keep pretrained params wherever shapes still match and the
            # node wasn't explicitly re-initialized
            reinit = set(self._reinit)
            for name, p in net.params.items():
                if name in reinit or name not in self._params:
                    continue
                old = self._params[name]
                if (set(old) == set(p)
                        and all(old[k].shape == p[k].shape for k in p)):
                    net.params[name] = old
                    if name in self._states and self._states[name]:
                        net.states[name] = self._states[name]
            return net

    @staticmethod
    def builder(net) -> "TransferLearning.Builder":
        return TransferLearning.Builder(net)

    @staticmethod
    def graph_builder(net) -> "TransferLearning.GraphBuilder":
        return TransferLearning.GraphBuilder(net)


class TransferLearningHelper:
    """Featurize-and-cache training for frozen-bottom networks
    (ref: transferlearning/TransferLearningHelper.java): run inputs through
    the frozen stack once, then train only the unfrozen top on the cached
    features."""

    def __init__(self, net: MultiLayerNetwork):
        net._check_init()
        self.net = net
        frozen = [i for i, l in enumerate(net.layers) if l.frozen]
        self._split = (max(frozen) + 1) if frozen else 0

    def featurize(self, features) -> jnp.ndarray:
        """Activations at the frozen/unfrozen boundary."""
        return self.net._activate_to(self._split, jnp.asarray(features))

    def unfrozen_net(self) -> MultiLayerNetwork:
        """A standalone net of the unfrozen top layers sharing params."""
        conf = copy.deepcopy(self.net.conf)
        conf.layers = conf.layers[self._split:]
        conf.preprocessors = {i - self._split: p
                              for i, p in conf.preprocessors.items()
                              if i >= self._split}
        conf.input_types = conf.input_types[self._split:]
        top = MultiLayerNetwork(conf)
        top.init(params=self.net.params[self._split:])
        top.states = self.net.states[self._split:]
        return top
