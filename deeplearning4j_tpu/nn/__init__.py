"""Neural network runtime: configuration DSL, layers, containers, updaters."""
