"""InputType: shape metadata flowing through the config DSL.

Mirrors the reference's ``InputType`` sealed hierarchy
(ref: nn/conf/inputs/InputType.java:47 — FF / RNN / CNN / CNNFlat) which
drives nIn inference and automatic preprocessor insertion between layer
representation families.

Convention difference from the reference: CNN activations are **NHWC**
(TPU/XLA-native layout) rather than DL4J's NCHW. Shapes recorded here are
per-example (no batch dim).
"""

from __future__ import annotations

from dataclasses import dataclass, asdict
from typing import Optional, Tuple


@dataclass(frozen=True)
class InputType:
    kind: str  # "ff" | "rnn" | "cnn" | "cnnflat"
    size: Optional[int] = None            # ff / rnn feature size
    timesteps: Optional[int] = None       # rnn (None = variable)
    height: Optional[int] = None          # cnn
    width: Optional[int] = None
    channels: Optional[int] = None

    # ---- factories (mirror InputType.feedForward/recurrent/convolutional) ----
    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType(kind="ff", size=size)

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType(kind="rnn", size=size, timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnn", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType(kind="cnnflat", height=height, width=width, channels=channels,
                         size=height * width * channels)

    # ---- derived ----
    def flat_size(self) -> int:
        if self.kind in ("ff", "cnnflat"):
            return int(self.size)
        if self.kind == "rnn":
            return int(self.size)
        if self.kind == "cnn":
            return int(self.height * self.width * self.channels)
        raise ValueError(self.kind)

    def example_shape(self) -> Tuple[int, ...]:
        """Per-example array shape (batch dim excluded)."""
        if self.kind in ("ff", "cnnflat"):
            return (self.flat_size(),)
        if self.kind == "rnn":
            ts = self.timesteps or 1
            return (ts, self.size)  # [T, F] per example (batch-major [B,T,F])
        if self.kind == "cnn":
            return (self.height, self.width, self.channels)  # NHWC
        raise ValueError(self.kind)

    def to_dict(self) -> dict:
        return {k: v for k, v in asdict(self).items() if v is not None}

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)
