"""Configuration DSL (ref: deeplearning4j-nn/.../nn/conf/)."""

from deeplearning4j_tpu.nn.conf.inputs import InputType  # noqa: F401
from deeplearning4j_tpu.nn.conf.builder import (  # noqa: F401
    NeuralNetConfiguration,
    MultiLayerConfiguration,
    TrainingConfig,
    UpdaterConfig,
    ListBuilder,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (  # noqa: F401
    InputPreProcessor,
    CnnToFeedForwardPreProcessor,
    FeedForwardToCnnPreProcessor,
    RnnToFeedForwardPreProcessor,
    FeedForwardToRnnPreProcessor,
    CnnToRnnPreProcessor,
    RnnToCnnPreProcessor,
)
