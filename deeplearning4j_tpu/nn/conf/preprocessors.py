"""Input preprocessors: reshape activations between layer families.

Ref: nn/conf/preprocessor/{CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
FeedForwardToRnnPreProcessor, CnnToRnnPreProcessor, RnnToCnnPreProcessor}.java
— the reference reshapes both activations (forward) and epsilons (backward);
under autodiff only the forward reshape is needed. Auto-insertion between
mismatched layer families mirrors the legacy ConvolutionLayerSetup wiring
(ref: nn/conf/layers/setup/ConvolutionLayerSetup.java:40).

Layout note: CNN tensors are NHWC here (TPU-native) vs the reference's NCHW;
RNN tensors are [B, T, F] vs the reference's [B, F, T].
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

Array = jax.Array

PREPROCESSOR_REGISTRY: Dict[str, Type["InputPreProcessor"]] = {}


def register_preprocessor(cls):
    PREPROCESSOR_REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class InputPreProcessor:
    def transform(self, x: Array, in_type: InputType) -> Array:
        raise NotImplementedError

    def infer_output_type(self, in_type: InputType) -> InputType:
        raise NotImplementedError

    def transform_mask(self, mask: Optional[Array], in_type: InputType):
        return mask

    def to_dict(self) -> dict:
        d = {"@type": type(self).__name__}
        d.update({k: v for k, v in self.__dict__.items() if v is not None})
        return d

    @staticmethod
    def from_dict(d: dict) -> "InputPreProcessor":
        d = dict(d)
        tag = d.pop("@type")
        return PREPROCESSOR_REGISTRY[tag](**d)


@register_preprocessor
@dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    def transform(self, x, in_type):
        return x.reshape(x.shape[0], -1)

    def infer_output_type(self, in_type):
        return InputType.feed_forward(in_type.flat_size())


@register_preprocessor
@dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def transform(self, x, in_type):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def infer_output_type(self, in_type):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
@dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[B, T, F] kept as-is; downstream FF layers broadcast over T. The
    reference flattens to [B*T, F] (RnnToFeedForwardPreProcessor.java) — the
    broadcast form is numerically identical for dense ops and avoids the
    reshape round-trip."""

    def transform(self, x, in_type):
        return x

    def infer_output_type(self, in_type):
        return InputType.feed_forward(in_type.size)


@register_preprocessor
@dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    def transform(self, x, in_type):
        return x  # [B, T, F] already, or [B, F] broadcast handled by layer

    def infer_output_type(self, in_type):
        return InputType.recurrent(in_type.flat_size())


@register_preprocessor
@dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """[B, H, W, C] -> [B, T=H*W, F=C]? No — the reference treats each
    example's whole CNN volume as one timestep-feature vector per time slice
    is not well defined without a time axis; it flattens HWC to features and
    yields T=1. We follow: flatten to [B, 1, H*W*C]."""

    def transform(self, x, in_type):
        return x.reshape(x.shape[0], 1, -1)

    def infer_output_type(self, in_type):
        return InputType.recurrent(in_type.flat_size(), 1)


@register_preprocessor
@dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def transform(self, x, in_type):
        b, t, f = x.shape
        return x.reshape(b * t, self.height, self.width, self.channels)

    def infer_output_type(self, in_type):
        return InputType.convolutional(self.height, self.width, self.channels)


def auto_preprocessor(current: InputType, expected_kind: str) -> Optional[InputPreProcessor]:
    """Choose the preprocessor bridging ``current`` to a layer expecting
    ``expected_kind`` ('ff' | 'cnn' | 'rnn' | 'any')."""
    kind = "ff" if current.kind == "cnnflat" else current.kind
    if expected_kind in ("any", kind):
        if current.kind == "cnnflat" and expected_kind == "cnn":
            return FeedForwardToCnnPreProcessor(current.height, current.width,
                                                current.channels)
        return None
    if kind == "cnn" and expected_kind == "ff":
        return CnnToFeedForwardPreProcessor()
    if kind == "ff" and expected_kind == "cnn":
        if current.kind == "cnnflat":
            return FeedForwardToCnnPreProcessor(current.height, current.width,
                                                current.channels)
        raise ValueError(
            f"Cannot infer CNN shape from {current}; set an explicit "
            "FeedForwardToCnnPreProcessor")
    if kind == "rnn" and expected_kind == "ff":
        return RnnToFeedForwardPreProcessor()
    if kind == "ff" and expected_kind == "rnn":
        return FeedForwardToRnnPreProcessor()
    if kind == "cnn" and expected_kind == "rnn":
        return CnnToRnnPreProcessor()
    raise ValueError(f"No preprocessor from {current.kind} to {expected_kind}")
