"""Graph vertices: non-layer DAG ops for ComputationGraph.

Ref: nn/graph/vertex/impl/{MergeVertex, ElementWiseVertex, SubsetVertex,
StackVertex, UnstackVertex, L2Vertex, L2NormalizeVertex, ScaleVertex,
PreprocessorVertex, LayerVertex}.java and rnn/{LastTimeStepVertex,
DuplicateToTimeSeriesVertex}.java. Each vertex here is a dataclass with
``infer_output_type(list[InputType])`` and a pure
``apply(params, inputs, ...)``; the reference's hand-written doBackward
methods disappear under autodiff.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Type, Union

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

Array = jax.Array

VERTEX_REGISTRY: Dict[str, Type["GraphVertex"]] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclass
class GraphVertex:
    """Parameterless multi-input op in the DAG."""

    def n_inputs(self) -> Optional[int]:
        return None  # None = any

    def infer_output_type(self, in_types: List[InputType]) -> InputType:
        raise NotImplementedError

    def apply(self, inputs: List[Array]) -> Array:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"@type": type(self).__name__}
        d.update({k: v for k, v in self.__dict__.items() if v is not None})
        return d

    @staticmethod
    def from_dict(d: dict) -> "GraphVertex":
        d = dict(d)
        tag = d.pop("@type")
        cls = VERTEX_REGISTRY[tag]
        for k, v in list(d.items()):
            if isinstance(v, list):
                d[k] = tuple(v)
        return cls(**d)


@register_vertex
@dataclass
class MergeVertex(GraphVertex):
    """Concatenate along the feature (last) axis
    (ref: MergeVertex.java — concat along dim 1 in NCHW; here last axis in
    NHWC/FF, which is the same logical channel/feature axis)."""

    def infer_output_type(self, in_types):
        t0 = in_types[0]
        if t0.kind == "cnn":
            return InputType.convolutional(
                t0.height, t0.width, sum(t.channels for t in in_types))
        if t0.kind == "rnn":
            return InputType.recurrent(sum(t.size for t in in_types), t0.timesteps)
        return InputType.feed_forward(sum(t.flat_size() for t in in_types))

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=-1)


@register_vertex
@dataclass
class ElementWiseVertex(GraphVertex):
    """Pointwise add/subtract/product/average/max
    (ref: ElementWiseVertex.java — Op enum Add, Subtract, Product; later
    versions add Average/Max; subtract requires exactly 2 inputs)."""
    op: str = "add"

    def infer_output_type(self, in_types):
        return in_types[0]

    def apply(self, inputs):
        op = self.op.lower()
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            if len(inputs) != 2:
                raise ValueError("subtract needs exactly 2 inputs")
            return inputs[0] - inputs[1]
        if op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "average":
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown elementwise op {self.op!r}")


@register_vertex
@dataclass
class SubsetVertex(GraphVertex):
    """Feature-axis slice [from, to] inclusive (ref: SubsetVertex.java)."""
    from_index: int = 0
    to_index: int = 0

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        n = self.to_index - self.from_index + 1
        t = in_types[0]
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timesteps)
        return InputType.feed_forward(n)

    def apply(self, inputs):
        return inputs[0][..., self.from_index:self.to_index + 1]


@register_vertex
@dataclass
class StackVertex(GraphVertex):
    """Stack along the batch axis (ref: StackVertex.java — used for shared
    weights / triplet nets)."""

    def infer_output_type(self, in_types):
        return in_types[0]

    def apply(self, inputs):
        return jnp.concatenate(inputs, axis=0)


@register_vertex
@dataclass
class UnstackVertex(GraphVertex):
    """Take slice ``index`` of ``num_stacks`` along batch axis
    (ref: UnstackVertex.java)."""
    index: int = 0
    num_stacks: int = 1

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        return in_types[0]

    def apply(self, inputs):
        x = inputs[0]
        step = x.shape[0] // self.num_stacks
        return x[self.index * step:(self.index + 1) * step]


@register_vertex
@dataclass
class L2NormalizeVertex(GraphVertex):
    """x / ||x||_2 over feature axes (ref: L2NormalizeVertex.java)."""
    eps: float = 1e-8

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        return in_types[0]

    def apply(self, inputs):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return x / n


@register_vertex
@dataclass
class L2Vertex(GraphVertex):
    """Pairwise L2 distance between two inputs -> [batch, 1]
    (ref: L2Vertex.java — used by triplet/siamese losses)."""
    eps: float = 1e-8

    def n_inputs(self):
        return 2

    def infer_output_type(self, in_types):
        return InputType.feed_forward(1)

    def apply(self, inputs):
        a, b = inputs
        axes = tuple(range(1, a.ndim))
        return jnp.sqrt(jnp.sum((a - b) ** 2, axis=axes, keepdims=True) + self.eps)


@register_vertex
@dataclass
class ScaleVertex(GraphVertex):
    """Multiply by a fixed scalar (ref: ScaleVertex.java)."""
    scale_factor: float = 1.0

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        return in_types[0]

    def apply(self, inputs):
        return inputs[0] * self.scale_factor


@register_vertex
@dataclass
class ShiftVertex(GraphVertex):
    """Add a fixed scalar (ref: ShiftVertex.java)."""
    shift: float = 0.0

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        return in_types[0]

    def apply(self, inputs):
        return inputs[0] + self.shift


@register_vertex
@dataclass
class ReshapeVertex(GraphVertex):
    """Reshape trailing (non-batch) dims (ref: ReshapeVertex.java)."""
    shape: Tuple[int, ...] = ()

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        if len(self.shape) == 1:
            return InputType.feed_forward(self.shape[0])
        if len(self.shape) == 3:
            return InputType.convolutional(*self.shape)
        if len(self.shape) == 2:
            return InputType.recurrent(self.shape[1], self.shape[0])
        raise ValueError(self.shape)

    def apply(self, inputs):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape))


@register_vertex
@dataclass
class LastTimeStepVertex(GraphVertex):
    """[B, T, F] -> [B, F] at the last unmasked step
    (ref: rnn/LastTimeStepVertex.java). Mask-aware variant is applied by the
    container, which passes the current mask via ``apply_masked``."""

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        return InputType.feed_forward(in_types[0].size)

    def apply(self, inputs):
        return inputs[0][:, -1, :]

    def apply_masked(self, inputs, mask):
        if mask is None:
            return self.apply(inputs)
        x = inputs[0]
        # index of the LAST step where mask == 1 (works for pre- and
        # post-padding: scan the reversed mask for its first 1)
        T = mask.shape[1]
        idx = T - 1 - jnp.argmax(jnp.flip(mask, axis=1) > 0, axis=1)
        return x[jnp.arange(x.shape[0]), idx]


@register_vertex
@dataclass
class DuplicateToTimeSeriesVertex(GraphVertex):
    """[B, F] -> [B, T, F] by duplication. ``timesteps`` is either a
    fixed int T, or the NAME of a reference graph node whose current
    activation supplies T at runtime (the reference's semantics —
    ref: rnn/DuplicateToTimeSeriesVertex.java resolves the named input's
    shape per forward pass, which is what keeps the vertex correct when
    tBPTT slices the time axis)."""
    timesteps: Union[int, str] = 1

    def n_inputs(self):
        return 1

    def infer_output_type(self, in_types):
        t = self.timesteps if isinstance(self.timesteps, int) else None
        return InputType.recurrent(in_types[0].flat_size(), t)

    def apply(self, inputs, ref_act=None):
        if ref_act is not None:
            t = ref_act.shape[1]
        elif isinstance(self.timesteps, int):
            t = self.timesteps
        else:
            raise ValueError(
                f"DuplicateToTimeSeriesVertex references node "
                f"{self.timesteps!r} but no reference activation was "
                "supplied")
        return jnp.repeat(inputs[0][:, None, :], t, axis=1)
