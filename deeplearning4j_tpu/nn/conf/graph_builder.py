"""ComputationGraph configuration builder.

Ref: nn/conf/ComputationGraphConfiguration.java:90-116 + its GraphBuilder
(addInputs / addLayer / addVertex / setOutputs / setInputTypes), producing a
JSON-serializable DAG description. Topological ordering uses Kahn's
algorithm exactly like the reference (ComputationGraph.java:888
topologicalSortOrder).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from deeplearning4j_tpu.nn.conf.builder import (
    NeuralNetConfiguration, TrainingConfig, expected_input_kind,
)
from deeplearning4j_tpu.nn.conf.graph import GraphVertex, VERTEX_REGISTRY
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.preprocessors import (
    InputPreProcessor, auto_preprocessor,
)
from deeplearning4j_tpu.nn.layers.base import BaseLayerConf, layer_from_dict


@dataclass
class NodeConf:
    """One DAG node: an input placeholder, a layer, or a vertex op."""
    name: str
    kind: str                       # "input" | "layer" | "vertex"
    inputs: List[str] = field(default_factory=list)
    layer: Optional[BaseLayerConf] = None
    vertex: Optional[GraphVertex] = None
    preprocessor: Optional[InputPreProcessor] = None


@dataclass
class ComputationGraphConfiguration:
    nodes: Dict[str, NodeConf]
    network_inputs: List[str]
    network_outputs: List[str]
    input_types: Dict[str, InputType] = field(default_factory=dict)
    resolved_types: Dict[str, InputType] = field(default_factory=dict)
    topological_order: List[str] = field(default_factory=list)
    training: TrainingConfig = field(default_factory=TrainingConfig)

    # ------------------------------------------------------------------ serde
    def to_dict(self) -> dict:
        def node_dict(n: NodeConf) -> dict:
            d = {"name": n.name, "kind": n.kind, "inputs": n.inputs}
            if n.layer is not None:
                d["layer"] = n.layer.to_dict()
            if n.vertex is not None:
                d["vertex"] = n.vertex.to_dict()
            if n.preprocessor is not None:
                d["preprocessor"] = n.preprocessor.to_dict()
            return d

        return {
            "format": "deeplearning4j_tpu/ComputationGraphConfiguration",
            "version": 1,
            "training": self.training.to_dict(),
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "input_types": {k: v.to_dict() for k, v in self.input_types.items()},
            "nodes": [node_dict(self.nodes[name])
                      for name in self.topological_order],
            "topological_order": self.topological_order,
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        nodes: Dict[str, NodeConf] = {}
        for nd in d["nodes"]:
            nodes[nd["name"]] = NodeConf(
                name=nd["name"], kind=nd["kind"], inputs=list(nd["inputs"]),
                layer=layer_from_dict(nd["layer"]) if "layer" in nd else None,
                vertex=GraphVertex.from_dict(nd["vertex"]) if "vertex" in nd else None,
                preprocessor=(InputPreProcessor.from_dict(nd["preprocessor"])
                              if "preprocessor" in nd else None))
        conf = ComputationGraphConfiguration(
            nodes=nodes,
            network_inputs=list(d["network_inputs"]),
            network_outputs=list(d["network_outputs"]),
            input_types={k: InputType.from_dict(v)
                         for k, v in d.get("input_types", {}).items()},
            topological_order=list(d["topological_order"]),
            training=TrainingConfig.from_dict(d["training"]),
        )
        conf._resolve_shapes()
        return conf

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))

    # ------------------------------------------------------- static analysis
    def validate(self, mesh=None, batch_size: Optional[int] = None,
                 hbm_bytes: Optional[int] = None,
                 weight_update_sharding=None, precision=None):
        """Run graphcheck over this DAG: cycle/dangling/dead-vertex
        detection, shape walk, loss-head and mesh-legality checks (incl.
        zero1/zero2 weight-update-sharding legality and GC015
        precision-policy legality — the config's own
        ``training.precision`` is validated when ``precision`` is not
        given). Returns a list of ``analysis.Finding``; never raises on
        broken graphs (unlike ``_resolve_shapes``)."""
        from deeplearning4j_tpu.analysis.graphcheck import check_graph
        return check_graph(self, mesh=mesh, batch_size=batch_size,
                           hbm_bytes=hbm_bytes,
                           weight_update_sharding=weight_update_sharding,
                           precision=precision)

    def memory_report(self, batch_size: int = 32):
        """Parameter-count + HBM/VMEM estimate (``MemoryReport``
        analogue) for this graph at the given batch size."""
        from deeplearning4j_tpu.analysis.memory import memory_report
        return memory_report(self, batch_size=batch_size)

    def to_yaml(self) -> str:
        """YAML twin of ``to_json`` (ref: ComputationGraphConfiguration
        toYaml/fromYaml mirror NeuralNetConfiguration.java:283-360). The
        dict is normalized through JSON first so both formats carry the
        exact same data."""
        import yaml
        return yaml.safe_dump(json.loads(self.to_json()), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))

    # ------------------------------------------------------------ shape pass
    def _topo_sort(self) -> List[str]:
        """Kahn's algorithm (ref: ComputationGraph.topologicalSortOrder:888)."""
        indeg = {n: len(c.inputs) for n, c in self.nodes.items()}
        children: Dict[str, List[str]] = {n: [] for n in self.nodes}
        for n, c in self.nodes.items():
            for inp in c.inputs:
                if inp not in self.nodes:
                    raise ValueError(f"Node {n!r} references unknown input {inp!r}")
                children[inp].append(n)
        queue = [n for n, d in indeg.items() if d == 0]
        order: List[str] = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for ch in children[n]:
                indeg[ch] -= 1
                if indeg[ch] == 0:
                    queue.append(ch)
        if len(order) != len(self.nodes):
            cyc = [n for n, d in indeg.items() if d > 0]
            raise ValueError(f"Graph has a cycle involving {cyc}")
        return order

    def _resolve_shapes(self) -> None:
        """Infer every node's output InputType; auto-insert preprocessors at
        layer inputs; fill layer n_in (ref: ComputationGraphConfiguration
        .addPreProcessors + getLayerActivationTypes)."""
        self.topological_order = self._topo_sort()
        if not self.input_types:
            return
        types: Dict[str, InputType] = {}
        for name in self.topological_order:
            node = self.nodes[name]
            if node.kind == "input":
                types[name] = self.input_types[name]
                continue
            in_ts = [types[i] for i in node.inputs]
            if node.kind == "layer":
                cur = in_ts[0]
                if node.preprocessor is None:
                    p = auto_preprocessor(cur, expected_input_kind(node.layer))
                    node.preprocessor = p
                if node.preprocessor is not None:
                    cur = node.preprocessor.infer_output_type(cur)
                node.layer.set_n_in(cur)
                types[name] = node.layer.infer_output_type(cur)
            else:
                want = node.vertex.n_inputs()
                if want is not None and len(in_ts) != want:
                    raise ValueError(
                        f"Vertex {name!r} expects {want} inputs, got {len(in_ts)}")
                types[name] = node.vertex.infer_output_type(in_ts)
        self.resolved_types = types


class GraphBuilder:
    """Fluent DAG builder (ref: ComputationGraphConfiguration.GraphBuilder)."""

    def __init__(self, parent: NeuralNetConfiguration):
        self._parent = parent
        self._nodes: Dict[str, NodeConf] = {}
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._input_types: Dict[str, InputType] = {}

    def add_inputs(self, *names: str) -> "GraphBuilder":
        for n in names:
            self._inputs.append(n)
            self._nodes[n] = NodeConf(name=n, kind="input")
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        if len(types) != len(self._inputs):
            raise ValueError("one InputType per network input required")
        self._input_types = dict(zip(self._inputs, types))
        return self

    def add_layer(self, name: str, layer: BaseLayerConf, *inputs: str,
                  preprocessor: Optional[InputPreProcessor] = None) -> "GraphBuilder":
        if name in self._nodes:
            raise ValueError(f"Duplicate node name {name!r}")
        layer.name = name
        self._nodes[name] = NodeConf(name=name, kind="layer",
                                     inputs=list(inputs), layer=layer,
                                     preprocessor=preprocessor)
        return self

    def add_vertex(self, name: str, vertex: GraphVertex, *inputs: str) -> "GraphBuilder":
        if name in self._nodes:
            raise ValueError(f"Duplicate node name {name!r}")
        self._nodes[name] = NodeConf(name=name, kind="vertex",
                                     inputs=list(inputs), vertex=vertex)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def backprop_type(self, t: str, fwd: int = 20, bwd: int = 20) -> "GraphBuilder":
        self._parent._training.backprop_type = t
        self._parent._training.tbptt_fwd_length = fwd
        self._parent._training.tbptt_bwd_length = bwd
        return self

    def validate(self, mesh=None, batch_size: Optional[int] = None,
                 weight_update_sharding=None):
        """graphcheck without build(): assemble a THROWAWAY copy of the
        config WITHOUT the throwing shape-resolution pass, so cycles/
        dangling refs surface as findings rather than exceptions. The
        copy matters: applying global defaults to the live nodes would
        freeze the current defaults into the model, silently ignoring
        any global-setting calls made after validate()."""
        import copy
        g = self._parent._global
        nodes = copy.deepcopy(self._nodes)
        for node in nodes.values():
            if node.layer is not None:
                node.layer.apply_global_defaults(g)
        conf = ComputationGraphConfiguration(
            nodes=nodes,
            network_inputs=list(self._inputs),
            network_outputs=list(self._outputs),
            input_types=dict(self._input_types),
            training=self._parent._training,
        )
        return conf.validate(mesh=mesh, batch_size=batch_size,
                             weight_update_sharding=weight_update_sharding)

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs:
            raise ValueError("addInputs() required")
        if not self._outputs:
            raise ValueError("setOutputs() required")
        for out in self._outputs:
            if out not in self._nodes:
                raise ValueError(f"Unknown output {out!r}")
        g = self._parent._global
        for node in self._nodes.values():
            if node.layer is not None:
                node.layer.apply_global_defaults(g)
        from deeplearning4j_tpu.nn.conf.builder import validate_layer_options
        validate_layer_options([n.layer for n in self._nodes.values()
                                if n.layer is not None])
        conf = ComputationGraphConfiguration(
            nodes=self._nodes,
            network_inputs=self._inputs,
            network_outputs=self._outputs,
            input_types=self._input_types,
            training=self._parent._training,
        )
        conf._resolve_shapes()
        if (self._parent._training.backprop_type == "truncated_bptt"
                and conf.resolved_types):
            bad = [o for o in self._outputs
                   if conf.resolved_types[o].kind != "rnn"]
            if bad:
                # config-time failure, matching the reference (VERDICT r3
                # weak #7 — see ListBuilder.build)
                raise ValueError(
                    "truncated_bptt requires time-distributed (rnn) "
                    f"output(s); outputs {bad} resolve to non-rnn types")
        return conf
