"""Network configuration DSL.

Mirrors the reference's fluent builder chain
(ref: nn/conf/NeuralNetConfiguration.java:211-250 `ListBuilder`,
nn/conf/MultiLayerConfiguration.java:108-124) producing a JSON-serializable
configuration: global hyperparameters (inherited per layer), the layer list,
auto-inserted preprocessors, shape inference from an ``InputType``, and
training settings (updater, schedules, gradient clipping, tBPTT).

Example::

    conf = (NeuralNetConfiguration.builder()
        .seed(12345)
        .updater("adam", learning_rate=1e-3)
        .weight_init("xavier")
        .l2(1e-4)
        .list()
        .layer(DenseLayer(n_out=256, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
        .set_input_type(InputType.feed_forward(784))
        .build())

JSON round-trip: ``conf.to_json()`` / ``MultiLayerConfiguration.from_json``
(ref: NeuralNetConfiguration.java:283-360 to/fromJson). Polymorphic layer
subtypes resolve through LAYER_REGISTRY type tags instead of Jackson
classpath reflection.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.preprocessors import (
    InputPreProcessor, auto_preprocessor,
)
from deeplearning4j_tpu.nn.layers.base import BaseLayerConf, GlobalConf, layer_from_dict
from deeplearning4j_tpu.nn.weights import Distribution

# Layer-family classification for automatic preprocessor insertion
# (plays the role of InputType.getPreProcessorForInputType overrides).
_CNN_LAYERS = {"ConvolutionLayer", "SubsamplingLayer", "ZeroPaddingLayer",
               "LocalResponseNormalization"}
_RNN_LAYERS = {"LSTM", "GravesLSTM", "GravesBidirectionalLSTM", "SimpleRnn",
               "GRU", "RnnOutputLayer", "Convolution1DLayer",
               "Subsampling1DLayer", "SelfAttentionLayer",
               "LastTimeStepLayer", "TimeDistributedLayer",
               "ZeroPadding1DLayer", "PositionalEmbeddingLayer",
               "TiedRnnOutputLayer"}
_ANY_LAYERS = {"BatchNormalization", "GlobalPoolingLayer", "ActivationLayer",
               "DropoutLayer", "LossLayer", "ReshapeLayer", "PermuteLayer",
               # feature-axis normalization is rank-agnostic: a LayerNorm
               # between attention blocks must keep its rnn-typed input
               # (an auto Rnn->FF preprocessor here would strip the time
               # axis the transformer's residual stream carries)
               "LayerNormalization"}


def expected_input_kind(layer: BaseLayerConf) -> str:
    tag = type(layer).__name__
    if tag in _CNN_LAYERS:
        return "cnn"
    if tag in _RNN_LAYERS:
        return "rnn"
    if tag in _ANY_LAYERS:
        return "any"
    return "ff"


@dataclass
class UpdaterConfig:
    """Updater + hyperparams (ref: nn/conf/Updater.java enum — SGD, ADAM,
    ADADELTA, NESTEROVS, ADAGRAD, RMSPROP, NONE — with params held on the
    layer conf: momentum, rho, epsilon, adamMeanDecay/adamVarDecay)."""
    name: str = "sgd"
    learning_rate: float = 0.1
    momentum: float = 0.9           # nesterovs
    rho: float = 0.95               # adadelta / rmsprop decay
    epsilon: float = 1e-8
    beta1: float = 0.9              # adam
    beta2: float = 0.999
    # learning-rate policy (ref: nn/conf/LearningRatePolicy.java)
    lr_policy: str = "none"         # none|exponential|inverse|poly|sigmoid|step|schedule
    lr_policy_decay_rate: float = 0.0
    lr_policy_power: float = 1.0
    lr_policy_steps: float = 1.0
    lr_schedule: Optional[Dict[int, float]] = None  # iteration -> lr

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items() if v is not None}
        if self.lr_schedule is not None:
            d["lr_schedule"] = {str(k): v for k, v in self.lr_schedule.items()}
        return d

    @staticmethod
    def from_dict(d: dict) -> "UpdaterConfig":
        d = dict(d)
        if d.get("lr_schedule"):
            d["lr_schedule"] = {int(k): v for k, v in d["lr_schedule"].items()}
        return UpdaterConfig(**d)


@dataclass
class TrainingConfig:
    """Training-loop settings carried alongside the layer stack
    (ref: NeuralNetConfiguration fields + MultiLayerConfiguration
    backprop/pretrain/backpropType/tBPTT*)."""
    seed: int = 12345
    optimization_algo: str = "sgd"  # sgd | line_gradient_descent | conjugate_gradient | lbfgs
    # outer optimizer iterations per fit() call (ref: conf.iterations)
    iterations: int = 1
    # per-iteration Armijo backtracking cap (ref: maxNumLineSearchIterations)
    max_num_line_search_iterations: int = 5
    minimize: bool = True
    minibatch: bool = True
    updater: UpdaterConfig = field(default_factory=UpdaterConfig)
    # gradient normalization (ref: nn/conf/GradientNormalization.java)
    gradient_normalization: str = "none"
    gradient_normalization_threshold: float = 1.0
    # backprop style
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "standard"  # standard | truncated_bptt
    tbptt_fwd_length: int = 20
    tbptt_bwd_length: int = 20
    dtype: str = "float32"
    # mixed-precision policy (nn/updater.PrecisionPolicy presets):
    # "fp32" (default — every cast gated out, bitwise-parity territory)
    # or "bf16"/"fp16" (half-precision compute, fp32 master weights,
    # explicit cast seams in every compiled step). ``loss_scale``
    # statically scales the loss before differentiation and unscales
    # the fp32 gradients after (the fp16 seam; optional for bf16).
    precision: str = "fp32"
    loss_scale: Optional[float] = None
    # rematerialization: recompute per-layer activations in the backward
    # pass instead of storing them (jax.checkpoint). Trades FLOPs for HBM
    # — the standard TPU lever for batch sizes that don't otherwise fit.
    remat: bool = False

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["updater"] = self.updater.to_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "TrainingConfig":
        d = dict(d)
        d["updater"] = UpdaterConfig.from_dict(d["updater"])
        return TrainingConfig(**d)


@dataclass
class MultiLayerConfiguration:
    """The fully-resolved sequential-network config
    (ref: nn/conf/MultiLayerConfiguration.java)."""
    layers: List[BaseLayerConf]
    preprocessors: Dict[int, InputPreProcessor] = field(default_factory=dict)
    input_type: Optional[InputType] = None
    input_types: List[InputType] = field(default_factory=list)  # per-layer, resolved
    training: TrainingConfig = field(default_factory=TrainingConfig)

    # ------------------------------------------------------------------ serde
    def to_dict(self) -> dict:
        return {
            "format": "deeplearning4j_tpu/MultiLayerConfiguration",
            "version": 1,
            "training": self.training.to_dict(),
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "input_types": [t.to_dict() for t in self.input_types],
            "preprocessors": {str(i): p.to_dict() for i, p in self.preprocessors.items()},
            "layers": [l.to_dict() for l in self.layers],
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=[layer_from_dict(ld) for ld in d["layers"]],
            preprocessors={int(i): InputPreProcessor.from_dict(pd)
                           for i, pd in d.get("preprocessors", {}).items()},
            input_type=(InputType.from_dict(d["input_type"])
                        if d.get("input_type") else None),
            input_types=[InputType.from_dict(t) for t in d.get("input_types", [])],
            training=TrainingConfig.from_dict(d["training"]),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))

    # ------------------------------------------------------- static analysis
    def validate(self, mesh=None, batch_size: Optional[int] = None,
                 hbm_bytes: Optional[int] = None,
                 weight_update_sharding=None, precision=None):
        """Run graphcheck over this config: shape/dtype walk, loss-head
        and mesh-legality checks (incl. zero1/zero2
        weight-update-sharding legality and GC015 precision-policy
        legality — the config's own ``training.precision`` is validated
        when ``precision`` is not given), HBM estimate. Returns a list
        of ``analysis.Finding`` — empty when the config is clean. Pure
        metadata; no arrays are built."""
        from deeplearning4j_tpu.analysis.graphcheck import check_multilayer
        return check_multilayer(
            self, mesh=mesh, batch_size=batch_size, hbm_bytes=hbm_bytes,
            weight_update_sharding=weight_update_sharding,
            precision=precision)

    def memory_report(self, batch_size: int = 32):
        """Parameter-count + HBM/VMEM estimate (``MemoryReport``
        analogue) for this config at the given batch size."""
        from deeplearning4j_tpu.analysis.memory import memory_report
        return memory_report(self, batch_size=batch_size)

    def to_yaml(self) -> str:
        """YAML twin of ``to_json`` (the reference serializes configs to
        both JSON and YAML — ref: nn/conf/MultiLayerConfiguration.java
        toYaml/fromYaml alongside toJson). The dict is normalized through
        JSON first so the YAML document is the exact same data JSON
        carries (tuples → lists, keys → strings)."""
        import yaml
        return yaml.safe_dump(json.loads(self.to_json()), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))


def validate_layer_options(layers) -> None:
    """Fail at config-build time (not first forward) on unknown
    activation/loss names — misconfiguration should not wait for tracing."""
    from deeplearning4j_tpu.ops.activations import get_activation
    from deeplearning4j_tpu.ops.losses import get_loss
    for l in layers:
        act = getattr(l, "activation", None)
        if act:
            get_activation(act)
        gate = getattr(l, "gate_activation", None)
        if gate:
            get_activation(gate)
        loss = getattr(l, "loss", None)
        if loss:
            get_loss(loss)


class ListBuilder:
    """Sequential-stack builder (ref: NeuralNetConfiguration.ListBuilder)."""

    def __init__(self, parent: "NeuralNetConfiguration"):
        self._parent = parent
        self._layers: List[BaseLayerConf] = []
        self._preprocessors: Dict[int, InputPreProcessor] = {}
        self._input_type: Optional[InputType] = None

    def layer(self, layer: BaseLayerConf, index: Optional[int] = None) -> "ListBuilder":
        if index is not None and index != len(self._layers):
            raise ValueError("layers must be added in order")
        self._layers.append(layer)
        return self

    def input_pre_processor(self, layer_index: int,
                            p: InputPreProcessor) -> "ListBuilder":
        self._preprocessors[layer_index] = p
        return self

    def set_input_type(self, t: InputType) -> "ListBuilder":
        self._input_type = t
        return self

    # alias matching the reference naming
    setInputType = set_input_type

    def backprop_type(self, t: str, fwd: int = 20, bwd: int = 20) -> "ListBuilder":
        self._parent._training.backprop_type = t
        self._parent._training.tbptt_fwd_length = fwd
        self._parent._training.tbptt_bwd_length = bwd
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._parent._training.pretrain = flag
        return self

    def validate(self, mesh=None, batch_size: Optional[int] = None,
                 weight_update_sharding=None):
        """graphcheck without build(): collect findings even for stacks
        ``build()`` would throw on (its throw becomes a finding). Builds
        a deep COPY — build() materializes the current global defaults
        onto the layers, and validating must not freeze them early."""
        import copy
        from deeplearning4j_tpu.analysis.findings import Finding, Severity
        try:
            conf = copy.deepcopy(self).build()
        except (ValueError, TypeError) as e:
            return [Finding("GC005", Severity.ERROR, "<build>", str(e),
                            "fix the configuration; build() rejects it "
                            "outright")]
        return conf.validate(mesh=mesh, batch_size=batch_size,
                             weight_update_sharding=weight_update_sharding)

    def build(self) -> MultiLayerConfiguration:
        g = self._parent._global
        training = self._parent._training
        if not self._layers:
            raise ValueError("No layers added")
        # 1. inherit global hyperparams (ref: Builder.layer() semantics)
        for l in self._layers:
            l.apply_global_defaults(g)
        validate_layer_options(self._layers)
        # 2. shape inference + auto preprocessors (ref: setInputType flow)
        input_types: List[InputType] = []
        cur = self._input_type
        if cur is not None:
            for i, l in enumerate(self._layers):
                if i not in self._preprocessors:
                    p = auto_preprocessor(cur, expected_input_kind(l))
                    if p is not None:
                        self._preprocessors[i] = p
                if i in self._preprocessors:
                    cur = self._preprocessors[i].infer_output_type(cur)
                l.set_n_in(cur)  # inference overrides any manual n_in
                input_types.append(cur)
                cur = l.infer_output_type(cur)
        else:
            for l in self._layers:
                if l.has_params() and l.n_in is None:
                    raise ValueError(
                        f"Layer {l}: n_in not set and no input_type given")
        if (training.backprop_type == "truncated_bptt"
                and self._input_type is not None
                and cur.kind != "rnn"):  # cur = final layer's output type
            # config-time failure, matching the reference (a rank-2-label
            # head under tBPTT would silently train against full-sequence
            # targets per slice — VERDICT r3 weak #7)
            raise ValueError(
                "truncated_bptt requires a time-distributed output layer "
                "(e.g. RnnOutputLayer); the final layer "
                f"{type(self._layers[-1]).__name__} produces "
                "non-recurrent output")
        return MultiLayerConfiguration(
            layers=self._layers,
            preprocessors=self._preprocessors,
            input_type=self._input_type,
            input_types=input_types,
            training=training,
        )


class NeuralNetConfiguration:
    """Global-hyperparameter builder (ref: NeuralNetConfiguration.Builder)."""

    def __init__(self):
        self._global = GlobalConf()
        self._training = TrainingConfig()

    @staticmethod
    def builder() -> "NeuralNetConfiguration":
        return NeuralNetConfiguration()

    # ---- fluent global hyperparameters ----
    def seed(self, s: int) -> "NeuralNetConfiguration":
        self._training.seed = int(s)
        return self

    def activation(self, a: str) -> "NeuralNetConfiguration":
        self._global.activation = a
        return self

    def weight_init(self, w: str) -> "NeuralNetConfiguration":
        self._global.weight_init = w
        return self

    def dist(self, d: Distribution) -> "NeuralNetConfiguration":
        self._global.dist = d
        return self

    def bias_init(self, b: float) -> "NeuralNetConfiguration":
        self._global.bias_init = b
        return self

    def l1(self, v: float) -> "NeuralNetConfiguration":
        self._global.l1 = v
        return self

    def l2(self, v: float) -> "NeuralNetConfiguration":
        self._global.l2 = v
        return self

    def dropout(self, retain_prob: float) -> "NeuralNetConfiguration":
        self._global.dropout = retain_prob
        return self

    # ---- training config ----
    #: the Updater enum (ref: nn/conf/Updater.java:9-11 — SGD, ADAM,
    #: ADADELTA, NESTEROVS, ADAGRAD, RMSPROP, NONE + ADAMAX)
    KNOWN_UPDATERS = ("sgd", "adam", "adamax", "adadelta", "nesterovs",
                      "adagrad", "rmsprop", "none")

    def updater(self, name: str, **kwargs) -> "NeuralNetConfiguration":
        # mutate in place so the fluent chain is order-insensitive
        # (.learning_rate(x).updater('adam') keeps x, like the reference)
        if name.lower() not in self.KNOWN_UPDATERS:
            raise ValueError(
                f"Unknown updater {name!r}; expected one of "
                f"{self.KNOWN_UPDATERS}")
        u = self._training.updater
        u.name = name.lower()
        for k, v in kwargs.items():
            if not hasattr(u, k):
                raise ValueError(f"Unknown updater option {k!r}")
            setattr(u, k, v)
        return self

    def learning_rate(self, lr: float) -> "NeuralNetConfiguration":
        self._training.updater.learning_rate = lr
        return self

    def optimization_algo(self, algo: str) -> "NeuralNetConfiguration":
        self._training.optimization_algo = algo.lower()
        return self

    def iterations(self, n: int) -> "NeuralNetConfiguration":
        self._training.iterations = n
        return self

    def max_num_line_search_iterations(self, n: int) -> "NeuralNetConfiguration":
        self._training.max_num_line_search_iterations = n
        return self

    def minimize(self, flag: bool = True) -> "NeuralNetConfiguration":
        self._training.minimize = flag
        return self

    def gradient_normalization(self, kind: str,
                               threshold: float = 1.0) -> "NeuralNetConfiguration":
        self._training.gradient_normalization = kind.lower()
        self._training.gradient_normalization_threshold = threshold
        return self

    def lr_policy(self, policy: str, decay_rate: float = 0.0, power: float = 1.0,
                  steps: float = 1.0,
                  schedule: Optional[Dict[int, float]] = None) -> "NeuralNetConfiguration":
        u = self._training.updater
        u.lr_policy = policy.lower()
        u.lr_policy_decay_rate = decay_rate
        u.lr_policy_power = power
        u.lr_policy_steps = steps
        u.lr_schedule = schedule
        return self

    def dtype(self, dt: str) -> "NeuralNetConfiguration":
        self._training.dtype = dt
        return self

    def precision(self, policy: str,
                  loss_scale: Optional[float] = None
                  ) -> "NeuralNetConfiguration":
        """Mixed-precision policy for every compiled train step:
        ``"bf16"`` runs forward/backward in bfloat16 against fp32
        master weights (cast seams at the step boundary; loss,
        gradients, optax, and the divergence sentinel stay fp32).
        ``"fp32"`` (default) gates every cast out. ``loss_scale``
        statically scales the loss before differentiation (the fp16
        seam; optional for bf16)."""
        self._training.precision = str(policy).lower()
        self._training.loss_scale = loss_scale
        return self

    def gradient_checkpointing(self, flag: bool = True) -> "NeuralNetConfiguration":
        """Rematerialize per-layer activations in backward (jax.checkpoint)
        — trade recompute FLOPs for HBM so larger batches fit."""
        self._training.remat = flag
        return self

    # ---- transition to layer stacking ----
    def list(self) -> ListBuilder:
        return ListBuilder(self)

    def graph_builder(self):
        """DAG-network builder (ref: ComputationGraphConfiguration.
        GraphBuilder)."""
        try:
            from deeplearning4j_tpu.nn.conf.graph_builder import GraphBuilder
        except ImportError as e:
            raise NotImplementedError(
                "ComputationGraph builder not available yet") from e
        return GraphBuilder(self)
