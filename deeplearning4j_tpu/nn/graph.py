"""ComputationGraph: the DAG model container.

Ref: nn/graph/ComputationGraph.java:79 — init (:273-483), fit (:701-771),
topologicalSortOrder (:888), computeGradientAndScore (:995-1036),
calcBackpropGradients (:1224). As with MultiLayerNetwork, the reference's
hand-written reverse-topological epsilon propagation collapses into
``jax.grad`` over one pure forward walk; the whole train step is a single
jitted XLA program.

Params are a dict keyed by node name -> {param name -> array}. Multi-input /
multi-output training uses MultiDataSet; plain DataSet maps to the first
input/output (ref: ComputationGraph.fit(DataSet) does the same).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator, DataSetIterator, ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf.graph import LastTimeStepVertex
from deeplearning4j_tpu.nn.conf.graph_builder import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.netcommon import LazyScoreMixin, jit_init
from deeplearning4j_tpu.nn.updater import build_optimizer, compute_updates
from deeplearning4j_tpu.optimize.listeners import IterationListener, TrainingListener

Array = jax.Array


def _dtype_of(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "float16": jnp.float16, "float64": jnp.float64}[name]


class ComputationGraph(LazyScoreMixin):
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: Optional[Dict[str, Dict[str, Array]]] = None
        self.states: Optional[Dict[str, Dict[str, Array]]] = None
        self.opt_state = None
        self.iteration_count = 0
        self.epoch_count = 0
        self.score_value = float("nan")
        self.listeners: List[IterationListener] = []
        self.last_batch_size = 0
        self.last_grads = None  # most recent gradient pytree (for listeners)
        self._tx = build_optimizer(conf.training)
        self._train_step_fn = None
        self._jit_infer = None          # cached jitted inference forward
        self._infer_traces = 0          # trace counter (tests)
        self._rng = jax.random.PRNGKey(conf.training.seed)
        # layer nodes in topological order (the trainable walk)
        self._layer_nodes = [n for n in conf.topological_order
                             if conf.nodes[n].kind == "layer"]
        self._output_layers = [conf.nodes[o] for o in conf.network_outputs]

    # ------------------------------------------------------------------ init
    def init(self, params=None) -> "ComputationGraph":
        dtype = _dtype_of(self.conf.training.dtype)
        if params is not None:
            self.params = params
            self.opt_state = jax.jit(self._tx.init)(self.params)
        else:
            # One jitted program for the whole init: eager per-tensor
            # jax.random calls would compile + dispatch hundreds of tiny
            # device programs (one per shape), which is pathological over
            # a remote-TPU link (round-trip each). Jitted, it is a single
            # compile and a single device execution.
            def _build(key):
                keys = jax.random.split(key, max(len(self._layer_nodes), 1))
                p = {}
                for name, k in zip(self._layer_nodes, keys):
                    layer = self.conf.nodes[name].layer
                    p[name] = (layer.init_params(k, dtype)
                               if layer.has_params() else {})
                return p, self._tx.init(p)
            self.params, self.opt_state = jit_init(
                _build, self.conf.training.seed)
        self.states = {name: self.conf.nodes[name].layer.init_state()
                       for name in self._layer_nodes}
        return self

    def _check_init(self):
        if self.params is None:
            raise RuntimeError("Call init() before using the network")


    def set_listeners(self, *listeners: IterationListener):
        self.listeners = list(listeners)
        # see MultiLayerNetwork._on_listeners_changed
        want = any(getattr(l, "collects_gradients", False)
                   for l in self.listeners)
        if want != getattr(self, "_collect_grads", False):
            self._collect_grads = want
            self._train_step_fn = None

    # ---------------------------------------------------------------- forward
    def _forward(self, params, states, inputs: Dict[str, Array], *,
                 train: bool, rng, masks: Optional[Dict[str, Array]] = None,
                 stop_before_loss: bool = True):
        """Walk the DAG in topological order.

        Returns (activations dict, masks dict, new_states). For output-layer
        nodes with a loss head, the stored activation is the node's INPUT
        (pre-head) when stop_before_loss — compute_loss consumes it —
        mirroring feedForward(excludeOutput=true) (ref: CG.java:1006).
        """
        acts: Dict[str, Array] = {}
        out_masks: Dict[str, Optional[Array]] = {}
        new_states: Dict[str, Dict[str, Array]] = {}
        output_set = set(self.conf.network_outputs)
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "input":
                acts[name] = inputs[name]
                out_masks[name] = (masks or {}).get(name)
                continue
            in_acts = [acts[i] for i in node.inputs]
            in_mask = out_masks.get(node.inputs[0]) if node.inputs else None
            if node.kind == "vertex":
                if isinstance(node.vertex, LastTimeStepVertex):
                    acts[name] = node.vertex.apply_masked(in_acts, in_mask)
                    out_masks[name] = None
                else:
                    acts[name] = node.vertex.apply(in_acts)
                    out_masks[name] = in_mask
                continue
            # layer node
            h = in_acts[0]
            cur_mask = in_mask
            if node.preprocessor is not None:
                h = node.preprocessor.transform(h, None)
                cur_mask = node.preprocessor.transform_mask(cur_mask, None)
            layer = node.layer
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            if (stop_before_loss and name in output_set
                    and hasattr(layer, "compute_loss")):
                acts[name] = h          # input to the loss head
                out_masks[name] = cur_mask
                new_states[name] = states[name]
                continue
            layer_train = train and not layer.frozen
            h, s = layer.apply(params[name], h, state=states[name],
                               train=layer_train, rng=sub, mask=cur_mask)
            if layer.frozen:
                s = states[name]
            acts[name] = h
            # layers that reduce away the time axis consume the mask
            from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayer
            out_masks[name] = None if isinstance(layer, GlobalPoolingLayer) else cur_mask
            new_states[name] = s
        return acts, out_masks, new_states

    def _infer_fn(self):
        """Cached JITTED inference forward (ref: the reference's output()
        reuses the same compiled-graph machinery as fit — CG.java:1006 /
        MultiLayerNetwork.java:1512); jax.jit re-traces per input shape and
        ``_infer_traces`` counts traces for tests."""
        if self._jit_infer is None:
            def infer(params, states, in_map):
                self._infer_traces += 1  # python side effect: runs per TRACE
                acts, _, _ = self._forward(params, states, in_map,
                                           train=False, rng=None,
                                           stop_before_loss=False)
                return [acts[o] for o in self.conf.network_outputs]
            self._jit_infer = jax.jit(infer)
        return self._jit_infer

    def outputs(self, inputs: Union[Array, Sequence[Array], Dict[str, Array]],
                train: bool = False) -> List[Array]:
        """Final activations of all output nodes
        (ref: ComputationGraph.output(...))."""
        self._check_init()
        in_map = self._to_input_map(inputs)
        if not train:
            return self._infer_fn()(self.params, self.states, in_map)
        acts, _, _ = self._forward(self.params, self.states, in_map,
                                   train=train, rng=None, stop_before_loss=False)
        return [acts[o] for o in self.conf.network_outputs]

    def output(self, inputs, train: bool = False) -> Array:
        return self.outputs(inputs, train=train)[0]

    def _to_input_map(self, inputs) -> Dict[str, Array]:
        names = self.conf.network_inputs
        if isinstance(inputs, dict):
            return {k: jnp.asarray(v) for k, v in inputs.items()}
        if isinstance(inputs, (list, tuple)):
            return {n: jnp.asarray(x) for n, x in zip(names, inputs)}
        return {names[0]: jnp.asarray(inputs)}

    # ------------------------------------------------------------------- loss
    def _loss_fn(self, params, states, inputs, labels: Dict[str, Array],
                 masks, label_masks, rng, train=True):
        acts, out_masks, new_states = self._forward(
            params, states, inputs, train=train, rng=rng, masks=masks)
        total = jnp.zeros(())
        for out_name in self.conf.network_outputs:
            layer = self.conf.nodes[out_name].layer
            if not hasattr(layer, "compute_loss"):
                raise ValueError(f"Output node {out_name!r} has no loss head")
            lm = (label_masks or {}).get(out_name)
            if lm is None:
                lbl = labels[out_name]
                lm = out_masks.get(out_name) if lbl.ndim > 2 else None
            total = total + layer.compute_loss(params[out_name], acts[out_name],
                                               labels[out_name], mask=lm)
        # L1/L2 over all layer params (score = Σ output losses + reg;
        # ref: CG.computeGradientAndScore:1016-1028)
        from deeplearning4j_tpu.nn.updater import l1_l2_penalty
        layer_list = [self.conf.nodes[n].layer for n in self._layer_nodes]
        param_list = [params[n] for n in self._layer_nodes]
        total = total + l1_l2_penalty(param_list, layer_list)
        from deeplearning4j_tpu.nn.multilayer import _sum_aux_losses
        total = total + _sum_aux_losses(new_states)
        return total, new_states

    def score(self, data: Union[DataSet, MultiDataSet], train: bool = False) -> float:
        self._check_init()
        inputs, labels, masks, lmasks = self._split(data)
        loss, _ = self._loss_fn(self.params, self.states, inputs, labels,
                                masks, lmasks, rng=None, train=train)
        return float(loss)

    def _split(self, data: Union[DataSet, MultiDataSet]):
        names_in = self.conf.network_inputs
        names_out = self.conf.network_outputs
        if isinstance(data, DataSet):
            inputs = {names_in[0]: jnp.asarray(data.features)}
            labels = {names_out[0]: jnp.asarray(data.labels)}
            masks = ({names_in[0]: jnp.asarray(data.features_mask)}
                     if data.features_mask is not None else None)
            lmasks = ({names_out[0]: jnp.asarray(data.labels_mask)}
                      if data.labels_mask is not None else None)
            return inputs, labels, masks, lmasks
        inputs = {n: jnp.asarray(x) for n, x in zip(names_in, data.features)}
        labels = {n: jnp.asarray(x) for n, x in zip(names_out, data.labels)}
        masks = None
        if data.features_masks is not None:
            masks = {n: (None if m is None else jnp.asarray(m))
                     for n, m in zip(names_in, data.features_masks)}
        lmasks = None
        if data.labels_masks is not None:
            lmasks = {n: (None if m is None else jnp.asarray(m))
                      for n, m in zip(names_out, data.labels_masks)}
        return inputs, labels, masks, lmasks

    # ------------------------------------------------------------- train step
    def _build_train_step(self):
        tx = self._tx
        training = self.conf.training
        collect_grads = getattr(self, "_collect_grads", False)

        def train_step(params, opt_state, states, inputs, labels, masks,
                       lmasks, rng):
            def loss_for_grad(p):
                return self._loss_fn(p, states, inputs, labels, masks,
                                     lmasks, rng)

            (loss, new_states), grads = jax.value_and_grad(
                loss_for_grad, has_aux=True)(params)
            layer_list = [self.conf.nodes[n].layer for n in self._layer_nodes]
            new_params, new_opt = compute_updates(
                tx, grads, opt_state, params, layer_list, training)
            return (new_params, new_opt, new_states, loss,
                    grads if collect_grads else None)

        # donate params/opt/states: ResNet-scale nets must not copy their
        # whole state every step (HBM traffic + footprint)
        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def fit_batch(self, data: Union[DataSet, MultiDataSet]) -> float:
        """One optimization step (ref: ComputationGraph.fit).

        NOTE: previous ``params``/``opt_state``/``states`` buffers are
        DONATED to the jitted step — external aliases held across a step
        raise "Array has been deleted"; ``np.asarray``-copy first."""
        self._check_init()
        algo = self.conf.training.optimization_algo
        if algo not in ("sgd", "stochastic_gradient_descent"):
            # line-search family (ref: BaseOptimizer.java:295-300 — the
            # same Solver serves ComputationGraph)
            from deeplearning4j_tpu.optimize.solvers import solver_fit_batch
            return solver_fit_batch(self, data)
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        inputs, labels, masks, lmasks = self._split(data)
        self._rng, step_rng = jax.random.split(self._rng)
        self.params, self.opt_state, self.states, loss, self.last_grads = \
            self._train_step_fn(
                self.params, self.opt_state, self.states, inputs, labels,
                masks, lmasks, step_rng)
        self.last_batch_size = data.num_examples()
        # raw device scalar — see MultiLayerNetwork.fit_batch: converting
        # eagerly would sync the pipeline every step
        self.score_value = loss
        self.iteration_count += 1
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration_count, self.score_value)
        return self._score_raw

    def fit(self, data, epochs: int = 1, use_async: bool = True) -> "ComputationGraph":
        """(ref: ComputationGraph.fit(DataSetIterator):701-771)"""
        self._check_init()
        if isinstance(data, (DataSet, MultiDataSet)):
            batches = [data]
            data = ListDataSetIterator(batches) if isinstance(data, DataSet) else None
            if data is None:
                for _ in range(epochs):
                    self.fit_batch(batches[0])
                return self
        assert isinstance(data, DataSetIterator)
        it = (AsyncDataSetIterator(data)
              if use_async and data.async_supported() else data)
        for _ in range(epochs):
            for listener in self.listeners:
                if isinstance(listener, TrainingListener):
                    listener.on_epoch_start(self)
            for batch in it:
                self.fit_batch(batch)
            self.epoch_count += 1
            for listener in self.listeners:
                if isinstance(listener, TrainingListener):
                    listener.on_epoch_end(self)
        return self

    # ----------------------------------------------------------- param access
    def num_params(self) -> int:
        self._check_init()
        return sum(int(np.prod(a.shape))
                   for p in self.params.values() for a in p.values())

    def params_flat(self) -> np.ndarray:
        """Flat param vector in topological-order/param-order
        (coefficients.bin contract for graphs)."""
        self._check_init()
        chunks = []
        for name in self._layer_nodes:
            layer = self.conf.nodes[name].layer
            for pname in layer.param_order():
                chunks.append(np.asarray(self.params[name][pname]).ravel())
        return np.concatenate(chunks) if chunks else np.zeros(0, np.float32)

    def set_params_flat(self, flat: np.ndarray) -> None:
        self._check_init()
        pos = 0
        for name in self._layer_nodes:
            layer = self.conf.nodes[name].layer
            for pname in layer.param_order():
                ref = self.params[name][pname]
                n = int(np.prod(ref.shape))
                self.params[name][pname] = jnp.asarray(
                    flat[pos:pos + n].reshape(ref.shape), ref.dtype)
                pos += n
        if pos != len(flat):
            raise ValueError(f"Expected {pos} params, got {len(flat)}")

    def predict(self, inputs) -> np.ndarray:
        return np.asarray(jnp.argmax(self.output(inputs), axis=-1))

    def evaluate(self, iterator: DataSetIterator):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        e = Evaluation()
        iterator.reset()
        for batch in iterator:
            out = self.output(batch.features)
            e.eval(batch.labels, np.asarray(out), mask=batch.labels_mask)
        return e
