"""ComputationGraph: the DAG model container.

Ref: nn/graph/ComputationGraph.java:79 — init (:273-483), fit (:701-771),
topologicalSortOrder (:888), computeGradientAndScore (:995-1036),
calcBackpropGradients (:1224). As with MultiLayerNetwork, the reference's
hand-written reverse-topological epsilon propagation collapses into
``jax.grad`` over one pure forward walk; the whole train step is a single
jitted XLA program.

Params are a dict keyed by node name -> {param name -> array}. Multi-input /
multi-output training uses MultiDataSet; plain DataSet maps to the first
input/output (ref: ComputationGraph.fit(DataSet) does the same).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterator import (
    AsyncDataSetIterator, DataSetIterator, ListDataSetIterator,
)
from deeplearning4j_tpu.nn.conf.graph import (
    DuplicateToTimeSeriesVertex, LastTimeStepVertex)
from deeplearning4j_tpu.nn.conf.graph_builder import ComputationGraphConfiguration
from deeplearning4j_tpu.nn.netcommon import (CostAnalysisMixin, EvalMixin,
                                              LazyScoreMixin, jit_init,
                                              ScanFitMixin, SentinelMixin,
                                              ShardCheckMixin,
)
from deeplearning4j_tpu.nn.updater import build_optimizer, compute_updates
from deeplearning4j_tpu.optimize.listeners import IterationListener, TrainingListener

Array = jax.Array


def _dtype_of(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16,
            "float16": jnp.float16, "float64": jnp.float64}[name]


def _time_slice(d: Optional[Dict[str, Array]], lo: int, hi: int,
                min_ndim: int = 3,
                only: Optional[set] = None) -> Optional[Dict[str, Array]]:
    """Slice the time axis (dim 1) of every time-distributed array in a
    name->array dict. ``min_ndim=3`` for features/labels ([B, T, ...];
    static [B, F] side inputs pass through unsliced), ``min_ndim=2`` for
    masks ([B, T]). ``only`` restricts slicing to the named keys (the
    recurrent inputs — a CNN input's [B, H, W, C] must NOT be sliced on
    its height axis)."""
    if d is None:
        return None
    return {k: (v if v is None or v.ndim < min_ndim
                or (only is not None and k not in only) else v[:, lo:hi])
            for k, v in d.items()}


class ComputationGraph(LazyScoreMixin, EvalMixin, ScanFitMixin,
                       CostAnalysisMixin, ShardCheckMixin, SentinelMixin):
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.params: Optional[Dict[str, Dict[str, Array]]] = None
        self.states: Optional[Dict[str, Dict[str, Array]]] = None
        self.opt_state = None
        self.iteration_count = 0
        self.epoch_count = 0
        self.score_value = float("nan")
        self.listeners: List[IterationListener] = []
        self.last_batch_size = 0
        self.last_grads = None  # most recent gradient pytree (for listeners)
        self._tx = build_optimizer(conf.training)
        self._train_step_fn = None
        self._jit_infer = None          # cached jitted inference forward
        self._infer_traces = 0          # trace counter (tests)
        self._rng = jax.random.PRNGKey(conf.training.seed)
        self._rnn_carries: Optional[Dict[str, Any]] = None  # rnnTimeStep
        self._tbptt_step_fn = None
        self._decode_fns = None         # (prefill, decode) pure fns
        self._paged_decode_fns: Dict[int, Any] = {}  # page_len -> step fn
        # layer nodes in topological order (the trainable walk)
        self._layer_nodes = [n for n in conf.topological_order
                             if conf.nodes[n].kind == "layer"]
        self._output_layers = [conf.nodes[o] for o in conf.network_outputs]
        # weight tying (TiedRnnOutputLayer.tied_to): resolve once, fail
        # loudly at construction — a dangling tie would otherwise only
        # surface as a missing-param KeyError deep inside a traced step
        for name in self._layer_nodes:
            tied = getattr(conf.nodes[name].layer, "tied_to", None)
            if not tied:
                continue
            src = conf.nodes.get(tied)
            if src is None or src.kind != "layer":
                raise ValueError(
                    f"node {name!r}: tied_to={tied!r} does not name a "
                    "layer node in this graph")
            if "W" not in (src.layer.param_order() or []):
                raise ValueError(
                    f"node {name!r}: tied_to node {tied!r} "
                    f"({type(src.layer).__name__}) has no 'W' param to "
                    "tie to")

    # ------------------------------------------------------------------ init
    def init(self, params=None) -> "ComputationGraph":
        dtype = _dtype_of(self.conf.training.dtype)
        if params is not None:
            self.params = params
            self.opt_state = jax.jit(self._tx.init)(self.params)
        else:
            # One jitted program for the whole init: eager per-tensor
            # jax.random calls would compile + dispatch hundreds of tiny
            # device programs (one per shape), which is pathological over
            # a remote-TPU link (round-trip each). Jitted, it is a single
            # compile and a single device execution.
            def _build(key):
                keys = jax.random.split(key, max(len(self._layer_nodes), 1))
                p = {}
                for name, k in zip(self._layer_nodes, keys):
                    layer = self.conf.nodes[name].layer
                    p[name] = (layer.init_params(k, dtype)
                               if layer.has_params() else {})
                return p, self._tx.init(p)
            self.params, self.opt_state = jit_init(
                _build, self.conf.training.seed)
        self.states = {name: self.conf.nodes[name].layer.init_state()
                       for name in self._layer_nodes}
        return self

    def _check_init(self):
        if self.params is None:
            raise RuntimeError("Call init() before using the network")

    def _layer_params(self, params, name: str):
        """Effective params of one layer node: its own dict, plus — for a
        tied head (``layer.tied_to``) — the tied node's token-embedding
        matrix injected as ``W_tok``. Indexing ``params`` (not a cached
        array) keeps autodiff honest: the head's gradient flows into the
        embedding's ``W``, which is the whole point of weight tying."""
        node = self.conf.nodes[name]
        tied = getattr(node.layer, "tied_to", None)
        if tied:
            return {**params[name], "W_tok": params[tied]["W"]}
        return params[name]


    def set_listeners(self, *listeners: IterationListener):
        self.listeners = list(listeners)
        # see MultiLayerNetwork._on_listeners_changed
        want = any(getattr(l, "collects_gradients", False)
                   for l in self.listeners)
        if want != getattr(self, "_collect_grads", False):
            self._collect_grads = want
            self._train_step_fn = None

    # ---------------------------------------------------------------- forward
    def _forward(self, params, states, inputs: Dict[str, Array], *,
                 train: bool, rng, masks: Optional[Dict[str, Array]] = None,
                 stop_before_loss: bool = True,
                 carries: Optional[Dict[str, Any]] = None,
                 subset: Optional[set] = None):
        """Walk the DAG in topological order.

        Returns (activations dict, masks dict, new_states). For output-layer
        nodes with a loss head, the stored activation is the node's INPUT
        (pre-head) when stop_before_loss — compute_loss consumes it —
        mirroring feedForward(excludeOutput=true) (ref: CG.java:1006).

        ``carries``: optional per-layer-node RNN carry dict (tBPTT /
        rnnTimeStep — ref: CG.java rnnTimeStep:1868 keeps per-vertex state
        maps). When given, recurrent layers run ``scan`` from their carry
        and the return is a 4-tuple (acts, masks, states, new_carries).
        """
        acts: Dict[str, Array] = {}
        out_masks: Dict[str, Optional[Array]] = {}
        new_states: Dict[str, Dict[str, Array]] = {}
        new_carries: Dict[str, Any] = {}
        output_set = set(self.conf.network_outputs)
        for name in self.conf.topological_order:
            if subset is not None and name not in subset:
                continue
            node = self.conf.nodes[name]
            if node.kind == "input":
                acts[name] = inputs[name]
                out_masks[name] = (masks or {}).get(name)
                continue
            in_acts = [acts[i] for i in node.inputs]
            in_mask = out_masks.get(node.inputs[0]) if node.inputs else None
            if node.kind == "vertex":
                if isinstance(node.vertex, LastTimeStepVertex):
                    acts[name] = node.vertex.apply_masked(in_acts, in_mask)
                    out_masks[name] = None
                elif isinstance(node.vertex, DuplicateToTimeSeriesVertex) \
                        and isinstance(node.vertex.timesteps, str):
                    # runtime T from the named reference node's activation
                    acts[name] = node.vertex.apply(
                        in_acts, acts[node.vertex.timesteps])
                    out_masks[name] = in_mask
                else:
                    acts[name] = node.vertex.apply(in_acts)
                    out_masks[name] = in_mask
                continue
            # layer node
            h = in_acts[0]
            cur_mask = in_mask
            if node.preprocessor is not None:
                h = node.preprocessor.transform(h, None)
                cur_mask = node.preprocessor.transform_mask(cur_mask, None)
            layer = node.layer
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            if (stop_before_loss and name in output_set
                    and hasattr(layer, "compute_loss")):
                acts[name] = h          # input to the loss head
                out_masks[name] = cur_mask
                new_states[name] = states[name]
                continue
            # remat (conf.gradient_checkpointing): recompute in backward
            remat = train and self.conf.training.remat
            if carries is not None and getattr(layer, "supports_carry", False):
                c_in = carries.get(name)
                if c_in is None:
                    c_in = layer.initial_carry(h.shape[0], h.dtype)
                # scan() bypasses apply(): input dropout must still fire
                # so tBPTT training regularizes like standard BPTT
                h = layer._dropout_input(h, train and not layer.frozen, sub)
                scan_fn = (jax.checkpoint(layer.scan) if remat
                           else layer.scan)
                h, c_out = scan_fn(self._layer_params(params, name), h,
                                   c_in, cur_mask)
                new_carries[name] = c_out
                s = states[name]
            else:
                layer_train = train and not layer.frozen

                def apply_fn(p, hh, s_in, r, m, _l=layer, _t=layer_train):
                    return _l.apply(p, hh, state=s_in, train=_t, rng=r,
                                    mask=m)
                if remat:
                    apply_fn = jax.checkpoint(apply_fn)
                h, s = apply_fn(self._layer_params(params, name), h,
                                states[name], sub, cur_mask)
                if layer.frozen:
                    s = states[name]
            acts[name] = h
            # layers that consume or rearrange the time axis drop the mask
            out_masks[name] = layer.propagate_mask(cur_mask)
            new_states[name] = s
        if carries is not None:
            return acts, out_masks, new_states, new_carries
        return acts, out_masks, new_states

    def _infer_fn(self):
        """Cached JITTED inference forward (ref: the reference's output()
        reuses the same compiled-graph machinery as fit — CG.java:1006 /
        MultiLayerNetwork.java:1512); jax.jit re-traces per input shape and
        ``_infer_traces`` counts traces for tests."""
        if self._jit_infer is None:
            def infer(params, states, in_map, masks):
                self._infer_traces += 1  # python side effect: runs per TRACE
                acts, _, _ = self._forward(params, states, in_map,
                                           train=False, rng=None,
                                           masks=masks,
                                           stop_before_loss=False)
                return [acts[o] for o in self.conf.network_outputs]
            self._jit_infer = jax.jit(infer)
        return self._jit_infer

    def outputs(self, inputs: Union[Array, Sequence[Array], Dict[str, Array]],
                train: bool = False, mask=None) -> List[Array]:
        """Final activations of all output nodes
        (ref: ComputationGraph.output(...)). ``mask``: a [B, T] feature
        mask for the first input, or a name->mask dict."""
        self._check_init()
        in_map = self._to_input_map(inputs)
        masks = None
        if mask is not None:
            masks = (
                {k: (None if v is None else jnp.asarray(v))
                 for k, v in mask.items()} if isinstance(mask, dict)
                else {self.conf.network_inputs[0]: jnp.asarray(mask)})
        if not train:
            return self._infer_fn()(self.params, self.states, in_map,
                                    masks)
        acts, _, _ = self._forward(self.params, self.states, in_map,
                                   train=train, rng=None, masks=masks,
                                   stop_before_loss=False)
        return [acts[o] for o in self.conf.network_outputs]

    def output(self, inputs, train: bool = False, mask=None) -> Array:
        return self.outputs(inputs, train=train, mask=mask)[0]

    def _to_input_map(self, inputs) -> Dict[str, Array]:
        names = self.conf.network_inputs
        if isinstance(inputs, dict):
            return {k: jnp.asarray(v) for k, v in inputs.items()}
        if isinstance(inputs, (list, tuple)):
            return {n: jnp.asarray(x) for n, x in zip(names, inputs)}
        return {names[0]: jnp.asarray(inputs)}

    # ------------------------------------------------------------------- loss
    def _data_loss(self, params, acts, out_masks, labels: Dict[str, Array],
                   label_masks) -> Array:
        """Sum of output-head losses (shared by the standard and tBPTT
        steps so the mask-fallback semantics cannot diverge)."""
        total = jnp.zeros(())
        for out_name in self.conf.network_outputs:
            layer = self.conf.nodes[out_name].layer
            if not hasattr(layer, "compute_loss"):
                raise ValueError(f"Output node {out_name!r} has no loss head")
            lm = (label_masks or {}).get(out_name)
            if lm is None:
                lbl = labels[out_name]
                lm = out_masks.get(out_name) if lbl.ndim > 2 else None
            total = total + layer.compute_loss(
                self._layer_params(params, out_name), acts[out_name],
                labels[out_name], mask=lm)
        return total

    def _loss_fn(self, params, states, inputs, labels: Dict[str, Array],
                 masks, label_masks, rng, train=True):
        acts, out_masks, new_states = self._forward(
            params, states, inputs, train=train, rng=rng, masks=masks)
        total = self._data_loss(params, acts, out_masks, labels, label_masks)
        # L1/L2 over all layer params (score = Σ output losses + reg;
        # ref: CG.computeGradientAndScore:1016-1028)
        from deeplearning4j_tpu.nn.updater import l1_l2_penalty
        layer_list = [self.conf.nodes[n].layer for n in self._layer_nodes]
        param_list = [params[n] for n in self._layer_nodes]
        total = total + l1_l2_penalty(param_list, layer_list)
        from deeplearning4j_tpu.nn.multilayer import _sum_aux_losses
        total = total + _sum_aux_losses(new_states)
        return total, new_states

    def score(self, data: Union[DataSet, MultiDataSet], train: bool = False) -> float:
        self._check_init()
        inputs, labels, masks, lmasks = self._split(data)
        loss, _ = self._loss_fn(self.params, self.states, inputs, labels,
                                masks, lmasks, rng=None, train=train)
        return float(loss)

    def _split(self, data: Union[DataSet, MultiDataSet]):
        names_in = self.conf.network_inputs
        names_out = self.conf.network_outputs
        if isinstance(data, DataSet):
            inputs = {names_in[0]: jnp.asarray(data.features)}
            labels = {names_out[0]: jnp.asarray(data.labels)}
            masks = ({names_in[0]: jnp.asarray(data.features_mask)}
                     if data.features_mask is not None else None)
            lmasks = ({names_out[0]: jnp.asarray(data.labels_mask)}
                      if data.labels_mask is not None else None)
            return inputs, labels, masks, lmasks
        inputs = {n: jnp.asarray(x) for n, x in zip(names_in, data.features)}
        labels = {n: jnp.asarray(x) for n, x in zip(names_out, data.labels)}
        masks = None
        if data.features_masks is not None:
            masks = {n: (None if m is None else jnp.asarray(m))
                     for n, m in zip(names_in, data.features_masks)}
        lmasks = None
        if data.labels_masks is not None:
            lmasks = {n: (None if m is None else jnp.asarray(m))
                      for n, m in zip(names_out, data.labels_masks)}
        return inputs, labels, masks, lmasks

    # ------------------------------------------------------------- train step
    def _build_train_step(self):
        tx = self._tx
        training = self.conf.training
        collect_grads = getattr(self, "_collect_grads", False)
        sentinel = self._sentinel
        if sentinel is not None:
            from deeplearning4j_tpu.resilience.sentinel import guard_update
        from deeplearning4j_tpu.nn.updater import (
            PrecisionPolicy, cast_floats, precision_value_and_grad,
        )
        policy = PrecisionPolicy.parse(
            getattr(training, "precision", None),
            loss_scale=getattr(training, "loss_scale", None))
        mixed = policy.mixed

        def train_step(params, opt_state, states, inputs, labels, masks,
                       lmasks, rng):
            if mixed:
                # step-boundary cast seams: forward/backward in the
                # compute dtype, fp32 master params stay the update's
                inputs = cast_floats(inputs, policy.compute_dtype)
                masks = cast_floats(masks, policy.compute_dtype)

            def loss_for_grad(p):
                return self._loss_fn(p, states, inputs, labels, masks,
                                     lmasks, rng)

            (loss, new_states), grads = precision_value_and_grad(
                loss_for_grad, policy)(params)
            layer_list = [self.conf.nodes[n].layer for n in self._layer_nodes]
            new_params, new_opt = compute_updates(
                tx, grads, opt_state, params, layer_list, training)
            out_grads = grads if collect_grads else None
            if sentinel is None:
                return new_params, new_opt, new_states, loss, out_grads
            # non-finite guard: a diverged update never lands (old state
            # selected in-program — no host sync; see resilience/sentinel)
            sel, bad = guard_update(
                loss, grads, (params, opt_state, states),
                (new_params, new_opt, new_states))
            return sel[0], sel[1], sel[2], loss, out_grads, bad

        # donate params/opt/states: ResNet-scale nets must not copy their
        # whole state every step (HBM traffic + footprint)
        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def fit_batch(self, data: Union[DataSet, MultiDataSet]) -> float:
        """One optimization step (ref: ComputationGraph.fit).

        NOTE: previous ``params``/``opt_state``/``states`` buffers are
        DONATED to the jitted step — external aliases held across a step
        raise "Array has been deleted"; ``np.asarray``-copy first."""
        self._check_init()
        algo = self.conf.training.optimization_algo
        if algo not in ("sgd", "stochastic_gradient_descent"):
            # line-search family (ref: BaseOptimizer.java:295-300 — the
            # same Solver serves ComputationGraph)
            from deeplearning4j_tpu.optimize.solvers import solver_fit_batch
            return solver_fit_batch(self, data)
        if self.conf.training.backprop_type == "truncated_bptt":
            all_feats = ([data.features] if isinstance(data, DataSet)
                         else list(data.features))
            all_labels = ([data.labels] if isinstance(data, DataSet)
                          else list(data.labels))
            has_rnn_input = any(f.ndim == 3 for f in all_feats)
            # EVERY label must be time-distributed (a rank-2 [B, C] label
            # would silently train its head per slice against the full-
            # sequence target), and EVERY rank-3 feature must really be a
            # time series: a CNN input's [B, H, W, C] would be sliced
            # along its height axis. The declared InputTypes disambiguate
            # (the reference falls back to standard BPTT with a warning).
            rnn_ok = all(
                (self.conf.input_types.get(n) is None and f.ndim == 3)
                or (self.conf.input_types.get(n) is not None
                    and (self.conf.input_types[n].kind == "rnn"
                         or f.ndim != 3))
                for n, f in zip(self.conf.network_inputs, all_feats)
                if f.ndim >= 3)
            if has_rnn_input and rnn_ok \
                    and all(l.ndim == 3 for l in all_labels):
                return self._fit_tbptt(data)
            if has_rnn_input:
                # hard failure, matching the reference's config-time error
                # (VERDICT r3 weak #7 — see MultiLayerNetwork.fit_batch)
                raise ValueError(
                    "truncated_bptt requires rank-3 (time-distributed) "
                    "labels on every output and recurrent InputTypes for "
                    "every rank-3 input; use backprop_type('standard') "
                    "for sequence-to-one heads")
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        inputs, labels, masks, lmasks = self._split(data)
        self._rng, step_rng = jax.random.split(self._rng)
        from deeplearning4j_tpu.profiling import get_tracer
        # host-side span: the (async) step dispatch — what hangs when a
        # compile or transfer wedges (see MultiLayerNetwork.fit_batch)
        with get_tracer().span("fit_batch", it=self.iteration_count + 1):
            out = self._train_step_fn(
                self.params, self.opt_state, self.states, inputs, labels,
                masks, lmasks, step_rng)
            (self.params, self.opt_state, self.states, loss,
             self.last_grads) = out[:5]
        self.last_batch_size = data.num_examples()
        # raw device scalar — see MultiLayerNetwork.fit_batch: converting
        # eagerly would sync the pipeline every step
        self.score_value = loss
        self.iteration_count += 1
        self._observe_sentinel(out[5] if len(out) > 5 else None)
        for listener in self.listeners:
            listener.iteration_done(self, self.iteration_count, self.score_value)
        return self._score_raw

    def fit(self, data, epochs: int = 1, use_async: bool = True,
            scan_window: int = 1) -> "ComputationGraph":
        """(ref: ComputationGraph.fit(DataSetIterator):701-771).
        ``scan_window``: see MultiLayerNetwork.fit — batches grouped into
        one jitted multi-step scan program per window."""
        self._check_init()
        if isinstance(data, (DataSet, MultiDataSet)):
            batches = [data]
            data = ListDataSetIterator(batches) if isinstance(data, DataSet) else None
            if data is None:
                for _ in range(epochs):
                    self.fit_batch(batches[0])
                return self
        assert isinstance(data, DataSetIterator)
        it = (AsyncDataSetIterator(data)
              if use_async and data.async_supported() else data)
        for _ in range(epochs):
            for listener in self.listeners:
                if isinstance(listener, TrainingListener):
                    listener.on_epoch_start(self)
            if scan_window > 1:
                self._fit_epoch_scan(it, scan_window)
            else:
                for batch in it:
                    self.fit_batch(batch)
            self.epoch_count += 1
            for listener in self.listeners:
                if isinstance(listener, TrainingListener):
                    listener.on_epoch_end(self)
        return self

    def _tbptt_rnn_inputs(self) -> set:
        """Network inputs whose time axis tBPTT may slice: declared-rnn
        InputTypes, or untyped inputs (the fit_batch gate only admits
        untyped inputs when they are rank-3 time series)."""
        return {n for n in self.conf.network_inputs
                if self.conf.input_types.get(n) is None
                or self.conf.input_types[n].kind == "rnn"}

    # ------------------------------------------------------------------ tBPTT
    def _build_tbptt_step(self):
        tx = self._tx
        training = self.conf.training
        fwd = training.tbptt_fwd_length
        bwd = training.tbptt_bwd_length or fwd
        data_loss_of = self._data_loss
        rnn_inputs = self._tbptt_rnn_inputs()
        sentinel = self._sentinel
        if sentinel is not None:
            from deeplearning4j_tpu.resilience.sentinel import guard_update
        from deeplearning4j_tpu.nn.updater import (
            PrecisionPolicy, cast_floats, precision_value_and_grad,
        )
        policy = PrecisionPolicy.parse(
            getattr(training, "precision", None),
            loss_scale=getattr(training, "loss_scale", None))
        mixed = policy.mixed

        def step(params, opt_state, states, inputs, labels, masks, lmasks,
                 carries, rng):
            if mixed:
                inputs = cast_floats(inputs, policy.compute_dtype)
                masks = cast_floats(masks, policy.compute_dtype)
            # bwd < fwd: run the slice head forward-only (stop-gradded
            # activations + carries), backprop through the last bwd steps
            # only — same semantics as MultiLayerNetwork._build_tbptt_step
            # (ref: ComputationGraph.doTruncatedBPTT:2042 shares the MLN
            # backward time-loop truncation via LSTMHelpers.java:333)
            T = next(v.shape[1] for n, v in inputs.items()
                     if n in rnn_inputs)
            split = max(T - bwd, 0) if bwd < fwd else 0

            def loss_for_grad(p):
                if split == 0:
                    acts, om, new_states, new_carries = self._forward(
                        p, states, inputs, train=True, rng=rng, masks=masks,
                        carries=carries)
                    data_loss = data_loss_of(p, acts, om, labels, lmasks)
                else:
                    rng1, rng2 = (jax.random.split(rng) if rng is not None
                                  else (None, None))
                    head = lambda d, m=3, o=None: _time_slice(
                        d, 0, split, m, only=o)
                    tail = lambda d, m=3, o=None: _time_slice(
                        d, split, T, m, only=o)
                    acts1, om1, states1, carries1 = self._forward(
                        p, states, head(inputs, o=rnn_inputs), train=True,
                        rng=rng1, masks=head(masks, 2, rnn_inputs),
                        carries=carries)
                    acts1 = jax.tree.map(jax.lax.stop_gradient, acts1)
                    carries1 = jax.tree.map(jax.lax.stop_gradient, carries1)
                    acts2, om2, new_states, new_carries = self._forward(
                        p, states1, tail(inputs, o=rnn_inputs),
                        train=True, rng=rng2,
                        masks=tail(masks, 2, rnn_inputs),
                        carries=carries1)
                    # per-timestep losses SUM over time: head + tail ==
                    # the single-call slice loss
                    data_loss = (
                        data_loss_of(p, acts1, om1, head(labels),
                                     head(lmasks, 2))
                        + data_loss_of(p, acts2, om2, tail(labels),
                                       tail(lmasks, 2)))
                from deeplearning4j_tpu.nn.updater import l1_l2_penalty
                layer_list = [self.conf.nodes[n].layer
                              for n in self._layer_nodes]
                param_list = [p[n] for n in self._layer_nodes]
                from deeplearning4j_tpu.nn.multilayer import _sum_aux_losses
                return (data_loss + l1_l2_penalty(param_list, layer_list)
                        + _sum_aux_losses(new_states),
                        (new_states, new_carries))

            (loss, (new_states, new_carries)), grads = \
                precision_value_and_grad(loss_for_grad, policy)(params)
            layer_list = [self.conf.nodes[n].layer for n in self._layer_nodes]
            new_params, new_opt = compute_updates(
                tx, grads, opt_state, params, layer_list, training)
            # stop gradients across tBPTT boundaries
            new_carries = jax.tree.map(jax.lax.stop_gradient, new_carries)
            if sentinel is None:
                return new_params, new_opt, new_states, new_carries, loss
            # non-finite guard incl. carries: a NaN window must not
            # poison the next window's recurrent state
            sel, bad = guard_update(
                loss, grads, (params, opt_state, states, carries),
                (new_params, new_opt, new_states, new_carries))
            return sel[0], sel[1], sel[2], sel[3], loss, bad

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def _fit_tbptt(self, data: Union[DataSet, MultiDataSet]) -> float:
        """Truncated BPTT over time slices, carrying per-node RNN state
        (ref: ComputationGraph.doTruncatedBPTT:2042-2103)."""
        if self._tbptt_step_fn is None:
            self._tbptt_step_fn = self._build_tbptt_step()
        self.last_grads = None  # tBPTT step doesn't collect gradients
        fwd = self.conf.training.tbptt_fwd_length
        inputs, labels, masks, lmasks = self._split(data)
        rnn_inputs = self._tbptt_rnn_inputs()
        T = next(v.shape[1] for n, v in inputs.items() if n in rnn_inputs)
        B = next(iter(inputs.values())).shape[0]
        # materialize initial carries so the jit signature is stable —
        # in the configured training dtype, not initial_carry's f32
        # default (a bf16 net must not run its recurrence in f32)
        dt = _dtype_of(self.conf.training.dtype)
        carries = {name: self.conf.nodes[name].layer.initial_carry(B, dt)
                   for name in self._layer_nodes
                   if getattr(self.conf.nodes[name].layer,
                              "supports_carry", False)}
        total, slices = 0.0, 0
        for start in range(0, T, fwd):
            end = min(start + fwd, T)
            self._rng, step_rng = jax.random.split(self._rng)
            out = self._tbptt_step_fn(
                self.params, self.opt_state, self.states,
                _time_slice(inputs, start, end, only=rnn_inputs),
                _time_slice(labels, start, end),
                _time_slice(masks, start, end, 2, rnn_inputs),
                _time_slice(lmasks, start, end, 2),
                carries, step_rng)
            (self.params, self.opt_state, self.states, carries, loss) = \
                out[:5]
            total = total + loss  # device accumulate — no per-slice sync
            slices += 1
            self.iteration_count += 1
            self.score_value = loss
            self._observe_sentinel(out[5] if len(out) > 5 else None)
            for listener in self.listeners:
                listener.iteration_done(self, self.iteration_count,
                                        self.score_value)
        self.last_batch_size = data.num_examples()
        return total / max(slices, 1)

    # ------------------------------------------------------- rnn statefulness
    def rnn_clear_previous_state(self) -> None:
        self._rnn_carries = None

    def rnn_time_step(self, inputs):
        """Stateful streaming inference (ref: ComputationGraph.rnnTimeStep:
        1868 — keeps per-vertex state maps between calls).

        Inputs as in ``outputs()``; [B, F] inputs are treated as one
        timestep and squeezed back. Returns the single output activation,
        or a list for multi-output graphs."""
        self._check_init()
        in_map = self._to_input_map(inputs)
        squeeze = all(v.ndim == 2 for v in in_map.values())
        if squeeze:
            in_map = {k: v[:, None, :] for k, v in in_map.items()}
        if self._rnn_carries is None:
            # materialize all carries up front so the jit signature is
            # stable from the first call (empty-dict -> populated-dict
            # would force a second trace/compile)
            B = next(iter(in_map.values())).shape[0]
            dt = _dtype_of(self.conf.training.dtype)
            self._rnn_carries = {
                name: self.conf.nodes[name].layer.initial_carry(B, dt)
                for name in self._layer_nodes
                if getattr(self.conf.nodes[name].layer,
                           "supports_carry", False)}
        if getattr(self, "_rnn_step_jit", None) is None:
            # one jitted program per streaming step (see MLN.rnn_time_step)
            def step(params, states, im, carries):
                acts, _, _, new_carries = self._forward(
                    params, states, im, train=False, rng=None,
                    stop_before_loss=False, carries=carries)
                return ([acts[o] for o in self.conf.network_outputs],
                        new_carries)
            self._rnn_step_jit = jax.jit(step)  # jaxlint: disable=JL006 -- inference step: params/states are NOT consumed, they persist across streaming calls
        outs_list, new_carries = self._rnn_step_jit(
            self.params, self.states, in_map, self._rnn_carries)
        self._rnn_carries = {**self._rnn_carries, **new_carries}
        outs = outs_list
        if squeeze:
            outs = [o[:, 0] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    # ----------------------------------------------------- incremental decode
    # Token-level serving (ISSUE 15): an autoregressive decoder served
    # token-at-a-time needs a STEP program whose shapes never depend on
    # how far each request has generated — per-request KV caches of
    # static [rows, H, max_len, D] shape are threaded through the step
    # as carry state (the serving analog of the tBPTT scan carries),
    # every row masks its own prefix, and the serving engine AOT-
    # compiles one prefill program per pow2 prompt-length bucket and
    # one decode program per pow2 row bucket (keras/generation.py).

    def kv_cache_nodes(self) -> List[str]:
        """Layer nodes that thread a KV cache (causal attention)."""
        return [n for n in self._layer_nodes
                if getattr(self.conf.nodes[n].layer,
                           "supports_kv_cache", False)]

    def decode_max_len(self) -> int:
        """Static cache length: the learned position table's capacity
        (every decode position must index it)."""
        for n in self._layer_nodes:
            ml = getattr(self.conf.nodes[n].layer, "max_timesteps", 0)
            if ml:
                return int(ml)
        for t in self.conf.input_types.values():
            if t is not None and t.kind == "rnn" and t.timesteps:
                return int(t.timesteps)
        raise ValueError(
            "decode needs a static max sequence length (a "
            "PositionalEmbeddingLayer max_timesteps or a recurrent "
            "InputType with fixed timesteps)")

    def decode_vocab(self) -> int:
        t = self.conf.input_types.get(self.conf.network_inputs[0])
        if t is None or t.kind != "rnn":
            raise ValueError("decode needs a recurrent input type")
        return int(t.size)

    def _check_decodable(self) -> None:
        """Fail loudly at engine-build time — not as a shape error deep
        inside a traced step — when the graph is not an incremental
        decoder: single input/output, every time-mixing layer either a
        CAUSAL attention (KV cache) or the positional embedding, and
        everything else per-timestep-local."""
        from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
        from deeplearning4j_tpu.nn.layers.attention import (
            SelfAttentionLayer)
        from deeplearning4j_tpu.nn.layers.normalization import (
            LayerNormalization)
        from deeplearning4j_tpu.nn.layers.shape import TimeDistributedLayer
        if len(self.conf.network_inputs) != 1 \
                or len(self.conf.network_outputs) != 1:
            raise ValueError("incremental decode supports single-input/"
                             "single-output graphs")
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "vertex":
                if not isinstance(node.vertex, ElementWiseVertex):
                    raise ValueError(
                        f"vertex {name!r} ({type(node.vertex).__name__}) "
                        "is not per-timestep-local; cannot decode "
                        "incrementally")
                continue
            if node.kind != "layer":
                continue
            layer = node.layer
            if isinstance(layer, SelfAttentionLayer):
                if not layer.supports_kv_cache:
                    raise ValueError(
                        f"attention node {name!r} is not causal — "
                        "incremental decode would change its output")
                continue
            if getattr(layer, "supports_carry", False):
                raise ValueError(
                    f"recurrent node {name!r} "
                    f"({type(layer).__name__}) has no decode path")
            ok = (hasattr(layer, "decode_step")
                  or isinstance(layer, (LayerNormalization,
                                        TimeDistributedLayer))
                  or hasattr(layer, "compute_loss"))
            if not ok:
                raise ValueError(
                    f"node {name!r} ({type(layer).__name__}) is not "
                    "known to be per-timestep-local; cannot decode "
                    "incrementally")

    def init_decode_cache(self, rows: int, max_len: Optional[int] = None
                          ) -> Dict[str, Dict[str, Array]]:
        """Fresh zeroed KV caches for a ``rows``-row decode bucket —
        one {k, v} pair per causal-attention node, static shapes."""
        if max_len is None:
            max_len = self.decode_max_len()
        dt = _dtype_of(self.conf.training.dtype)
        return {n: {"k": jnp.zeros(self.conf.nodes[n].layer.cache_shape(
                        rows, max_len), dt),
                    "v": jnp.zeros(self.conf.nodes[n].layer.cache_shape(
                        rows, max_len), dt)}
                for n in self.kv_cache_nodes()}

    def decode_cache_bytes(self, rows: int,
                           max_len: Optional[int] = None) -> int:
        """HBM footprint of a ``rows``-row bucket's KV caches — what the
        serving engine budgets ring-buffer eviction against."""
        if max_len is None:
            max_len = self.decode_max_len()
        dt = np.dtype(self.conf.training.dtype)
        total = 0
        for n in self.kv_cache_nodes():
            shape = self.conf.nodes[n].layer.cache_shape(rows, max_len)
            total += 2 * int(np.prod(shape)) * dt.itemsize
        return total

    def _incremental_forward(self, params, states, x, caches, positions,
                             lengths=None):
        """One DAG walk shared by prefill (``lengths`` given, x is the
        padded [B, T, V] prompt block) and decode (x is the [B, 1, V]
        current token, ``positions`` the per-row sequence position).
        Returns (output activation, new caches)."""
        acts: Dict[str, Array] = {self.conf.network_inputs[0]: x}
        new_caches: Dict[str, Dict[str, Array]] = {}
        for name in self.conf.topological_order:
            node = self.conf.nodes[name]
            if node.kind == "input":
                continue
            in_acts = [acts[i] for i in node.inputs]
            if node.kind == "vertex":
                acts[name] = node.vertex.apply(in_acts)
                continue
            layer = node.layer
            h = in_acts[0]
            if node.preprocessor is not None:
                h = node.preprocessor.transform(h, None)
            p = self._layer_params(params, name)
            if getattr(layer, "supports_kv_cache", False):
                cache = caches[name]
                if lengths is not None:
                    h, kc, vc = layer.prefill(p, h, cache["k"],
                                              cache["v"], lengths)
                else:
                    h, kc, vc = layer.decode_step(p, h, cache["k"],
                                                  cache["v"], positions)
                new_caches[name] = {"k": kc, "v": vc}
            elif lengths is None and hasattr(layer, "decode_step"):
                h = layer.decode_step(p, h, positions)
            else:
                h, _ = layer.apply(p, h, state=states[name], train=False,
                                   rng=None, mask=None)
            acts[name] = h
        return acts[self.conf.network_outputs[0]], new_caches

    def decode_fns(self):
        """The two PURE step functions token-level serving AOT-compiles
        (params/states stay arguments — fit never invalidates a
        compiled bucket; caches are donate-able carries):

        - ``prefill(params, states, caches, x, lengths)`` -> ``(probs
          [B, V] at each row's last prompt position, caches)`` — x is
          the pow2-padded one-hot prompt block [B, T, V].
        - ``decode(params, states, caches, x, positions)`` -> ``(probs
          [B, V], caches)`` — x is the [B, 1, V] one-hot of each row's
          current token.
        """
        if self._decode_fns is None:
            self._check_decodable()

            def prefill(params, states, caches, x, lengths):
                out, new_caches = self._incremental_forward(
                    params, states, x, caches, None, lengths=lengths)
                probs = jnp.take_along_axis(
                    out, (lengths - 1)[:, None, None], axis=1)[:, 0, :]
                return probs, new_caches

            def decode(params, states, caches, x, positions):
                out, new_caches = self._incremental_forward(
                    params, states, x, caches, positions)
                return out[:, 0, :], new_caches

            self._decode_fns = (prefill, decode)
        return self._decode_fns

    # ------------------------------------------------- block-paged decode
    # ISSUE 20: the serving engine stores KV state as a fixed pool of
    # [n_pages, H, page_len, D] pages per attention node plus a per-row
    # page table. The paged step gathers each row's pages into the
    # EXACT dense [rows, H, max_len, D] shape the unmodified decode
    # path expects (page_len must divide max_len), runs it, and
    # scatters the one new K/V token per row back into its write page —
    # values and shapes are identical to the dense step, so batched
    # paged decode stays bitwise equal to singleton dense decode.

    def kv_page_len(self, page_len: Optional[int] = None) -> int:
        """Resolve (and validate) the KV page length: must divide the
        static ``decode_max_len`` so pages tile a row exactly."""
        ml = self.decode_max_len()
        if page_len is None:
            from deeplearning4j_tpu.analysis.memory import (
                default_kv_page_len)
            return default_kv_page_len(ml)
        page_len = int(page_len)
        if page_len < 1 or ml % page_len:
            raise ValueError(
                f"kv_page_len={page_len} must divide the static decode "
                f"max_len {ml} (pages must tile a cache row exactly)")
        return page_len

    def init_kv_page_pool(self, n_pages: int, page_len: int
                          ) -> Dict[str, Dict[str, Array]]:
        """Fresh zeroed page pool — one {k, v} pair of
        ``[n_pages, H, page_len, D]`` arrays per causal-attention node.
        A physical page id addresses ONE page group: the same slot
        across every node's k and v arrays."""
        dt = _dtype_of(self.conf.training.dtype)
        return {n: {"k": jnp.zeros(self.conf.nodes[n].layer.cache_shape(
                        n_pages, page_len), dt),
                    "v": jnp.zeros(self.conf.nodes[n].layer.cache_shape(
                        n_pages, page_len), dt)}
                for n in self.kv_cache_nodes()}

    def kv_page_group_bytes(self, page_len: int) -> int:
        """HBM footprint of ONE page group (k + v, ``page_len``
        positions, across every causal-attention node) — the eviction
        granularity the paged serving engine budgets against."""
        return self.decode_cache_bytes(1, page_len)

    def paged_decode_fn(self, page_len: Optional[int] = None):
        """The PURE paged decode step the serving engine AOT-compiles:

        ``paged_decode(params, states, pool, x, positions, page_table)
        -> (probs [rows, V], new_pool)`` — ``pool`` is the donate-able
        page-pool pytree, ``page_table`` ``[rows, max_len // page_len]``
        int32. Gather -> dense decode -> scatter-back keeps the
        attention math untouched; shardcheck SC010 statically proves
        both the gather indirection and that the pool pages stayed
        donated through it."""
        page_len = self.kv_page_len(page_len)
        cached = self._paged_decode_fns.get(page_len)
        if cached is not None:
            return cached
        _, decode = self.decode_fns()   # validates decodability
        from deeplearning4j_tpu.nn.layers.attention import (
            gather_kv_pages, scatter_kv_token)

        def paged_decode(params, states, pool, x, positions, page_table):
            caches = {n: {k: gather_kv_pages(v, page_table)
                          for k, v in kv.items()}
                      for n, kv in pool.items()}
            probs, new_caches = decode(params, states, caches, x,
                                       positions)
            rows = jnp.arange(x.shape[0])
            new_pool = {}
            for n, kv in pool.items():
                new_pool[n] = {}
                for k, v in kv.items():
                    tok_kv = new_caches[n][k][rows, :, positions, :]
                    new_pool[n][k] = scatter_kv_token(
                        v, tok_kv, page_table, positions)
            return probs, new_pool

        self._paged_decode_fns[page_len] = paged_decode
        return paged_decode

    # --------------------------------------------------------------- pretrain
    def _ancestors(self, target: str) -> set:
        """Ancestor closure of ``target`` (exclusive), for partial walks.
        Includes runtime reference nodes (DuplicateToTimeSeriesVertex's
        named T source) so subset walks can resolve them."""
        seen: set = set()
        stack = list(self.conf.nodes[target].inputs)
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            node = self.conf.nodes[n]
            stack.extend(node.inputs)
            if (node.kind == "vertex"
                    and isinstance(node.vertex, DuplicateToTimeSeriesVertex)
                    and isinstance(node.vertex.timesteps, str)):
                stack.append(node.vertex.timesteps)
        return seen

    def _activations_to(self, target: str, in_map: Dict[str, Array],
                        masks: Optional[Dict[str, Array]] = None) -> Array:
        """Inference activations feeding node ``target`` (after its
        preprocessor) — the graph analog of feedForwardToLayer. Walks only
        the target's ancestor subgraph, mask-aware, as ONE jitted program
        per target (eager per-op dispatch would be pathological on a
        remote-TPU link; see init())."""
        node = self.conf.nodes[target]
        if node.kind != "layer":
            raise ValueError(f"Node {target!r} is not a layer node")
        if len(node.inputs) != 1:
            raise ValueError(
                f"Node {target!r} has {len(node.inputs)} inputs; layerwise "
                "pretraining needs a single-input node (pretraining on "
                "inputs[0] alone would silently use the wrong objective)")
        cache = getattr(self, "_act_to_fns", None)
        if cache is None:
            cache = self._act_to_fns = {}
        if target not in cache:
            subset = self._ancestors(target)

            def fn(params, states, inputs, msks, _subset=subset):
                acts, _, _ = self._forward(params, states, inputs,
                                           train=False, rng=None, masks=msks,
                                           stop_before_loss=True,
                                           subset=_subset)
                h = acts[node.inputs[0]]
                if node.preprocessor is not None:
                    h = node.preprocessor.transform(h, None)
                return h
            cache[target] = jax.jit(fn)
        return cache[target](self.params, self.states, in_map, masks)

    def pretrain(self, iterator, epochs: int = 1) -> None:
        """Greedy layerwise pretraining over the topological order
        (ref: ComputationGraph.pretrain:527-545)."""
        self._check_init()
        for name in self._layer_nodes:
            self.pretrain_layer(name, iterator, epochs=epochs)

    def pretrain_layer(self, name: str, iterator, epochs: int = 1) -> None:
        """Pretrain one layer node on the activations of the subgraph
        below it (ref: ComputationGraph.pretrainLayer:547-579). Layers
        that are not pretrainable (no AE/RBM/VAE objective) are skipped,
        as the reference does."""
        self._check_init()
        from deeplearning4j_tpu.nn.layers.core import RBM, AutoEncoder
        from deeplearning4j_tpu.nn.layers.variational import (
            VariationalAutoencoder)

        layer = self.conf.nodes[name].layer
        if not isinstance(layer, (RBM, AutoEncoder, VariationalAutoencoder)):
            return
        from deeplearning4j_tpu.nn.netcommon import make_pretrain_step
        tx = build_optimizer(self.conf.training)
        layer_opt = tx.init(self.params[name])
        step = make_pretrain_step(layer, tx)

        for _ in range(epochs):
            iterator.reset()
            for batch in iterator:
                inputs, _, masks, _ = self._split(batch)
                x = self._activations_to(name, inputs, masks)
                self._rng, k = jax.random.split(self._rng)
                # reassign every step: the jitted step donates its param
                # buffer, so a stale self.params[name] would alias a
                # deleted Array on donation-capable backends
                p, layer_opt, loss = step(self.params[name], layer_opt,
                                          x, k)
                self.params[name] = p
                self.score_value = loss

    # ----------------------------------------------------------- param access
    def num_params(self) -> int:
        self._check_init()
        return sum(int(np.prod(a.shape))
                   for p in self.params.values() for a in p.values())

    def params_flat(self) -> np.ndarray:
        """Flat param vector in topological-order/param-order
        (coefficients.bin contract for graphs)."""
        self._check_init()
        chunks = []
        for name in self._layer_nodes:
            layer = self.conf.nodes[name].layer
            for pname in layer.param_order():
                chunks.append(np.asarray(self.params[name][pname]).ravel())
        return np.concatenate(chunks) if chunks else np.zeros(0, np.float32)

    def set_params_flat(self, flat: np.ndarray) -> None:
        self._check_init()
        pos = 0
        for name in self._layer_nodes:
            layer = self.conf.nodes[name].layer
            for pname in layer.param_order():
                ref = self.params[name][pname]
                n = int(np.prod(ref.shape))
                self.params[name][pname] = jnp.asarray(
                    flat[pos:pos + n].reshape(ref.shape), ref.dtype)
                pos += n
        if pos != len(flat):
            raise ValueError(f"Expected {pos} params, got {len(flat)}")

    def predict(self, inputs) -> np.ndarray:
        return np.asarray(jnp.argmax(self.output(inputs), axis=-1))

    # evaluate / evaluate_roc / evaluate_roc_multi_class /
    # evaluate_regression come from EvalMixin (netcommon.py)
