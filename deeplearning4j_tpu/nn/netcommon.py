"""Shared container plumbing for MultiLayerNetwork and ComputationGraph.

Both containers (the reference's two model types, ref:
nn/multilayer/MultiLayerNetwork.java and nn/graph/ComputationGraph.java)
need the same device-friendly mechanics; keeping them here prevents the
two copies from drifting:

- ``LazyScoreMixin``: ``fit_batch`` stores the RAW device scalar loss so
  back-to-back training steps dispatch asynchronously — converting to
  float eagerly would force a device round-trip per step, which on a
  remote-TPU link serializes the whole pipeline. The first read of
  ``score_value`` synchronizes and caches the float.
- ``jit_init``: run a param-building closure as ONE jitted program. Eager
  per-tensor init compiles + dispatches hundreds of tiny device programs
  (one per shape) — minutes over a remote-TPU link; jitted it is a single
  compile and a single execution.
"""

from __future__ import annotations

import jax


class LazyScoreMixin:
    """Lazy float conversion of the last minibatch loss.

    Containers assign ``self.score_value = <device scalar or float>`` and
    read ``self.score_value`` as a float; ``self._score_raw`` holds
    whatever was last assigned (listener-free training never syncs).
    """

    _score_raw = float("nan")

    @property
    def score_value(self) -> float:
        v = self._score_raw
        if not isinstance(v, float):
            v = float(v)  # device sync happens here, on first read
            self._score_raw = v
        return v

    @score_value.setter
    def score_value(self, v) -> None:
        self._score_raw = v


def jit_init(build, seed: int):
    """Run ``build(key) -> (params, opt_state)`` as one jitted program."""
    return jax.jit(build)(jax.random.PRNGKey(seed))


class SentinelMixin:
    """Divergence-sentinel attachment shared by both containers (and
    read by all three parallel trainers at step-build time).

    With a sentinel attached, every compiled train step grows an
    in-step non-finite guard (``resilience/sentinel.py:guard_update``):
    a NaN/inf loss or grad-norm means the update never lands, and the
    step returns one extra device-scalar flag that ``fit_batch`` hands
    to the sentinel's lag-based drain. Attaching/detaching drops the
    container's cached jitted steps here (guarded and unguarded steps
    are different programs); the parallel trainers detect the change
    themselves at their next ``fit_batch`` and rebuild their own cached
    steps.
    """

    _sentinel = None

    def set_divergence_sentinel(self, sentinel):
        self._sentinel = sentinel
        self._train_step_fn = None
        # derived caches key on _train_step_fn identity or are rebuilt
        # lazily; the tBPTT step is cached separately
        if getattr(self, "_tbptt_step_fn", None) is not None:
            self._tbptt_step_fn = None
        return self

    def _observe_sentinel(self, flag) -> None:
        """Hand the just-completed step's flag to the sentinel (may
        raise per policy — see resilience/sentinel.py)."""
        if self._sentinel is not None and flag is not None:
            self._sentinel.observe(flag, self.iteration_count)


class EvalMixin:
    """Shared evaluation drivers (ref: MultiLayerNetwork.evaluate /
    evaluateROC:2436 / evaluateROCMultiClass:2449 / evaluateRegression —
    ComputationGraph mirrors the same four). Containers provide
    ``output(features)``; every evaluator shares one drive loop so the
    batch semantics cannot drift between the four."""

    def _drive_eval(self, evaluator, iterator):
        import numpy as np
        iterator.reset()
        for batch in iterator:
            # the feature mask must reach the forward pass: padded steps
            # would otherwise flow through the recurrence as real data
            out = self.output(batch.features, mask=batch.features_mask)
            evaluator.eval(batch.labels, np.asarray(out),
                           mask=batch.labels_mask)
        return evaluator

    def evaluate(self, iterator):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        return self._drive_eval(Evaluation(), iterator)

    def evaluate_roc(self, iterator, threshold_steps: int = 100):
        from deeplearning4j_tpu.eval.roc import ROC
        return self._drive_eval(ROC(threshold_steps), iterator)

    def evaluate_roc_multi_class(self, iterator,
                                 threshold_steps: int = 100):
        from deeplearning4j_tpu.eval.roc import ROCMultiClass
        return self._drive_eval(ROCMultiClass(threshold_steps), iterator)

    def evaluate_regression(self, iterator):
        from deeplearning4j_tpu.eval.regression import RegressionEvaluation
        return self._drive_eval(RegressionEvaluation(), iterator)


class CostAnalysisMixin:
    """``cost_analysis(batch)`` for both containers: XLA's compile-time
    cost model over the REAL jitted train step — FLOPs and bytes
    accessed per optimization step, plus the chip's peak for an analytic
    MFU. Pure compile-time work (runs on CPU, no accelerator needed);
    pays one AOT compile per call, so call it once per batch shape, not
    per step."""

    def cost_analysis(self, batch, peak=None) -> dict:
        from deeplearning4j_tpu.profiling.cost import train_step_cost
        return train_step_cost(self, batch, peak=peak)


class ShardCheckMixin:
    """``shardcheck(batch)`` for both containers: static analysis of
    the container's own COMPILED train step (analysis/shardcheck) —
    donation landed (SC005), no host transfers in the hot path (SC006),
    precision boundaries honored (SC004), collective census (SC002).
    The zero1/zero2 layout rules live on the data-parallel trainers'
    ``shardcheck`` (the container's own step is the single-device
    program). Same compile cost as ``cost_analysis``: one AOT lower per
    (model, batch shape), no execution."""

    def shardcheck(self, batch, **overrides):
        from deeplearning4j_tpu.analysis.shardcheck import (
            check_step_program, net_step_program, param_leaf_sizes,
        )
        training = self.conf.training
        ctx = dict(weight_update_sharding="off", dp=1,
                   precision=getattr(training, "precision", None),
                   expect_donation=True,
                   param_leaf_sizes=param_leaf_sizes(self.params))
        ctx.update(overrides)
        return check_step_program(net_step_program(self, batch), **ctx)


def make_pretrain_step(layer, tx):
    """Jitted single-layer pretraining step for the greedy layerwise walk
    both containers run (ref: MultiLayerNetwork.pretrain /
    ComputationGraph.pretrainLayer:547-579): RBM layers step on CD
    gradients, AE/VAE layers on grad of their reconstruction/ELBO loss.

    Returns ``step(params, opt_state, x, rng) -> (params, opt_state,
    loss)``.
    """
    if hasattr(layer, "cd_gradients"):  # RBM: contrastive divergence
        def step(p, opt, x, rng):
            grads, err = layer.cd_gradients(p, x, rng=rng)
            updates, opt = tx.update(grads, opt, p)
            return jax.tree.map(lambda a, u: a + u, p, updates), opt, err
    else:
        def step(p, opt, x, rng):
            loss, grads = jax.value_and_grad(
                lambda pp: layer.pretrain_loss(pp, x, rng=rng))(p)
            updates, opt = tx.update(grads, opt, p)
            return jax.tree.map(lambda a, u: a + u, p, updates), opt, loss
    # both pretrain drivers overwrite (params, opt) with the step's
    # returns, so the old buffers are donatable
    return jax.jit(step, donate_argnums=(0, 1))


def emit_scan_burst(net, losses, n, t0, stats=None):
    """Post-window listener burst shared by the containers and
    ParallelTrainer: one iteration event per scanned step with that
    step's loss. ``net.last_scan_window`` carries {n, wall_s} for the
    duration of the burst so time-based listeners (PerformanceListener)
    amortize the window wall time per step instead of misreading the
    burst cadence; try/finally guarantees a raising listener can't leave
    the stale window dict behind."""
    import time as _time
    jax.block_until_ready(losses)
    net.last_scan_window = {"n": n, "wall_s": _time.perf_counter() - t0}
    t_l = _time.perf_counter()
    try:
        for i in range(n):
            net.iteration_count += 1
            # listeners reading model.score_value must see THIS
            # iteration's loss, not the window's final one
            net.score_value = float(losses[i])
            for listener in net.listeners:
                listener.iteration_done(net, net.iteration_count,
                                        net.score_value)
    finally:
        net.last_scan_window = None
    if stats:
        stats.record("listener", _time.perf_counter() - t_l)


def make_scan_fit(step_fn, donate_argnums=(0, 1, 2)):
    """Multi-step training as ONE jitted program: ``lax.scan`` of the
    container's train step over a leading batch axis.

    Per-step host dispatch costs a host->device round trip per iteration;
    over a remote-tunneled TPU that latency can exceed the step's compute
    (the r03 LeNet rung bottomed out near a fixed ms/step floor). Scanning
    N steps inside one program pays ONE dispatch for the whole window —
    the idiomatic XLA shape for a training loop (static trip count,
    donated carry).

    ``step_fn`` is the (non-jitted semantics of the) per-batch step with
    signature (params, opt, states, feats, labels, fmask, lmask, rng) ->
    (params, opt, states, loss[, grads]) — both arities are accepted
    (the containers' steps emit grads, ParallelTrainer's doesn't; the
    body reads only the first four outputs).
    Masks are fixed to None in the scanned program. feats/labels may be
    arrays (MultiLayerNetwork) or name-keyed dicts (ComputationGraph) —
    lax.scan slices pytrees.
    """

    def scan_program(params, opt_state, states, feats, labels, rng):
        def body(carry, xs):
            p, o, s, r = carry
            f, l = xs
            r, sub = jax.random.split(r)
            out = step_fn(p, o, s, f, l, None, None, sub)
            p, o, s, loss = out[:4]
            return (p, o, s, r), loss

        (p, o, s, _), losses = jax.lax.scan(
            body, (params, opt_state, states, rng), (feats, labels))
        return p, o, s, losses

    return jax.jit(scan_program, donate_argnums=donate_argnums)


class ScanFitMixin:
    """``fit_batches_scan(datasets)`` for both containers."""

    def _fit_epoch_scan(self, it, scan_window: int) -> None:
        """One epoch's batches grouped into scan windows; the short tail
        (and any unscannable window, via fit_batches_scan's fallback)
        still trains per batch."""
        window: list = []
        for batch in it:
            window.append(batch)
            if len(window) == scan_window:
                self.fit_batches_scan(window)
                window = []
        for batch in window:
            self.fit_batch(batch)

    def fit_batches_scan(self, datasets):
        """Run one optimization step per DataSet, all inside ONE jitted
        scan program (see make_scan_fit). Requirements: SGD-family
        optimizer, standard backprop, uniform batch shapes, no masks, no
        gradient-collecting listeners — anything else falls back to the
        per-batch ``fit_batch`` loop. Returns the per-step losses as a
        device array (no sync unless converted)."""
        import jax.numpy as jnp
        import numpy as _np

        self._check_init()
        datasets = list(datasets)
        if not datasets:
            return _np.zeros((0,), _np.float32)
        def has_mask(d):
            # DataSet: singular attrs; MultiDataSet: plural lists
            for attr in ("features_mask", "labels_mask",
                         "features_masks", "labels_masks"):
                m = getattr(d, attr, None)
                if isinstance(m, (list, tuple)):
                    if any(x is not None for x in m):
                        return True
                elif m is not None:
                    return True
            return False

        def shape_sig(d):
            f, l = d.features, d.labels
            if isinstance(f, (list, tuple)):  # MultiDataSet
                return (tuple(_np.shape(x) for x in f),
                        tuple(_np.shape(y) for y in l))
            return (_np.shape(f), _np.shape(l))

        algo = self.conf.training.optimization_algo
        scannable = (
            algo in ("sgd", "stochastic_gradient_descent")
            and self.conf.training.backprop_type != "truncated_bptt"
            and not getattr(self, "_collect_grads", False)
            # a divergence sentinel needs per-step host observation
            # (raise/rollback policies); the scan body would silently
            # drop the flags — train per batch instead
            and getattr(self, "_sentinel", None) is None
            and not any(has_mask(d) for d in datasets)
            # a ragged batch (short dataset tail) cannot stack — loop it
            and len({shape_sig(d) for d in datasets}) == 1)
        if not scannable:
            return _np.asarray([float(self.fit_batch(d))
                                for d in datasets], _np.float32)
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        cached = getattr(self, "_scan_fit", None)
        if cached is None or cached[0] is not self._train_step_fn:
            self._scan_fit = (self._train_step_fn,
                              make_scan_fit(self._train_step_fn))
        scan_fn = self._scan_fit[1]

        if hasattr(self, "_split"):  # ComputationGraph: name-keyed dicts
            splits = [self._split(d) for d in datasets]
            feats = jax.tree.map(lambda *xs: jnp.stack(
                [jnp.asarray(x) for x in xs]), *[s[0] for s in splits])
            labels = jax.tree.map(lambda *xs: jnp.stack(
                [jnp.asarray(x) for x in xs]), *[s[1] for s in splits])
        else:
            feats = jnp.stack([jnp.asarray(d.features) for d in datasets])
            labels = jnp.stack([jnp.asarray(d.labels) for d in datasets])

        import time as _time
        t0 = _time.perf_counter()
        self._rng, r = jax.random.split(self._rng)
        self.params, self.opt_state, self.states, losses = scan_fn(
            self.params, self.opt_state, self.states, feats, labels, r)
        self.last_batch_size = datasets[-1].num_examples()
        self.last_grads = None
        self.last_input = getattr(datasets[-1], "features", None)
        if self.listeners:
            emit_scan_burst(self, losses, len(datasets), t0)
        else:
            self.iteration_count += len(datasets)
        self.score_value = losses[-1]
        return losses
